#!/usr/bin/env python
"""faultsmoke — CI fault-injection smoke: crash/resume + fleet faults.

Phase 1 trains a zoo model a few steps, checkpoints it through the
crash-safe store, arms a torn checkpoint write and crashes mid-save,
then proves recovery end to end: the torn temp is ignored, the newest
VERIFIED serial restores bit-exact parameters, and training continues
with finite loss. Exercises resilience/{checkpoint,faultinject}.py
plus the io.save_checkpoint/load_checkpoint integration — the same
path tests/test_resilience.py covers, but as a standalone process the
way tools/selfcheck.sh runs it (no pytest, fresh interpreter,
env-style usage documented in docs/RELIABILITY.md).

Phase 2 stands up an in-process 2-worker training fleet
(cluster/train_fabric.py over real loopback sockets) and arms each of
the four trainer fault points — ``trainer_crash_at_step``,
``trainer_straggle``, ``train_net_partition``,
``coordinator_crash`` — verifying for each that the armed count is
respected exactly, the failure surfaces TYPED (eviction event /
SimulatedCrash), the run still commits the same serials+shas as an
undisturbed baseline (zero lost committed steps), and the harness is
clean afterwards (nothing left armed).

Usage: python tools/faultsmoke.py [--model fit_a_line] [--dir DIR]
                                  [--skip-fleet]
Exit 0 on success; any failure raises. Pure CPU, runs in seconds.
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.models import zoo  # noqa: E402
from paddle_tpu.resilience import checkpoint as ckpt  # noqa: E402
from paddle_tpu.resilience import SimulatedCrash, faultinject  # noqa: E402


def synth_feed(program, feed_names, batch=4, rng=None):
    """Random feed arrays shaped from the program's data vars (-1 dims
    become ``batch``; int vars get small non-negative ids)."""
    rng = rng or np.random.RandomState(0)
    gb = program.global_block()
    feed = {}
    for name in feed_names:
        var = gb.var(name)
        shape = [batch if (d is None or d < 0) else d for d in var.shape]
        dtype = str(var.dtype)
        if "int" in dtype:
            feed[name] = rng.randint(0, 2, size=shape).astype(dtype)
        else:
            feed[name] = rng.randn(*shape).astype(dtype)
    return feed


def fleet_phase():
    """Arm and verify the four trainer fault points against a live
    2-worker loopback fleet. Each sub-drill asserts three things: the
    armed count was respected (spec.fired == configured times), the
    failure surfaced typed (eviction event kinds / SimulatedCrash),
    and the committed (serial, sha) sequence matches an undisturbed
    baseline — the zero-lost-committed-steps contract."""
    import tempfile as _tmp

    from paddle_tpu.cluster.train_fabric import (LinRegTask,
                                                 TrainCoordinator)
    from paddle_tpu.cluster.train_worker import TrainWorkerServer

    # racecheck: ok(global-mutation) — single-process smoke entrypoint
    os.environ.setdefault("PADDLE_TPU_FAULT_STRAGGLE_S", "1.0")
    task = lambda: LinRegTask(seed=7)  # noqa: E731 — fresh per run

    def fleet(n=2, **kw):
        workers = [TrainWorkerServer() for _ in range(n)]
        kw.setdefault("step_deadline_s", 5.0)
        co = TrainCoordinator(
            task(), [w.addr for w in workers], _tmp.mkdtemp(),
            commit_interval=5, n_shards=4,
            admit_deadline_s=2.0, readmit_interval_s=0.05, **kw)
        return co, workers

    def teardown(co, workers):
        co.close()
        for w in workers:
            w.close()

    co, ws = fleet(n=1)
    co.run(10)
    base = co.commits()
    teardown(co, ws)
    assert len(base) == 2, base

    # 1) trainer_crash_at_step — worker dies mid-step: evict + retry
    co, ws = fleet()
    co.run(2)
    spec = faultinject.arm("trainer_crash_at_step", at=0)
    co.run(8)
    assert spec.fired == 1, f"armed count not respected: {spec}"
    assert co.commits() == base, "crash drill lost a committed step"
    kinds = [e["kind"] for e in co.events()]
    assert "evicted" in kinds, f"no typed eviction event: {kinds}"
    faultinject.disarm()
    teardown(co, ws)

    # 2) trainer_straggle — stall past the straggler deadline: evict
    co, ws = fleet(step_deadline_s=0.3)
    co.run(2)
    spec = faultinject.arm("trainer_straggle", at=0)
    co.run(8)
    assert spec.fired == 1, f"armed count not respected: {spec}"
    assert co.commits() == base, "straggle drill lost a committed step"
    assert co.evictions_total >= 1, "straggler was not evicted"
    faultinject.disarm()
    teardown(co, ws)

    # 3) train_net_partition — RPC route vanishes typed, heals, rejoin
    co, ws = fleet()
    co.run(2)
    spec = faultinject.arm("train_net_partition", at=0, times=2)
    co.run(8)
    assert spec.fired == 2, f"armed count not respected: {spec}"
    assert co.commits() == base, "partition drill lost a committed step"
    assert co.evictions_total >= 1 and co.rejoins_total >= 1, (
        f"expected evict+rejoin across the partition, got "
        f"evictions={co.evictions_total} rejoins={co.rejoins_total}")
    reasons = [e["reason"] for e in co.events()
               if e["kind"] == "evicted"]
    assert any("RemoteUnavailableError" in r for r in reasons), (
        f"partition must surface typed RemoteUnavailableError, "
        f"got {reasons}")
    teardown(co, ws)

    # 4) coordinator_crash — SimulatedCrash (never swallowed), workers
    # park, a NEW coordinator resumes from the last committed serial
    co, ws = fleet()
    co.run(5)
    spec = faultinject.arm("coordinator_crash", at=0)
    try:
        co.run(5)
    except SimulatedCrash:
        pass
    else:
        raise AssertionError("coordinator_crash did not fire")
    assert spec.fired == 1, f"armed count not respected: {spec}"
    faultinject.disarm()
    ckpt_dir = co.checkpoint_dir
    co.close()
    assert all(w.coordinator_age_s() >= 0 for w in ws)
    co2 = TrainCoordinator(
        task(), [w.addr for w in ws], ckpt_dir,
        commit_interval=5, n_shards=4)
    assert co2.step == 5, f"resume picked step {co2.step}, not 5"
    co2.run(10 - co2.step)
    assert co2.commits()[-1] == base[-1], \
        "post-coordinator-crash resume diverged from baseline sha"
    teardown(co2, ws)

    # clean state after: nothing armed, nothing half-fired
    for kind in ("trainer_crash_at_step", "trainer_straggle",
                 "train_net_partition", "coordinator_crash"):
        assert faultinject.armed(kind) is None, f"{kind} left armed"
    print("faultsmoke ok: trainer fleet drills verified "
          "(crash/straggle/partition/coordinator; zero lost "
          "committed steps)")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="fit_a_line")
    ap.add_argument("--dir", default=None)
    ap.add_argument("--skip-fleet", action="store_true",
                    help="skip the trainer-fleet fault phase")
    args = ap.parse_args(argv)

    # racecheck: ok(global-mutation) — single-process smoke entrypoint:
    # force_cpu before any thread exists, owns the whole process
    fluid.force_cpu()
    d = args.dir or tempfile.mkdtemp(prefix="faultsmoke_")
    zp = zoo.build_zoo_program(args.model)
    loss = zp.fetch_list[0]
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(zp.startup)
    feed = synth_feed(zp.main, zp.feed_names)

    for _ in range(3):
        # racecheck: ok(run-without-scope) — the global scope IS the
        # checkpoint surface under test; single-threaded smoke
        out = exe.run(zp.main, feed=feed, fetch_list=[loss])
    assert np.isfinite(np.asarray(out[0])).all(), "training diverged"
    fluid.io.save_checkpoint(exe, d, main_program=zp.main, step=1)

    pname = zp.main.all_parameters()[0].name
    saved = np.asarray(fluid.global_scope().find_var(pname)).copy()

    # crash mid-save: the serial-2 write is torn, serial 1 must survive
    faultinject.arm("torn_write")
    try:
        fluid.io.save_checkpoint(exe, d, main_program=zp.main, step=2)
    except SimulatedCrash:
        pass
    else:
        raise AssertionError("torn_write fault did not fire")
    faultinject.disarm()

    assert ckpt.list_serials(d) == [1], \
        f"expected only serial 1 after the crash, got {ckpt.list_serials(d)}"
    assert any(e.startswith(".tmp_ckpt_") for e in os.listdir(d)), \
        "the crash should have left a torn temp dir behind"

    # "new process": trash the live state, then recover from disk
    fluid.global_scope().set(pname, np.zeros_like(saved))
    path = fluid.io.load_checkpoint(exe, d, main_program=zp.main)
    assert path.endswith("ckpt_1"), path
    got = np.asarray(fluid.global_scope().find_var(pname))
    np.testing.assert_array_equal(got, saved)

    # racecheck: ok(run-without-scope) — ditto: recovery must read the
    # same global scope load_checkpoint repopulated
    out = exe.run(zp.main, feed=feed, fetch_list=[loss])
    assert np.isfinite(np.asarray(out[0])).all(), "resume diverged"
    print(f"faultsmoke ok: {args.model} crash/resume cycle verified "
          f"(checkpoints under {d})")
    if not args.skip_fleet:
        fleet_phase()
    return 0


if __name__ == "__main__":
    sys.exit(main())
