#!/usr/bin/env python
"""faultsmoke — CI fault-injection smoke: one crash/resume cycle.

Trains a zoo model a few steps, checkpoints it through the crash-safe
store, arms a torn checkpoint write and crashes mid-save, then proves
recovery end to end: the torn temp is ignored, the newest VERIFIED
serial restores bit-exact parameters, and training continues with
finite loss. Exercises resilience/{checkpoint,faultinject}.py plus the
io.save_checkpoint/load_checkpoint integration — the same path
tests/test_resilience.py covers, but as a standalone process the way
tools/selfcheck.sh runs it (no pytest, fresh interpreter, env-style
usage documented in docs/RELIABILITY.md).

Usage: python tools/faultsmoke.py [--model fit_a_line] [--dir DIR]
Exit 0 on success; any failure raises. Pure CPU, runs in seconds.
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.models import zoo  # noqa: E402
from paddle_tpu.resilience import checkpoint as ckpt  # noqa: E402
from paddle_tpu.resilience import SimulatedCrash, faultinject  # noqa: E402


def synth_feed(program, feed_names, batch=4, rng=None):
    """Random feed arrays shaped from the program's data vars (-1 dims
    become ``batch``; int vars get small non-negative ids)."""
    rng = rng or np.random.RandomState(0)
    gb = program.global_block()
    feed = {}
    for name in feed_names:
        var = gb.var(name)
        shape = [batch if (d is None or d < 0) else d for d in var.shape]
        dtype = str(var.dtype)
        if "int" in dtype:
            feed[name] = rng.randint(0, 2, size=shape).astype(dtype)
        else:
            feed[name] = rng.randn(*shape).astype(dtype)
    return feed


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="fit_a_line")
    ap.add_argument("--dir", default=None)
    args = ap.parse_args(argv)

    # racecheck: ok(global-mutation) — single-process smoke entrypoint:
    # force_cpu before any thread exists, owns the whole process
    fluid.force_cpu()
    d = args.dir or tempfile.mkdtemp(prefix="faultsmoke_")
    zp = zoo.build_zoo_program(args.model)
    loss = zp.fetch_list[0]
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(zp.startup)
    feed = synth_feed(zp.main, zp.feed_names)

    for _ in range(3):
        # racecheck: ok(run-without-scope) — the global scope IS the
        # checkpoint surface under test; single-threaded smoke
        out = exe.run(zp.main, feed=feed, fetch_list=[loss])
    assert np.isfinite(np.asarray(out[0])).all(), "training diverged"
    fluid.io.save_checkpoint(exe, d, main_program=zp.main, step=1)

    pname = zp.main.all_parameters()[0].name
    saved = np.asarray(fluid.global_scope().find_var(pname)).copy()

    # crash mid-save: the serial-2 write is torn, serial 1 must survive
    faultinject.arm("torn_write")
    try:
        fluid.io.save_checkpoint(exe, d, main_program=zp.main, step=2)
    except SimulatedCrash:
        pass
    else:
        raise AssertionError("torn_write fault did not fire")
    faultinject.disarm()

    assert ckpt.list_serials(d) == [1], \
        f"expected only serial 1 after the crash, got {ckpt.list_serials(d)}"
    assert any(e.startswith(".tmp_ckpt_") for e in os.listdir(d)), \
        "the crash should have left a torn temp dir behind"

    # "new process": trash the live state, then recover from disk
    fluid.global_scope().set(pname, np.zeros_like(saved))
    path = fluid.io.load_checkpoint(exe, d, main_program=zp.main)
    assert path.endswith("ckpt_1"), path
    got = np.asarray(fluid.global_scope().find_var(pname))
    np.testing.assert_array_equal(got, saved)

    # racecheck: ok(run-without-scope) — ditto: recovery must read the
    # same global scope load_checkpoint repopulated
    out = exe.run(zp.main, feed=feed, fetch_list=[loss])
    assert np.isfinite(np.asarray(out[0])).all(), "resume diverged"
    print(f"faultsmoke ok: {args.model} crash/resume cycle verified "
          f"(checkpoints under {d})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
