#!/usr/bin/env python
"""protolint — CLI for the static distributed-contract analyzer
(protocheck).

Lints the fabric's shared vocabularies across ``cluster/``,
``serving/``, ``resilience/`` and ``tools/``, per docs/RELIABILITY.md
"Static protocol checking": wire-verb parity across the three
transports, typed-error completeness against ``net.WIRE_ERRORS``,
fault-point discipline against ``faultinject.KNOWN_POINTS``, counter
vocabulary hygiene, and the ``PADDLE_TPU_*`` knob registry.

    python tools/protolint.py                 # lint the repo tree
    python tools/protolint.py --json          # machine-readable, CI
    python tools/protolint.py path.py dir/    # lint explicit paths ONLY
    python tools/protolint.py --list-rules
    python tools/protolint.py --knobs-table   # the docs/RELIABILITY.md
                                              # knob reference table

Exit status is 1 iff any UNSUPPRESSED error-level finding exists —
the selfcheck stage 15 gate. Suppressions
(`# protocheck: ok(<rule>) — reason`) are reported but do not fail
the lint. Pure AST analysis: nothing is imported or compiled, so it
honors JAX_PLATFORMS=cpu trivially.
"""
import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from paddle_tpu.analysis import protocheck  # noqa: E402
from paddle_tpu.analysis.diagnostics import CODES, ERROR  # noqa: E402


def _expand(paths):
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                out.extend(os.path.join(dirpath, n)
                           for n in sorted(filenames)
                           if n.endswith(".py"))
        else:
            out.append(p)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="protolint",
        description="static contract analyzer for the distributed "
                    "fabric (see docs/RELIABILITY.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: cluster/, "
                         "serving/, resilience/, tools/)")
    ap.add_argument("--paths", dest="extra_paths", nargs="+",
                    default=None, metavar="PATH",
                    help="WIDEN the analyzed tree: lint the default "
                         "targets PLUS these files/dirs — unlike "
                         "positional paths, which replace the "
                         "defaults")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON document instead of text")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings (text mode)")
    ap.add_argument("--knobs-table", action="store_true",
                    help="print the marker-delimited PADDLE_TPU_* "
                         "knob reference table (the block committed "
                         "into docs/RELIABILITY.md) and exit 0")
    args = ap.parse_args(argv)

    if args.list_rules:
        for code in protocheck.RULES:
            level, meaning = CODES[code]
            family = protocheck.FAMILY[code]
            print(f"{code:24s} [{level:7s}] ({family}) {meaning}")
        return 0

    if args.paths:
        files = _expand(args.paths)
        if args.extra_paths:
            files += _expand(args.extra_paths)
        report = protocheck.analyze_files(files)
    elif args.extra_paths:
        files = protocheck.default_target_files()
        extra = [p for p in _expand(args.extra_paths)
                 if p not in set(files)]
        report = protocheck.analyze_files(files + extra)
    else:
        report = protocheck.run_tree()

    if args.knobs_table:
        sys.stdout.write(protocheck.render_knobs_table(report.knobs))
        return 0

    errs = report.errors()
    if args.json:
        doc = report.to_dict()
        doc["ok"] = not errs
        json.dump(doc, sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        for d in report.findings:
            print(d.format())
        if args.show_suppressed:
            for d, reason in report.suppressed:
                print(f"suppressed[{d.code}] {d.path}:{d.line} — "
                      f"{reason}")
        warn = len(report.findings) - len(errs)
        print(f"protolint: {len(report.files)} file(s), "
              f"{len(report.knobs)} knob(s), "
              f"{len(errs)} error(s), {warn} warning(s), "
              f"{len(report.suppressed)} suppressed")
    return 1 if errs else 0


if __name__ == "__main__":
    sys.exit(main())
