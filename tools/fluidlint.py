#!/usr/bin/env python
"""fluidlint — static program verifier CLI.

Runs the analysis/ pass pipeline (shape/dtype inference, structural
verification, TPU performance lints) over a program WITHOUT tracing,
jitting, or touching any accelerator, and prints the diagnostics.

Targets (one of):
  --model NAME       build a model-zoo program (paddle_tpu/models/zoo.py)
  --all-models       lint EVERY zoo model in this one process and emit
                     a single summary (one JSON document with --json) —
                     the CI sweep, replacing N separate invocations
  --program FILE     a Program saved as JSON (Program.to_json), with
                     optional --startup FILE and --fetch NAME ...
  --saved-model DIR  a save_inference_model directory (__model__.json +
                     __meta__.json supply the program and fetch names)
  --list             print the zoo model names and exit

Output: human-readable diagnostics, or one JSON document with --json
(for CI — tools/selfcheck.sh). Exit code 1 iff any error-level
diagnostic was found, else 0; warnings never fail the lint.

--report additionally prints the static cost/memory analysis
(analysis/cost.py): the top-k costliest ops by FLOPs, total
FLOPs/bytes, the liveness-based peak-residency estimate, the fwd→bwd
residual estimate with the recommended remat policy, the DCE-provable
dead-op count, the rewrite-pipeline stats (Program.optimize on a
throwaway clone: ops folded, chains fused, merged/removed, with
per-pass cost-model FLOPs/bytes deltas), and the numerics analysis
(analysis/numcheck.py: CODES findings + finiteness verdict, under
"report.numerics" with --json; tools/numlint.py is the gating CLI).
--all-models also aggregates the numerics codes per model, and a
builder-side numerics ERROR fails the sweep like a verifier error. The cost analysis never
traces or compiles; the rewrite stats' fold pass evaluates constant
ops eagerly on host CPU (JAX_PLATFORMS=cpu is pinned). --json always
carries the lowering↔infer registry coverage ("infer_coverage") and,
with --report, the full cost document under "report" (rewrite stats
under "report.rewrites").

Examples:
  python tools/fluidlint.py --model mnist
  python tools/fluidlint.py --model llama --json
  python tools/fluidlint.py --model resnet --report
  python tools/fluidlint.py --saved-model /tmp/my_model --json
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the verifier never compiles anything; pin jax to host CPU before any
# backend can initialize so a wedged TPU tunnel cannot hang the lint
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _load_target(args):
    """Returns (main, startup|None, fetch_list|None, feed_names|None,
    label)."""
    from paddle_tpu.core.executor import force_cpu
    # racecheck: ok(global-mutation) — lint CLI entrypoint: pins the
    # backend before anything compiles, single-threaded process
    force_cpu()
    if args.model:
        from paddle_tpu.models.zoo import build_zoo_program
        zp = build_zoo_program(args.model)
        return (zp.main, zp.startup, zp.fetch_list, zp.feed_names,
                f"model:{args.model}")
    from paddle_tpu.core.framework import Program
    if args.saved_model:
        with open(os.path.join(args.saved_model, "__model__.json")) as f:
            main = Program.from_json(f.read())
        meta_path = os.path.join(args.saved_model, "__meta__.json")
        fetch, feed = None, None
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            fetch = meta.get("fetch_names")
            feed = meta.get("feed_names")
        return main, None, fetch, feed, f"saved:{args.saved_model}"
    with open(args.program) as f:
        main = Program.from_json(f.read())
    startup = None
    if args.startup:
        with open(args.startup) as f:
            startup = Program.from_json(f.read())
    fetch = args.fetch or None
    return main, startup, fetch, None, f"program:{args.program}"


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="fluidlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    target = ap.add_mutually_exclusive_group(required=True)
    target.add_argument("--model", help="model-zoo entry to build")
    target.add_argument("--all-models", action="store_true",
                        help="lint the whole zoo in one process")
    target.add_argument("--program", help="Program JSON file")
    target.add_argument("--saved-model",
                        help="save_inference_model directory")
    target.add_argument("--list", action="store_true",
                        help="list zoo model names and exit")
    ap.add_argument("--startup", help="startup Program JSON "
                                      "(with --program)")
    ap.add_argument("--fetch", nargs="*", default=None,
                    help="fetch target names (with --program)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output for CI")
    ap.add_argument("--no-warnings", action="store_true",
                    help="print errors only")
    ap.add_argument("--report", action="store_true",
                    help="static cost/memory report (top-k op costs, "
                         "peak residency, dead-op count, remat "
                         "recommendation)")
    ap.add_argument("--top-k", type=int, default=10,
                    help="ops listed in the --report cost table")
    ap.add_argument("--assume-batch", type=int, default=1,
                    help="value substituted for unknown (-1) dims in "
                         "the cost model")
    args = ap.parse_args(argv)

    if args.list:
        from paddle_tpu.models.zoo import zoo_model_names
        print("\n".join(zoo_model_names()))
        return 0

    if args.all_models:
        return _lint_all_models(args)

    main_prog, startup, fetch, feed_names, label = _load_target(args)
    from paddle_tpu.analysis import CODES, errors, verify_program
    diags = verify_program(main_prog, startup=startup, fetch_list=fetch,
                           feed_names=feed_names, level="full")
    errs = errors(diags)
    warns = [d for d in diags if d.level == "warning"]

    report = None
    rewrites = None
    layout_plan = None
    numerics = None
    if args.report:
        from paddle_tpu.analysis import program_cost
        report = program_cost(main_prog, fetch_list=fetch,
                              assume_batch=args.assume_batch)
        rewrites = _rewrite_stats(main_prog, fetch)
        layout_plan = _layout_stats(main_prog, fetch,
                                    args.assume_batch)
        numerics = _numerics_stats(main_prog, fetch)

    if args.as_json:
        from paddle_tpu.core.registry import (registered_infer_types,
                                              registered_op_types)
        lowering = registered_op_types()
        infer = set(registered_infer_types())
        doc = {
            "target": label,
            "n_errors": len(errs),
            "n_warnings": len(warns),
            "codes": sorted({d.code for d in diags}),
            "diagnostics": [d.to_dict() for d in diags],
            "infer_coverage": {
                "n_lowering": len(lowering),
                "n_infer": len(infer),
                "missing": [t for t in lowering if t not in infer],
            },
        }
        if report is not None:
            doc["report"] = report.to_dict(args.top_k)
            doc["report"]["rewrites"] = rewrites
            doc["report"]["layout"] = layout_plan
            doc["report"]["numerics"] = numerics
        print(json.dumps(doc, indent=2))
    else:
        shown = errs if args.no_warnings else diags
        for d in shown:
            print(d.format())
        print(f"\n{label}: {len(errs)} error(s), {len(warns)} "
              f"warning(s)")
        if report is not None:
            _print_report(label, report, args.top_k)
            _print_rewrites(rewrites)
            _print_layout(layout_plan)
            _print_numerics(numerics)
        unknown = {d.code for d in diags} - set(CODES)
        if unknown:
            print(f"note: undocumented codes emitted: {unknown}",
                  file=sys.stderr)
    return 1 if errs else 0


def _lint_all_models(args):
    """One process, every zoo model: build → verify, one aggregated
    document. Builders and the verifier are jax-free, so the sweep is
    pure host work no matter how big the zoo grows."""
    from paddle_tpu.core.executor import force_cpu
    # racecheck: ok(global-mutation) — same CLI entrypoint contract
    force_cpu()
    from paddle_tpu.analysis import check_program, errors, verify_program
    from paddle_tpu.models.zoo import build_zoo_program, zoo_model_names
    models = {}
    total_errs = 0
    for name in zoo_model_names():
        try:
            zp = build_zoo_program(name)
            diags = verify_program(
                zp.main, startup=zp.startup, fetch_list=zp.fetch_list,
                feed_names=zp.feed_names, level="full")
            num = check_program(zp.main, fetch_list=zp.fetch_list)
        except Exception as e:      # a builder crash IS a lint failure
            models[name] = {"build_error": repr(e), "n_errors": 1,
                            "n_warnings": 0, "codes": [],
                            "diagnostics": []}
            total_errs += 1
            continue
        errs = errors(diags)
        # a builder-side numerics ERROR fails the sweep the same way a
        # verifier error does (numlint gates fixtures; this gates the
        # zoo builders themselves)
        total_errs += len(errs) + len(num.errors())
        models[name] = {
            "n_errors": len(errs),
            "n_warnings": sum(d.level == "warning" for d in diags),
            "codes": sorted({d.code for d in diags}),
            "diagnostics": [d.to_dict() for d in diags],
            "numerics": {
                "n_errors": len(num.errors()),
                "n_warnings": len(num.warnings()),
                "codes": sorted({d.code for d in num.findings}),
                "finite_safe": num.finite_safe,
            },
        }
    if args.as_json:
        print(json.dumps({"target": "all-models",
                          "n_models": len(models),
                          "n_errors": total_errs,
                          "models": models}, indent=2))
    else:
        for name, doc in models.items():
            num = doc.get("numerics")
            status = doc.get("build_error") or (
                f"{doc['n_errors']} error(s), "
                f"{doc['n_warnings']} warning(s); numerics "
                f"{num['n_errors']}E/{num['n_warnings']}W"
                + (" finite-safe" if num["finite_safe"] else ""))
            print(f"{name:24s} {status}")
        print(f"\nall-models: {len(models)} model(s), "
              f"{total_errs} error(s)")
    return 1 if total_errs else 0


def _rewrite_stats(main_prog, fetch):
    """What the rewrite pipeline (Program.optimize) would do to this
    program, measured on a throwaway clone with per-pass cost-model
    deltas — ops folded, chains fused, merged/removed counts, and the
    estimated FLOPs/bytes movement per pass. None without a fetch
    contract (nothing is provably rewritable), and never touches the
    caller's program. NOTE: the fold pass evaluates lowering rules
    eagerly (jax on host CPU — JAX_PLATFORMS=cpu is pinned above);
    every other fluidlint path stays jax-free."""
    if not fetch:
        return None
    fetch_names = [v.name if hasattr(v, "name") else v
                   for v in fetch]
    try:
        clone = main_prog.clone(for_test=main_prog._is_test)
        report = clone.optimize(fetch_list=fetch_names,
                                collect_cost=True)
    except Exception as e:
        return {"error": repr(e)}
    doc = report.to_dict()
    doc["n_ops_before"] = len(main_prog.global_block().ops)
    doc["n_ops_after"] = len(clone.global_block().ops)
    return doc


def _layout_stats(main_prog, fetch, assume_batch):
    """What the opt-in layout pass (analysis/layout.py) would do:
    conversion regions, inserted-transpose count, and the cost model's
    estimated bytes delta. Pure analysis on the caller's program —
    nothing is mutated, nothing traced."""
    try:
        from paddle_tpu.analysis import analyze_layout
        fetch_names = [v.name if hasattr(v, "name") else v
                       for v in (fetch or [])] or None
        plan = analyze_layout(main_prog, fetch_list=fetch_names,
                              assume_batch=assume_batch)
        return plan.to_dict()
    except Exception as e:
        return {"error": repr(e)}


def _numerics_stats(main_prog, fetch):
    """The abstract numerics interpretation (analysis/numcheck.py):
    CODES findings, finiteness verdict, and the AMP bf16-narrowing
    count. Pure analysis — nothing mutated, nothing traced."""
    try:
        from paddle_tpu.analysis import check_program
        report = check_program(main_prog, fetch_list=fetch)
        return report.to_dict()
    except Exception as e:
        return {"error": repr(e)}


def _print_numerics(num):
    print("\n-- numerics analysis (numcheck; tools/numlint.py is the "
          "gate CLI) --")
    if num is None or "error" in num:
        print(f"numerics analysis failed: {num and num.get('error')}")
        return
    safe = "finite-safe" if num["finite_safe"] else "not finite-safe"
    print(f"{num['n_errors']} error(s), {num['n_warnings']} "
          f"warning(s); {safe}"
          + (f"; AMP={num['amp']}: {num['n_narrowed']} binding(s) "
             f"bf16-narrowed" if num["amp"] else ""))
    for d in num["findings"]:
        loc = f"b{d['block_idx']}#{d['op_idx']}" \
            if d.get("op_idx") is not None else "program"
        print(f"  {d['level']}[{d['code']}] {loc}: {d['message']}")


def _print_layout(plan):
    print("\n-- layout analysis (opt-in passes=('layout',...)) --")
    if plan is None or "error" in plan:
        print(f"layout analysis failed: {plan and plan.get('error')}")
        return
    if plan.get("refused"):
        print(f"whole-program refusal: {plan['refused']}")
        return
    if not plan["n_regions"]:
        print("no 4-D NCHW conv/pool/BN regions found")
        return
    print(f"{plan['n_regions']} region(s), {plan['n_selected']} "
          f"profitable; converting would insert "
          f"{plan['n_transposes']} frontier transpose(s) and save an "
          f"estimated {plan['bytes_delta']:.3g} B of implicit "
          f"relayout copies per step")
    for i, r in enumerate(plan["regions"]):
        verdict = "CONVERT" if r["selected"] else \
            f"keep NCHW ({r['reason']})"
        delta = r["bytes_delta"]
        print(f"  region {i}: {r['n_ops']} ops "
              f"({r['n_sensitive']} layout-sensitive), "
              f"{r['n_transposes']} frontier transpose(s), "
              f"est. delta {delta if delta is None else f'{delta:.3g}'}"
              f" B -> {verdict}")


def _print_rewrites(rw):
    print("\n-- rewrite pipeline (Program.optimize, on a clone) --")
    if rw is None:
        print("no fetch contract: nothing provably rewritable")
        return
    if "error" in rw:
        print(f"rewrite pipeline failed: {rw['error']}")
        return
    print(f"passes {','.join(rw['passes'])}: ops "
          f"{rw['n_ops_before']} -> {rw['n_ops_after']} "
          f"({rw['folded']} folded, {rw['fused']} chains fused, "
          f"{rw['merged']} merged, {rw['removed']} removed)")
    for name, d in (rw.get("cost_deltas") or {}).items():
        print(f"  {name:5s} est. delta: {d['flops']:+.3g} FLOPs  "
              f"{d['bytes']:+.3g} B  {d['n_ops']:+d} ops")


def _print_report(label, report, top_k):
    def _mb(b):
        return f"{b / 2**20:8.2f} MiB" if b is not None else "   n/a"

    print(f"\n-- static cost report ({label}, assumed batch "
          f"{report.assume_batch}) --")
    print(f"ops: {len(report.per_op)}  total FLOPs: "
          f"{report.total_flops:.3g}  total bytes: "
          f"{report.total_bytes:.3g}  ops w/ unknown shapes: "
          f"{report.n_unknown_shape_ops}")
    print(f"params resident: {_mb(report.params_bytes)}   "
          f"peak residency estimate: {_mb(report.peak_residency_bytes)}")
    if report.residual_at_backward_bytes is not None:
        print(f"fwd->bwd residual estimate: "
              f"{_mb(report.residual_at_backward_bytes)}   recommended "
              f"remat policy: {report.recommended_remat_policy!r}")
    if report.dead_op_count is not None:
        print(f"DCE-provable dead ops: {report.dead_op_count}")
    print(f"top {top_k} ops by FLOPs:")
    for c in report.top_ops(top_k):
        outs = ",".join(c.outputs)
        print(f"  {c.flops:12.3g} flops {c.bytes:12.3g} B  "
              f"b{c.block_idx}#{c.op_idx:<4} {c.op_type:24s} -> {outs}")


if __name__ == "__main__":
    sys.exit(main())
