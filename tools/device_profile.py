"""Per-kernel DEVICE-TIME profile of the ResNet-50 bench step (VERDICT
r4 task 3): wrap one measured dispatch in jax.profiler.trace, parse the
xplane proto, and print the top kernels by actual device duration.

Every prior perf argument leaned on compiled_stats' bytes/flops
ESTIMATES; this is the reference device_tracer's role
(/root/reference/paddle/fluid/platform/device_tracer.cc — CUPTI
activity records → per-op device spans) done the XLA way.

If the tunneled backend does not return device trace data, the script
prints the planes it DID get and exits 3 — that output is the recorded
failed attempt BASELINE.json cites.

Run on the chip:  python tools/device_profile.py [model] [batch]
(model: resnet50 | vgg16). Prints one JSON line: {"planes": [...],
"top_kernels_by_time": [{name, total_ms, count}...], "step_ms": ...}.
"""
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    model = sys.argv[1] if len(sys.argv) > 1 else "resnet50"
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 128

    import jax
    import numpy as np
    import paddle_tpu as fluid
    from paddle_tpu.transpiler import amp_transpile

    on_tpu = jax.default_backend() in ("tpu", "axon")
    layout = "NHWC" if on_tpu else "NCHW"
    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        img = fluid.layers.data(name="img", shape=[3, 224, 224],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1],
                                  dtype="int64")
        if model == "vgg16":
            from paddle_tpu.models.vgg import vgg16
            avg_cost, _, _ = vgg16(img, label, layout=layout)
        else:
            from paddle_tpu.models.resnet import resnet50
            avg_cost, _, _ = resnet50(img, label, layout=layout)
        fluid.optimizer.Momentum(learning_rate=0.1,
                                 momentum=0.9).minimize(avg_cost)
    if on_tpu:
        amp_transpile(main_p, level="O2")

    exe = fluid.Executor(fluid.TPUPlace())
    scope = fluid.Scope()
    reps = 8 if on_tpu else 1
    trace_dir = "/tmp/ptpu_device_trace"
    import shutil
    shutil.rmtree(trace_dir, ignore_errors=True)
    # racecheck: ok(global-mutation) — single-process profiling
    # entrypoint: owns the whole process, no serving threads exist
    with fluid.scope_guard(scope):
        exe.run(startup_p)
        rng = np.random.RandomState(0)
        feed = {"img": jax.device_put(
                    rng.rand(batch, 3, 224, 224).astype(np.float32)),
                "label": jax.device_put(
                    rng.randint(0, 1000, (batch, 1)).astype(np.int64))}
        # warm: compile happens OUTSIDE the trace
        # racecheck: ok(run-without-scope) — scope_guard above binds a
        # private Scope; single-threaded profiler, nothing to race
        exe.run(main_p, feed=feed, fetch_list=[avg_cost], repeats=reps)
        exe.run(main_p, feed=feed, fetch_list=[avg_cost], repeats=reps)
        import time
        jax.profiler.start_trace(trace_dir)
        t0 = time.perf_counter()
        # racecheck: ok(run-without-scope) — same private scope_guard
        out = exe.run(main_p, feed=feed, fetch_list=[avg_cost],
                      repeats=reps)
        step_ms = (time.perf_counter() - t0) * 1e3 / reps
        jax.profiler.stop_trace()
        assert np.isfinite(float(np.asarray(out[0]).reshape(())))

    paths = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        print(json.dumps({"error": "no xplane.pb produced",
                          "trace_dir": trace_dir}))
        sys.exit(3)
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    space = xplane_pb2.XSpace()
    with open(paths[0], "rb") as f:
        space.ParseFromString(f.read())

    planes = [p.name for p in space.planes]
    device_planes = [p for p in space.planes
                     if "TPU" in p.name or "device" in p.name.lower()]
    kernels = {}
    for plane in device_planes:
        # XPlane: event_metadata id -> name; events carry duration_ps
        meta = {m_id: m.name for m_id, m in plane.event_metadata.items()}
        for line in plane.lines:
            for ev in line.events:
                name = meta.get(ev.metadata_id, str(ev.metadata_id))
                ms = ev.duration_ps / 1e9
                agg = kernels.setdefault(name, [0.0, 0])
                agg[0] += ms
                agg[1] += 1
    top = sorted(kernels.items(), key=lambda kv: -kv[1][0])[:25]
    rec = {
        "model": model, "batch": batch, "repeats": reps,
        "backend": jax.default_backend(),
        "host_step_ms": round(step_ms, 2),
        "planes": planes,
        "n_device_kernels": len(kernels),
        "top_kernels_by_time": [
            {"name": n[:120], "total_ms": round(t, 3), "count": c}
            for n, (t, c) in top],
    }
    print(json.dumps(rec))
    if not kernels:
        sys.exit(3)


if __name__ == "__main__":
    main()
