#!/usr/bin/env python
"""numlint — static numerics & precision-flow lint CLI (numcheck).

Runs the abstract numerics interpreter (analysis/numcheck.py) over a
program WITHOUT tracing or compiling anything and prints the CODES
findings: ``fp16-overflow-risk``, ``cast-precision-loss``,
``int8-scale-clip``, ``domain-hazard``, ``amp-unprotected-reduce``
(docs/RELIABILITY.md "Numerics checking").

Targets (one of):
  --model NAME       build a model-zoo program (paddle_tpu/models/zoo.py)
  --all-models       lint EVERY zoo model in this one process — the CI
                     sweep (one JSON document with --json)
  --program FILE     a Program saved as JSON (Program.to_json), with
                     optional --startup FILE and --fetch NAME ...
  --saved-model DIR  a save_inference_model directory
  --list             print the zoo model names and exit

--amp O1|O2 transpiles the target(s) to mixed precision first, so the
sweep covers the AMP dtype-narrowing flow the rewrite gates consult.

Suppression uses the same grammar as racecheck (analysis/suppress.py)
under the ``numcheck:`` tag::

    # numcheck: ok(<code>[, <code>...]) — <non-empty reason>

but matched FILE-SCOPED rather than line-anchored: numcheck findings
point at IR ops, not source lines, so a suppression anywhere in the
suppression source (default for model targets:
``paddle_tpu/models/zoo.py`` — the builders' home; override with
--suppressions FILE) suppresses that code for the target. Suppressed
findings are reported but do not fail the lint; a reason-less
``ok(...)`` is itself a ``bad-suppression`` warning.

Exit status is 1 iff any UNSUPPRESSED error-level finding exists (for
--all-models: in any model, and a builder crash counts) — the
selfcheck stage 11 gate.
"""
import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# numcheck never compiles anything; pin jax to host CPU before any
# backend can initialize so a wedged TPU tunnel cannot hang the lint
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_ZOO_SOURCE = os.path.join(_REPO, "paddle_tpu", "models", "zoo.py")


def _load_suppressions(path):
    from paddle_tpu.analysis.suppress import Suppressions
    if not path or not os.path.isfile(path):
        return None
    with open(path, "r", encoding="utf-8") as f:
        return Suppressions(f.read(), path, tag="numcheck")


def _lint_program(main, fetch, amp, supp):
    """Returns (doc, n_unsuppressed_errors). ``doc`` is the per-target
    JSON fragment: the NumericsReport dict with findings split into
    unsuppressed/suppressed by the file-scoped suppression table."""
    from paddle_tpu.analysis.numcheck import check_program
    if amp:
        from paddle_tpu.transpiler import amp_transpile
        amp_transpile(main, level=amp)
    report = check_program(main, fetch_list=fetch)
    findings, suppressed = [], []
    for d in report.findings:
        reason = supp.match_any(d.code) if supp is not None else None
        if reason is not None:
            suppressed.append((d, reason))
        else:
            findings.append(d)
    bad = list(supp.bad) if supp is not None else []
    n_err = sum(d.level == "error" for d in findings)
    doc = report.to_dict()
    doc["findings"] = [d.to_dict() for d in findings]
    doc["n_findings"] = len(findings)
    doc["n_errors"] = n_err
    doc["n_warnings"] = (sum(d.level == "warning" for d in findings)
                         + len(bad))
    doc["suppressed"] = [dict(d.to_dict(), reason=reason)
                         for d, reason in suppressed]
    doc["bad_suppressions"] = [d.to_dict() for d in bad]
    return doc, n_err


def _print_doc(label, doc, show_suppressed):
    for d in doc["findings"]:
        loc = f"b{d['block_idx']}#{d['op_idx']}" \
            if d.get("op_idx") is not None else "program"
        print(f"{d['level']}[{d['code']}] {label} {loc}: "
              f"{d['message']}")
        if d.get("hint"):
            print(f"    hint: {d['hint']}")
    for d in doc["bad_suppressions"]:
        print(f"{d['level']}[{d['code']}] {d['path']}:{d['line']}: "
              f"{d['message']}")
    if show_suppressed:
        for d in doc["suppressed"]:
            print(f"suppressed[{d['code']}] {label} — {d['reason']}")
    safe = "finite-safe" if doc["finite_safe"] else "not finite-safe"
    print(f"{label}: {doc['n_errors']} error(s), "
          f"{doc['n_warnings']} warning(s), "
          f"{len(doc['suppressed'])} suppressed; {safe}"
          + (f"; {doc['n_narrowed']} binding(s) bf16-narrowed"
             if doc["amp"] else ""))


def _load_explicit(args):
    from paddle_tpu.core.framework import Program
    if args.saved_model:
        with open(os.path.join(args.saved_model, "__model__.json")) as f:
            main = Program.from_json(f.read())
        meta_path = os.path.join(args.saved_model, "__meta__.json")
        fetch = None
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                fetch = json.load(f).get("fetch_names")
        return main, fetch, f"saved:{args.saved_model}"
    with open(args.program) as f:
        main = Program.from_json(f.read())
    return main, args.fetch or None, f"program:{args.program}"


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="numlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    target = ap.add_mutually_exclusive_group(required=True)
    target.add_argument("--model", help="model-zoo entry to build")
    target.add_argument("--all-models", action="store_true",
                        help="lint the whole zoo in one process")
    target.add_argument("--program", help="Program JSON file")
    target.add_argument("--saved-model",
                        help="save_inference_model directory")
    target.add_argument("--list", action="store_true",
                        help="list zoo model names and exit")
    ap.add_argument("--startup", help="ignored (accepted for symmetry "
                                      "with fluidlint)")
    ap.add_argument("--fetch", nargs="*", default=None,
                    help="fetch target names (with --program)")
    ap.add_argument("--amp", default=None, choices=("O1", "O2"),
                    help="transpile the target(s) to mixed precision "
                         "before checking")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output for CI")
    ap.add_argument("--suppressions", default=None,
                    help="source file carrying '# numcheck: ok(...)' "
                         "comments (default for model targets: the "
                         "zoo builder module; none otherwise)")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings (text mode)")
    args = ap.parse_args(argv)

    if args.list:
        from paddle_tpu.models.zoo import zoo_model_names
        print("\n".join(zoo_model_names()))
        return 0

    from paddle_tpu.core.executor import force_cpu
    # racecheck: ok(global-mutation) — lint CLI entrypoint: pins the
    # backend before anything compiles, single-threaded process
    force_cpu()

    supp_path = args.suppressions
    if supp_path is None and (args.model or args.all_models):
        supp_path = _ZOO_SOURCE
    supp = _load_suppressions(supp_path)

    if args.all_models:
        from paddle_tpu.models.zoo import (build_zoo_program,
                                           zoo_model_names)
        models, total_errs = {}, 0
        for name in zoo_model_names():
            try:
                zp = build_zoo_program(name)
                doc, n_err = _lint_program(
                    zp.main, zp.fetch_list, args.amp, supp)
            except Exception as e:  # a builder crash IS a lint failure
                models[name] = {"build_error": repr(e), "n_errors": 1}
                total_errs += 1
                continue
            models[name] = doc
            total_errs += n_err
        if args.as_json:
            print(json.dumps({"target": "all-models",
                              "amp": args.amp or False,
                              "n_models": len(models),
                              "n_errors": total_errs,
                              "models": models}, indent=2))
        else:
            for name, doc in models.items():
                if "build_error" in doc:
                    print(f"{name:24s} BUILD ERROR: "
                          f"{doc['build_error']}")
                    continue
                safe = "finite-safe" if doc["finite_safe"] else \
                    "not finite-safe"
                print(f"{name:24s} {doc['n_errors']} error(s), "
                      f"{doc['n_warnings']} warning(s), "
                      f"{len(doc['suppressed'])} suppressed; {safe}")
            amp_tag = f" @ amp={args.amp}" if args.amp else ""
            print(f"\nall-models{amp_tag}: {len(models)} model(s), "
                  f"{total_errs} unsuppressed error(s)")
        return 1 if total_errs else 0

    if args.model:
        from paddle_tpu.models.zoo import build_zoo_program
        zp = build_zoo_program(args.model)
        main_prog, fetch, label = (zp.main, zp.fetch_list,
                                   f"model:{args.model}")
    else:
        main_prog, fetch, label = _load_explicit(args)

    doc, n_err = _lint_program(main_prog, fetch, args.amp, supp)
    doc["target"] = label
    if args.as_json:
        print(json.dumps(doc, indent=2))
    else:
        _print_doc(label, doc, args.show_suppressed)
    return 1 if n_err else 0


if __name__ == "__main__":
    sys.exit(main())
