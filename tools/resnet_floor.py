"""Analytic minimum HBM bytes/step for the ResNet-50 train bench
(VERDICT r4 task 1a): what would a PERFECT compiler have to move?

The model of "minimum" (optimistic — assumes every elementwise /
batch-norm / pool / residual-add op fuses for free into an adjacent
conv's read or write pass, and nothing but the conv boundary
activations ever crosses HBM):

  forward, per conv:   read A_in, read W, write A_out
  backward, per conv:  read A_out   (recompute the BN+ReLU epilogue),
                       read dY      (written by the next layer's dX),
                       read A_in    (for dW), read W (for dX),
                       write dX, write dW
  optimizer (momentum, f32 master + velocity, bf16 compute copy):
                       read W32, read vel, read dW, write W32,
                       write vel, write W16
  input batch:         read once (uint8-decoded f32 feed cast to bf16)

Activations/grads are billed at the train dtype (bf16 under the bench
AMP-O2 default); params/grads at bf16 with the f32 master/velocity
sweep billed at f32. dY of layer L IS dX of layer L+1: each boundary
gradient is written once and read once — both passes are counted, one
on each side.

This floor is what the measured step (BASELINE resnet_gap_analysis,
~37-42 GB) must be compared against: measured/floor <= ~1.3x means the
bytes-bound conclusion is real, not a stopping excuse. Reference
counterpart of the question: the per-op CUDA kernels of
/root/reference/paddle/fluid/operators/conv_cudnn_op.cu.cc make every
one of these passes explicit; XLA's job is to not add more.

Run: python tools/resnet_floor.py [batch]
Prints one JSON line with the breakdown.
"""
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax                                                   # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np                                           # noqa: E402

import paddle_tpu as fluid                                   # noqa: E402
from paddle_tpu.models.resnet import resnet50                # noqa: E402


def floor_bytes(batch=128, act_bytes=2, param_bytes=2, opt_bytes=4,
                layout="NHWC"):
    main_p, startup_p = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup_p):
        img = fluid.layers.data(name="img", shape=[3, 224, 224],
                                dtype="float32")
        label = fluid.layers.data(name="label", shape=[1],
                                  dtype="int64")
        avg_cost, _, _ = resnet50(img, label, layout=layout)
        fluid.optimizer.Momentum(learning_rate=0.1,
                                 momentum=0.9).minimize(avg_cost)

    def numel(var_name):
        shape = [batch if (d is None or d < 0) else d
                 for d in main_p.global_block().var(var_name).shape]
        return int(np.prod(shape))

    convs = []
    n_params = 0
    block = main_p.global_block()
    for op in block.ops:
        if op.type in ("conv2d", "mul"):       # mul = the final fc
            x_name = op.input("Input" if op.type == "conv2d" else "X")[0]
            w_name = op.input("Filter" if op.type == "conv2d" else "Y")[0]
            out_name = op.output("Output" if op.type == "conv2d"
                                 else "Out")[0]
            convs.append({
                "op": op.type,
                "a_in": numel(x_name),
                "w": numel(w_name),
                "a_out": numel(out_name),
            })
    for name, var in block.vars.items():
        if getattr(var, "persistable", False) and name.endswith(
                (".w_0", ".b_0", ".w_1", ".w_2")):
            pass
    # parameter count from the startup program (it initializes exactly
    # the trainable params + BN stats; velocities are optimizer state)
    for op in startup_p.global_block().ops:
        for n in op.output_names():
            v = block.vars.get(n)
            # skip BN moving stats and optimizer accumulators (their
            # sweep is billed separately in `opt` below)
            if v is not None and not n.endswith(
                    (".global_0", ".global_1")) and "velocity" not in n:
                n_params += numel(n)

    fwd = sum(c["a_in"] + c["w"] + c["a_out"] for c in convs)
    bwd = sum(2 * c["a_out"]            # read A_out (epilogue) + dY
              + 2 * c["a_in"]           # read A_in (dW) + write dX
              + 2 * c["w"]              # read W (dX) + write dW
              for c in convs)
    act_gb = (fwd + bwd) * act_bytes / 2**30
    # weights billed at param dtype in fwd/bwd above — rebill their
    # share: fwd W read + bwd (W read + dW write) are param_bytes wide
    w_total = sum(c["w"] for c in convs)
    opt = n_params * (3 * opt_bytes      # read W32, vel, dW-as-f32
                      + 2 * opt_bytes    # write W32, vel
                      + param_bytes)     # write bf16 compute copy
    input_bytes = batch * 3 * 224 * 224 * act_bytes
    total = (fwd + bwd) * act_bytes + opt + input_bytes
    return {
        "batch": batch,
        "n_convs": len(convs),
        "n_params": n_params,
        "fwd_gb": round(fwd * act_bytes / 2**30, 2),
        "bwd_gb": round(bwd * act_bytes / 2**30, 2),
        "conv_weight_passes_gb": round(3 * w_total * act_bytes / 2**30,
                                       2),
        "optimizer_gb": round(opt / 2**30, 2),
        "input_gb": round(input_bytes / 2**30, 3),
        "floor_gb_per_step": round(total / 2**30, 2),
        "activation_share": round((fwd + bwd) * act_bytes / total, 3),
        "note": ("optimistic floor: perfect epilogue fusion, conv "
                 "boundary activations cross HBM exactly the passes "
                 "listed in the module docstring"),
    }


if __name__ == "__main__":
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    print(json.dumps(floor_bytes(batch)))
