#!/usr/bin/env python
"""trainbench — elastic training fabric bench + multi-process chaos
drill (cluster/train_fabric.py, cluster/train_worker.py).

Default mode is a loopback throughput bench: an in-process fleet runs
N coordinated steps and reports steps/s and per-worker step-time
percentiles.

``--chaos`` is the headline drill behind selfcheck stage 12: REAL
subprocess workers (``python -m paddle_tpu.cluster.train_worker``),
all four trainer fault points fired against one run —

1. ``trainer_crash_at_step`` (env-armed, ``--hard-exit``: the worker
   takes an ``os._exit`` mid-step — the SIGKILL shape), the
   coordinator evicts and retries at reduced world size, and a
   REPLACEMENT worker cold-provisions its ``__artifacts__`` over the
   wire from a live peer (``--task program``: total_compiles must be
   0) and is folded back in (elastic up, ``train_elastic_resume_s``);
2. ``trainer_straggle`` (env-armed stall past the coordinator's
   straggler deadline): evicted typed, REJOINS after the stall heals
   (``train_recover_s``);
3. ``train_net_partition`` (armed coordinator-side): the RPC route
   vanishes typed for two calls, the worker is evicted and rejoins
   when the route heals;
4. ``coordinator_crash`` (SimulatedCrash — no exit checkpoint): the
   workers park, a NEW coordinator resumes from the last committed
   serial.

PASS requires the chaos run's committed ``(serial, sha)`` sequence to
EQUAL an uninterrupted single-worker reference run's — zero lost
committed steps AND bit-deterministic resume — plus loss-curve parity.
``--no-recover`` disables elasticity (the teeth-check: the drill must
then FAIL, proving the assertions detect lost runs).

Usage:
    python tools/trainbench.py [--steps 60] [--workers 2]
    python tools/trainbench.py --chaos [--task linreg|program]
                               [--steps 20] [--no-recover]
                               [--json] [--out FILE]
Pure CPU; exit 0 on pass, 1 on failure.
"""
import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _task(kind, seed=11):
    from paddle_tpu.cluster.train_fabric import (LinRegTask,
                                                 ProgramGradTask)
    if kind == "linreg":
        return LinRegTask(seed=seed)
    return ProgramGradTask(seed=seed)


def _reference_run(kind, steps, commit_interval, n_shards):
    """Uninterrupted single-worker run: the parity target."""
    from paddle_tpu.cluster.train_fabric import TrainCoordinator
    from paddle_tpu.cluster.train_worker import TrainWorkerServer
    d = tempfile.mkdtemp(prefix="trainbench_ref_")
    w = TrainWorkerServer(
        artifact_dir=tempfile.mkdtemp(prefix="trainbench_ref_af_")
        if kind == "program" else None)
    co = TrainCoordinator(_task(kind), [w.addr], d,
                          commit_interval=commit_interval,
                          n_shards=n_shards)
    co.run(steps)
    commits, losses = co.commits(), co.losses()
    co.close()
    w.close()
    return commits, losses


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_worker(port, artifact_dir=None, provision_from=None,
                  faults=None, straggle_s=None, hard_exit=False):
    """Launch a real subprocess worker; block until its ready line."""
    cmd = [sys.executable, "-m", "paddle_tpu.cluster.train_worker",
           "--host", "127.0.0.1", "--port", str(port)]
    if artifact_dir:
        cmd += ["--artifact-dir", artifact_dir]
    if provision_from:
        cmd += ["--provision-from", provision_from]
    if hard_exit:
        cmd += ["--hard-exit"]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PYTHONUNBUFFERED"] = "1"
    if faults:
        env["PADDLE_TPU_FAULTS"] = faults
    if straggle_s is not None:
        env["PADDLE_TPU_FAULT_STRAGGLE_S"] = str(straggle_s)
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    deadline = time.monotonic() + 120.0
    for line in proc.stdout:
        if "ready on" in line:
            return proc
        if time.monotonic() > deadline:
            break
    proc.kill()
    raise RuntimeError(f"worker on port {port} never became ready")


def bench_main(args):
    from paddle_tpu.cluster.train_fabric import TrainCoordinator
    from paddle_tpu.cluster.train_worker import TrainWorkerServer
    workers = [TrainWorkerServer() for _ in range(args.workers)]
    co = TrainCoordinator(
        _task(args.task), [w.addr for w in workers],
        tempfile.mkdtemp(prefix="trainbench_"),
        commit_interval=args.commit_interval,
        n_shards=max(args.workers * 2, 4))
    t0 = time.monotonic()
    co.run(args.steps)
    wall = time.monotonic() - t0
    snap = co.stats()
    steps_s = args.steps / wall
    report = {
        "mode": "bench", "task": args.task, "steps": args.steps,
        "workers": args.workers, "wall_s": round(wall, 3),
        "steps_per_s": round(steps_s, 2),
        "worker_rows": [
            {k: r[k] for k in ("name", "last_step",
                               "step_time_p50_ms",
                               "step_time_p99_ms")}
            for r in snap["workers"]],
        "bench_record": {
            "metric": "train_fabric_steps_per_s",
            "value": round(steps_s, 2), "unit": "steps/s",
            "backend": "cpu", "workers": args.workers,
            "task": args.task},
    }
    co.close()
    for w in workers:
        w.close()
    _emit(args, report,
          f"trainbench: {args.steps} steps x {args.workers} workers "
          f"in {wall:.2f}s ({steps_s:.1f} steps/s)")
    return 0


def chaos_main(args):
    from paddle_tpu.cluster.train_fabric import TrainCoordinator
    from paddle_tpu.resilience import faultinject
    from paddle_tpu.resilience.faultinject import SimulatedCrash

    kind = args.task
    steps = max(args.steps, 30)     # the 4 phases need the room
    commit_interval, n_shards = 5, 4
    failures = []
    records = {}

    print(f"trainbench --chaos: reference run ({kind}, {steps} "
          "steps)...", flush=True)
    ref_commits, ref_losses = _reference_run(kind, steps,
                                             commit_interval, n_shards)

    ckpt_dir = tempfile.mkdtemp(prefix="trainbench_chaos_")
    afs = {n: tempfile.mkdtemp(prefix=f"trainbench_{n}_")
           for n in ("w1", "w2", "w3")}
    # w1 dies hard on its 3rd served step; w2 straggles once later
    w1 = _spawn_worker(_free_port(),
                       artifact_dir=afs["w1"] if kind == "program"
                       else None,
                       faults="trainer_crash_at_step@2",
                       hard_exit=True)
    # w2's 11th handled step stalls: steps 1-6 plus the crash retry
    # are 7 handles in phase 1, 2 more after w3 joins — index 10
    # lands inside phase 2's window, after the warmup deadline drops
    w2 = _spawn_worker(_free_port(),
                       artifact_dir=afs["w2"] if kind == "program"
                       else None,
                       faults="trainer_straggle@10", straggle_s=3.0)
    w1_addr = None
    w2_addr = None
    procs = [w1, w2]
    try:
        # recover the addresses from the spawn ports: the ready lines
        # were consumed by _spawn_worker, so re-derive from the cmd
        w1_addr = f"127.0.0.1:{w1.args[w1.args.index('--port') + 1]}"
        w2_addr = f"127.0.0.1:{w2.args[w2.args.index('--port') + 1]}"
        co = TrainCoordinator(
            _task(kind), [w1_addr, w2_addr], ckpt_dir,
            commit_interval=commit_interval, n_shards=n_shards,
            step_deadline_s=30.0, admit_deadline_s=10.0,
            readmit_interval_s=0.1, elastic=not args.no_recover)

        # --- phase 1: hard worker crash + elastic replacement -------
        print("phase 1: trainer_crash_at_step (hard exit) ...",
              flush=True)
        co.run(6)
        if co.evictions_total < 1:
            failures.append("w1's hard crash never evicted it")
        w3_port = _free_port()
        t0 = time.monotonic()
        w3 = _spawn_worker(
            w3_port,
            artifact_dir=afs["w3"] if kind == "program" else None,
            provision_from=w2_addr if kind == "program" else None)
        procs.append(w3)
        w3_addr = f"127.0.0.1:{w3_port}"
        w3_client = co.admit(w3_addr)
        co.run(2)                       # the admit sweep folds w3 in
        if not w3_client.admitted:
            failures.append("replacement worker w3 was never admitted")
        records["train_elastic_resume_s"] = round(
            time.monotonic() - t0, 3)

        # --- phase 2: straggler evict + rejoin ----------------------
        print("phase 2: trainer_straggle past the deadline ...",
              flush=True)
        # every program is warm now (and w3 provisioned, so no
        # compile ever re-raises the bar): a 3s stall against a 1.5s
        # deadline is an unambiguous straggler
        co.step_deadline_s = 1.5
        evict_before = co.evictions_total
        rejoin_before = co.rejoins_total
        co.run(4)                       # w2's 11th handle stalls 3s
        deadline = time.monotonic() + 15.0
        while (co.rejoins_total <= rejoin_before
               and time.monotonic() < deadline
               and co.step < steps - 4):
            # pace the loop so the readmit backoff can elapse — the
            # reduced fleet steps in microseconds otherwise
            time.sleep(0.15)
            co.run(1)
        if co.evictions_total <= evict_before:
            failures.append("the straggler was never evicted")
        if co.rejoins_total <= rejoin_before:
            failures.append("the healed straggler never rejoined")
        records["train_recover_s"] = co.last_recover_s and round(
            co.last_recover_s, 3)

        # --- phase 3: net partition (coordinator side) --------------
        print("phase 3: train_net_partition x2 ...", flush=True)
        faultinject.arm("train_net_partition", at=0, times=2)
        co.run(2)
        faultinject.disarm("train_net_partition")

        # --- phase 4: coordinator crash + resume --------------------
        print("phase 4: coordinator_crash + resume ...", flush=True)
        faultinject.arm("coordinator_crash", at=0)
        crashed = False
        try:
            co.run(max(1, steps - co.step))
        except SimulatedCrash:
            crashed = True
        faultinject.disarm()
        if not crashed:
            failures.append("coordinator_crash never fired")
        co_totals = (co.evictions_total, co.rejoins_total,
                     co.retries_total)
        co.close()
        co2 = TrainCoordinator(
            _task(kind),
            [w2_addr, f"127.0.0.1:{w3_port}"], ckpt_dir,
            commit_interval=commit_interval, n_shards=n_shards,
            step_deadline_s=30.0, admit_deadline_s=10.0,
            readmit_interval_s=0.1, elastic=not args.no_recover)
        resumed_at = co2.step
        co2.run(steps - co2.step)
        chaos_commits, chaos_losses = co2.commits(), co2.losses()

        # --- verdicts ----------------------------------------------
        # zero lost committed steps + bit-deterministic resume
        ref_tail = [c for c in ref_commits if c[0] >= resumed_at]
        if chaos_commits != ref_tail and chaos_commits != ref_commits:
            failures.append(
                f"committed (serial, sha) diverged: chaos "
                f"{chaos_commits} vs reference {ref_commits}")
        # loss-curve parity for every step the resumed run computed
        ref_by_step = {i + 1: v for i, v in enumerate(ref_losses)}
        for i, loss in enumerate(chaos_losses):
            step = resumed_at + i + 1
            ref = ref_by_step.get(step)
            if ref is not None and abs(loss - ref) > 1e-6 * max(
                    1.0, abs(ref)):
                failures.append(
                    f"loss curve diverged at step {step}: "
                    f"{loss} vs {ref}")
                break
        # the replacement provisioned with zero compiles
        if kind == "program":
            for c in co2.live_workers():
                if c.name == w3_addr:
                    c.refresh()     # a stats heartbeat fills the cache
                    compiles = c.stats().get("total_compiles")
                    if compiles != 0:
                        failures.append(
                            f"replacement worker recompiled: "
                            f"total_compiles={compiles}")
        snap = co2.stats()
        co2.close()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    report = {
        "mode": "chaos", "task": kind, "steps": steps,
        "resumed_at_serial": resumed_at,
        "reference_commits": [[s, sha] for s, sha in ref_commits],
        "chaos_commits": [[s, sha] for s, sha in chaos_commits],
        "evictions_total": co_totals[0] + snap["evictions_total"],
        "rejoins_total": co_totals[1] + snap["rejoins_total"],
        "retries_total": co_totals[2] + snap["retries_total"],
        "events": snap["events"],
        "failures": failures,
        "bench_record": {
            "metric": "train_recover_s",
            "value": records.get("train_recover_s"), "unit": "s",
            "backend": "cpu", "task": kind,
            "train_elastic_resume_s":
                records.get("train_elastic_resume_s")},
    }
    ok = not failures
    _emit(args, report,
          ("trainbench --chaos PASS: zero lost committed steps, "
           f"resume sha-deterministic at serial {resumed_at} "
           f"(recover {records.get('train_recover_s')}s, elastic "
           f"resume {records.get('train_elastic_resume_s')}s)")
          if ok else
          "trainbench --chaos FAIL:\n  - " + "\n  - ".join(failures))
    return 0 if ok else 1


def _emit(args, report, line):
    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if args.json:
        print(text)
    else:
        print(line)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="training-fabric bench + multi-process chaos "
                    "drill")
    ap.add_argument("--chaos", action="store_true")
    ap.add_argument("--task", choices=("linreg", "program"),
                    default="linreg")
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--commit-interval", type=int, default=5)
    ap.add_argument("--no-recover", action="store_true",
                    help="disable elastic eviction/retry — the drill "
                         "MUST fail (inverted teeth-check)")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.steps is None:
        args.steps = 20 if args.chaos else 60
    # racecheck: ok(global-mutation) — single-process bench entrypoint:
    # runs before any thread or jax backend exists
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import paddle_tpu as fluid
    # racecheck: ok(global-mutation) — ditto: entrypoint-owned process
    fluid.force_cpu()
    if args.chaos:
        try:
            return chaos_main(args)
        except Exception as exc:    # noqa: BLE001 — a typed failure
            # of the drill itself is a FAIL, not a crash dump
            print(f"trainbench --chaos FAIL: "
                  f"{type(exc).__name__}: {exc}")
            return 1
    return bench_main(args)


if __name__ == "__main__":
    sys.exit(main())
