#!/usr/bin/env python
"""servebench — serving load generator: batched vs single-request.

Builds a tiny model-zoo entry, stands up a
``paddle_tpu.serving.ServingEngine`` over it (warmup pre-compiles
every declared bucket), then drives the same request set two ways:

1. **baseline** — the pre-serving story: one synchronous
   ``Executor.run`` per request, one device dispatch each.
2. **batched** — ``--concurrency`` client threads submitting through
   the engine, which coalesces them into bucket-padded micro-batches.

Reports requests/s for both, the speedup, the engine's metrics
snapshot (batch-fill ratio, latency percentiles), and a correctness
sweep: every request's served rows must match its single-request rows
(the per-row fetch is the cross_entropy input — the model's
prediction head — so batch-mean scalars never blur the comparison).
The cross-shape comparison is tolerance-based (rtol 1e-5): XLA
legitimately re-tiles a matmul per batch shape, so batch-8 rows can
differ from batch-1 rows by an ulp — bit-for-bit equality holds
WITHIN a bucket shape and is pinned that way in tests/test_serving.py;
across buckets "zero dropped-correctness" means zero beyond-float-
tolerance divergences. ``assert_no_recompiles`` additionally proves
zero XLA compiles happened during traffic.

Usage:
  python tools/servebench.py [--model mnist_mlp] [--requests 128]
      [--concurrency 16] [--max-batch 8] [--max-wait-ms 2.0]
      [--assert-speedup 1.0] [--json] [--out FILE]

Exit 0 on success; exit 1 when correctness drops or the measured
speedup falls below ``--assert-speedup`` (tools/selfcheck.sh stage 3
gates on both). CPU-only, seconds.

Chaos mode (``--chaos``, tools/selfcheck.sh stage 4) swaps the
speedup race for a fault drill: it injects ``serving_device_error``
mid-load and asserts the hardening contract (docs/SERVING.md
"Operating under failure") — ZERO lost requests (every submission
terminates with a result or a typed error), the circuit breaker
demonstrably opens and then recovers once the fault clears, post-
recovery traffic is all-success with measurable throughput,
``close(drain=True)`` completes every in-flight request, and
``assert_no_recompiles`` still holds in steady state.

Decode mode (``--decode``, tools/selfcheck.sh stage 6) benchmarks the
continuous-batching decode engine (docs/SERVING.md "Continuous decode
batching") on a tiny llama config: baseline is sequential per-request
generation through the fused ``build_llama_generator`` program (one
request at a time — the pre-engine story), continuous is concurrent
submission through ``serving.DecodeEngine``. Reports aggregate tok/s
both ways, per-request greedy-token equality (exact), TTFT/TPOT
percentiles, a zero-recompile check, and a BENCH-compatible record
under ``bench_record`` (metric ``llama_decode_serving_tok_s``).
``--spec`` runs the engine in speculative mode (perfect draft).

SLO mode (``--decode --slo``, tools/selfcheck.sh stage 13) swaps the
throughput race for a scheduling-policy gate: a mixed short/long
interference trace runs under FIFO admission, the EDF SLO scheduler,
and a 2-prefill/2-decode disaggregated pool (docs/SERVING.md
"Disaggregated decode serving"), with the ``serving_handoff_drop``
chaos drill riding the pool arm. The interactive TTFT target is
calibrated to a quarter of FIFO's measured queue-wait TTFT, so the
pass/fail is scheduling-order-driven on any CPU speed: exit 1 unless
the SLO scheduler's TTFT attainment STRICTLY beats FIFO's, tokens are
bit-identical across all arms, and the chaos drill loses zero
requests. Records ``llama_decode_slo_attainment`` and
``llama_decode_mixed_tok_s``.

Arrival modes (both main and decode): ``--arrival closed`` (default —
every client re-submits as soon as its request finishes) or
``--arrival poisson --rate R`` — open-loop Poisson arrivals at R req/s,
the first slice of the trace-driven load story (ROADMAP item 5): the
generator does NOT slow down when the server does, so overload shows
up as shed/timeout counts (reported per run) instead of silently
stretched client think time.
"""
import argparse
import json
import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.models import zoo  # noqa: E402
from paddle_tpu import serving  # noqa: E402


def synth_feed(program, feed_names, batch, rng):
    """Random single-request feed shaped from the program's data vars
    (-1 dims become ``batch``; int vars get small non-negative ids)."""
    gb = program.global_block()
    feed = {}
    for name in feed_names:
        var = gb.var(name)
        shape = [batch if (d is None or d < 0) else d for d in var.shape]
        shape[0] = batch
        dtype = str(var.dtype)
        if "int" in dtype:
            feed[name] = rng.randint(0, 2, size=shape).astype(dtype)
        else:
            feed[name] = rng.randn(*shape).astype(dtype)
    return feed


# loss-op input slot that carries the model's per-row prediction head
_PRED_SLOTS = {"cross_entropy": "X", "softmax_with_cross_entropy":
               "Logits", "square_error_cost": "X"}


def row_fetch(program, fallback):
    """The per-row output to serve: the first loss op's prediction
    input ([rows, ...] — row independent, so batched vs single
    comparisons are exact). Falls back to the zoo fetch list when no
    known loss op exists — correctness is then NOT comparable (those
    fetches are batch-mean scalars) and the sweep is skipped."""
    for op in program.global_block().ops:
        slot = _PRED_SLOTS.get(op.type)
        if slot is not None:
            return [op.input(slot)[0]], True
    return fallback, False


def _setup(args):
    """Shared bench scaffolding: zoo model, inference program, fetch,
    initialized private scope, and one single-row feed per request."""
    # racecheck: ok(global-mutation) — bench CLI entrypoint: pins the
    # backend before any serving thread exists
    fluid.force_cpu()
    zp = zoo.build_zoo_program(args.model)
    infer = zp.main.clone(for_test=True)
    fetch, per_row = row_fetch(infer, zp.fetch_list)
    scope = fluid.Scope()
    startup_exe = fluid.Executor(fluid.CPUPlace())
    # racecheck: ok(global-mutation) — driver-thread setup before any
    # serving engine thread starts; the scope is bench-private
    with fluid.scope_guard(scope):
        startup_exe.run(zp.startup)
    rng = np.random.RandomState(0)
    feeds = [synth_feed(infer, zp.feed_names, 1, rng)
             for _ in range(args.requests)]
    return zp, infer, fetch, per_row, scope, feeds


def _drive_closed(eng, feeds, concurrency, timeout=60.0, repeats=3):
    """One closed-loop drive of ``feeds`` (cycled ``repeats`` times so
    the timed window dwarfs scheduler jitter) through ``eng``; returns
    requests/s."""
    wave = list(feeds) * repeats
    with ThreadPoolExecutor(concurrency) as pool:
        t0 = time.perf_counter()
        list(pool.map(lambda f: eng.infer(f, timeout=timeout), wave))
        dt = time.perf_counter() - t0
    return len(wave) / dt if dt > 0 else 0.0


def _opt_compare_classifier(args, eng_on, infer, zp, fetch, scope,
                            feeds):
    """Opt-on vs opt-off serving throughput (the measured-win record
    for the graph-rewrite pipeline). ``eng_on`` is the already-warm
    default engine; an identical engine with ``optimize=False`` serves
    the same program unrewritten. Both sides get two alternating
    closed-loop rounds and keep their best, so a CI scheduling stall
    on one round can't flip the comparison."""
    from paddle_tpu.analysis.optimize import DEFAULT_PASSES
    eng_off = serving.ServingEngine(
        infer, zp.feed_names, fetch, scope=scope,
        place=fluid.CPUPlace(), optimize=False,
        buckets=serving.BucketSpec(
            batch_sizes=_bucket_sizes(args.max_batch)),
        config=serving.ServingConfig(
            max_wait_ms=args.max_wait_ms,
            max_queue=max(2 * args.requests, 64)))
    try:
        eng_off.warmup()
        on_samples, off_samples = [], []
        for _ in range(5):       # alternating so drift hits both
            off_samples.append(_drive_closed(
                eng_off, feeds, args.concurrency))
            on_samples.append(_drive_closed(
                eng_on, feeds, args.concurrency))
        on_rps = float(np.median(on_samples))
        off_rps = float(np.median(off_samples))
        eng_off.assert_no_recompiles()
    finally:
        eng_off.close()
    opt_stats = (eng_on.stats().get("optimize") or {})
    return {
        "metric": f"{args.model}_serving_optimize_speedup",
        "value": round(on_rps / off_rps, 3) if off_rps else None,
        "unit": "x",
        "opt_on_rps": round(on_rps, 1),
        "opt_off_rps": round(off_rps, 1),
        "optimize_passes": ",".join(DEFAULT_PASSES),
        "rewrites": {k: opt_stats.get(k) for k in
                     ("folded", "fused", "merged", "removed")},
        "backend": "cpu",
    }


def _bucket_sizes(max_batch):
    sizes = []
    b = 1
    while b < max_batch:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch)
    return tuple(sizes)


def poisson_arrivals(n, rate, rng):
    """Absolute arrival offsets (seconds) for ``n`` open-loop requests
    at ``rate`` req/s — exponential inter-arrival gaps, the memoryless
    arrival process real traffic is usually modeled by."""
    if rate <= 0:
        raise ValueError(f"--rate must be > 0, got {rate}")
    return np.cumsum(rng.exponential(1.0 / rate, size=n))


def synth_trace(n, rate, rng, burst_factor=4.0, burst_len=16,
                cycle=64, tail_sigma=0.8):
    """Synthetic bursty, heavy-tailed arrival trace (ROADMAP item 5):
    ``burst_len`` of every ``cycle`` requests arrive at
    ``burst_factor`` x the base rate (the diurnal-spike shape), and
    every inter-arrival gap is jittered by a lognormal factor
    (sigma ``tail_sigma``) — heavy-tailed gaps, so quiet stretches and
    pile-ups both happen, unlike pure Poisson. Returns (offsets,
    burst_mask); mean arrival rate stays ≈ ``rate`` (the lognormal's
    mean is divided back out)."""
    if rate <= 0:
        raise ValueError(f"trace rate must be > 0, got {rate}")
    gaps = np.empty(n)
    burst = np.zeros(n, dtype=bool)
    correction = np.exp(tail_sigma ** 2 / 2.0)
    for i in range(n):
        in_burst = (i % cycle) < burst_len
        burst[i] = in_burst
        r = rate * (burst_factor if in_burst else 1.0)
        gaps[i] = rng.exponential(1.0 / r) \
            * rng.lognormal(0.0, tail_sigma) / correction
    return np.cumsum(gaps), burst


def load_rich_trace(path):
    """A recorded trace (docs/SERVING.md "Trace-file schema"): JSON —
    either a bare list of absolute arrival offsets (seconds), or a
    dict with ``offsets`` plus optional per-request columns:

    - ``class``:  priority tier per request ("interactive" /
      "standard" / "batch")
    - ``bucket``: prompt-length bucket per request (int)
    - ``phase``:  segment label per request ("diurnal" / "flash" ...);
      ``"flash"`` rows double as the burst mask
    - ``burst``:  explicit bool burst mask (overrides ``phase``)

    Returns a dict with ``offsets`` (float64 array), ``burst`` (bool
    array) and — None when the file doesn't carry them — ``classes``,
    ``buckets``, ``phases``. Every present column must match
    ``offsets`` in length."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict):
        data = {"offsets": data}
    offsets = np.asarray(data["offsets"], dtype=np.float64)
    n = len(offsets)
    phases = data.get("phase")
    if "burst" in data:
        burst = np.asarray(data["burst"], dtype=bool)
    elif phases is not None:
        burst = np.asarray([p == "flash" for p in phases], dtype=bool)
    else:
        burst = np.zeros(n, dtype=bool)
    classes = data.get("class")
    buckets = data.get("bucket")
    buckets = None if buckets is None else [int(b) for b in buckets]
    for col_name, col in (("class", classes), ("bucket", buckets),
                          ("phase", phases), ("burst", burst)):
        if col is not None and len(col) != n:
            raise ValueError(
                f"trace column {col_name!r} has {len(col)} entries "
                f"for {n} offsets — every per-request column must "
                "align with 'offsets'")
    return {"offsets": offsets, "burst": burst, "classes": classes,
            "buckets": buckets, "phases": phases}


def load_trace(path):
    """Back-compat view of :func:`load_rich_trace`: (offsets,
    burst_mask) — what the plain ``--arrival trace`` ladder needs."""
    rich = load_rich_trace(path)
    return rich["offsets"], rich["burst"]


def gen_overload_trace(n, rate, rng, buckets=(8, 16), flash_factor=4.0,
                       diurnal_cycles=2.0, flash_start=0.55,
                       flash_len=0.15, mix=(0.2, 0.45, 0.35)):
    """Deterministic overload trace (the --overload referee's input):
    ``n`` arrivals whose instantaneous rate follows ``diurnal_cycles``
    sinusoidal day/night cycles around ``rate`` (0.4x troughs, 1.0x
    peaks), with one contiguous FLASH CROWD — the ``flash_len``
    fraction of the trace starting at the ``flash_start`` fraction
    arrives at ``flash_factor`` x the diurnal rate. Request classes
    are drawn from ``mix`` = (interactive, standard, batch) fractions,
    and the prompt-bucket skew DRIFTS long across the trace (20% long
    at the start, 80% at the end) so bucketed prefill sees a changing
    shape mix, not a stationary one. Same shape as
    :func:`load_rich_trace`'s return."""
    if rate <= 0:
        raise ValueError(f"trace rate must be > 0, got {rate}")
    names = ("interactive", "standard", "batch")
    cum = np.cumsum(np.asarray(mix, dtype=np.float64))
    if abs(cum[-1] - 1.0) > 1e-9:
        raise ValueError(f"class mix must sum to 1, got {mix}")
    gaps = np.empty(n)
    bucket_col = []
    classes = []
    phases = []
    for i in range(n):
        frac = i / max(1, n - 1)
        m = 0.7 + 0.3 * np.sin(2.0 * np.pi * diurnal_cycles * frac)
        in_flash = flash_start <= frac < flash_start + flash_len
        if in_flash:
            m *= flash_factor
        gaps[i] = rng.exponential(1.0 / (rate * m))
        phases.append("flash" if in_flash else "diurnal")
        classes.append(names[int(np.searchsorted(cum, rng.uniform(),
                                                 side="left"))])
        p_long = 0.2 + 0.6 * frac       # bucket-skew drift
        bucket_col.append(int(buckets[-1] if rng.uniform() < p_long
                              else buckets[0]))
    return {"offsets": np.cumsum(gaps),
            "burst": np.asarray([p == "flash" for p in phases]),
            "classes": classes, "buckets": bucket_col,
            "phases": phases}


def open_loop_drive(submit, items, offsets, result_timeout=120.0):
    """Submit ``items`` at the given absolute arrival offsets
    regardless of server state (open loop), then collect every handle.
    Returns (outcomes dict, results list aligned with items — None
    where the request was shed or failed, wall seconds, per-item
    client-side latency list — None where unserved). ``submit``
    returns a handle with ``.done()``/``.result(timeout)``; typed
    serving errors count as shed / timeout / error, never raise.

    Latencies are captured by a collector thread sampling ``done()``,
    so a request that finished long before collection is timestamped
    when it SETTLED, not when the tail of the run got around to it —
    p99-under-burst depends on that."""
    import threading
    from paddle_tpu.serving import (QueueFullError, RequestTimeoutError,
                                    ServingError)
    counts = {"ok": 0, "shed": 0, "timeout": 0, "error": 0}
    handles = [None] * len(items)
    submitted_at = [None] * len(items)
    settled_at = {}
    stop = threading.Event()

    def collect():
        while not stop.is_set():
            for i, h in enumerate(handles):
                if h is not None and i not in settled_at and h.done():
                    settled_at[i] = time.perf_counter()
            stop.wait(0.001)

    collector = threading.Thread(target=collect, daemon=True)
    collector.start()
    t0 = time.perf_counter()
    for i, (item, off) in enumerate(zip(items, offsets)):
        delay = t0 + off - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        try:
            submitted_at[i] = time.perf_counter()
            handles[i] = submit(item)
        except QueueFullError:
            counts["shed"] += 1
        except ServingError:
            counts["error"] += 1
    results = [None] * len(items)
    for i, h in enumerate(handles):
        if h is None:
            continue
        try:
            results[i] = h.result(result_timeout)
            counts["ok"] += 1
        except RequestTimeoutError:
            counts["timeout"] += 1
        except Exception:               # noqa: BLE001 — tallied
            counts["error"] += 1
        settled_at.setdefault(i, time.perf_counter())
    wall = time.perf_counter() - t0
    stop.set()
    collector.join(1.0)
    latencies = [None] * len(items)
    for i in range(len(items)):
        if results[i] is not None and submitted_at[i] is not None \
                and i in settled_at:
            latencies[i] = settled_at[i] - submitted_at[i]
    return counts, results, wall, latencies


def trace_ladder(submit, items, args, rng):
    """Max-sustainable-QPS search: replay the bursty trace at a ladder
    of base rates (``--rate`` x growth^k); the highest rung with ZERO
    shed/timeout/error is the sustained capacity, and its p99 over
    burst-phase requests is the p99-under-burst number. Stops at the
    first dirty rung (open loop: past the knee, everything sheds)."""
    report = {"rungs": [], "max_sustained_qps": None,
              "p99_burst_ms": None}
    rate = args.rate
    for _ in range(args.ladder_rungs):
        if args.trace_file:
            base, burst = load_trace(args.trace_file)
            # replaying a recorded trace faster = scaling time down
            offsets = base * (args.rate / rate)
        else:
            offsets, burst = synth_trace(
                len(items), rate, rng,
                burst_factor=args.burst_factor)
        counts, _results, wall, lats = open_loop_drive(
            submit, items, offsets,
            result_timeout=args.request_timeout + 30.0)
        achieved = counts["ok"] / wall if wall > 0 else 0.0
        burst_lats = [l for l, b in zip(lats, burst)
                      if l is not None and b]
        p99b = (round(float(np.percentile(burst_lats, 99.0)) * 1e3, 2)
                if burst_lats else None)
        clean = (counts["shed"] == 0 and counts["timeout"] == 0
                 and counts["error"] == 0)
        report["rungs"].append({
            "base_rate": round(rate, 1),
            "achieved_qps": round(achieved, 1),
            "counts": counts, "p99_burst_ms": p99b,
            "clean": clean})
        if not clean:
            break
        report["max_sustained_qps"] = round(achieved, 1)
        report["p99_burst_ms"] = p99b
        rate *= args.ladder_growth
    return report


def _decode_model(args):
    """Tiny llama config + initialized serving scope + prompts (+ the
    fused-generator baseline programs, one per prompt bucket; the
    FIRST one's startup initializes the shared serving scope)."""
    from paddle_tpu.models.llama import (LlamaConfig,
                                         build_llama_generator)
    # racecheck: ok(global-mutation) — bench CLI entrypoint: pins the
    # backend before any serving thread exists
    fluid.force_cpu()
    cfg = LlamaConfig(vocab_size=128, dim=32, n_layers=2, n_heads=4,
                      n_kv_heads=2, ffn_hidden=64, dtype="float32")
    buckets = (8, 16)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    gen = {}
    for j, L in enumerate(buckets):
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            ptok = fluid.layers.data(name="ptok", shape=[1, L],
                                     dtype="int64",
                                     append_batch_size=False)
            out = build_llama_generator(cfg, ptok,
                                        max_new_tokens=args.max_new)
        gen[L] = (prog, out)
        if j == 0:
            # racecheck: ok(global-mutation) — driver-thread setup,
            # no serving threads yet; bench-private scope
            with fluid.scope_guard(scope):
                exe.run(startup)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, cfg.vocab_size,
                           (int(rng.choice(buckets)),)).astype(np.int64)
               for _ in range(args.requests)]
    return cfg, buckets, scope, exe, gen, prompts


def _decode_config(args, buckets):
    from paddle_tpu import serving
    max_queue = (max(2 * args.requests, 64)
                 if getattr(args, "max_queue", None) is None
                 else args.max_queue)
    return serving.DecodeConfig(
        max_batch=args.max_batch, prompt_buckets=buckets,
        max_new_tokens=args.max_new, page_size=8,
        decode_block=args.decode_block,
        prefill_batch=args.prefill_batch,
        max_queue=max_queue,
        default_timeout_s=120.0)


def decode_main(args):
    """--decode: continuous batching vs sequential per-request
    generation on a tiny-config llama."""
    from paddle_tpu.models.llama import copy_weights_as_draft
    from paddle_tpu import serving

    cfg, buckets, scope, exe, gen, prompts = _decode_model(args)
    max_new = args.max_new

    baseline_tok_s = None
    baseline_out = None
    if not args.skip_baseline:
        # racecheck: ok(global-mutation) — single-threaded baseline
        # measurement in the driver; bench-private scope
        with fluid.scope_guard(scope):
            for L in buckets:           # compile outside the clock
                # racecheck: ok(run-without-scope) — inside the
                # bench-private scope_guard, single-threaded
                exe.run(gen[L][0],
                        feed={"ptok": np.zeros((1, L), np.int64)},
                        fetch_list=[gen[L][1]], mode="test")
            t0 = time.perf_counter()
            baseline_out = []
            for p in prompts:
                # racecheck: ok(run-without-scope) — ditto: private
                # scope_guard, single-threaded baseline
                full = np.asarray(exe.run(
                    gen[len(p)][0], feed={"ptok": p[None]},
                    fetch_list=[gen[len(p)][1]], mode="test")[0])
                baseline_out.append(full[0, len(p):])
            base_s = time.perf_counter() - t0
        baseline_tok_s = args.requests * max_new / base_s

    draft_cfg = None
    if args.spec:
        # racecheck: ok(global-mutation) — driver-thread setup before
        # the decode engine starts; bench-private scope
        with fluid.scope_guard(scope):
            copy_weights_as_draft(scope)
        draft_cfg = cfg
    eng = serving.DecodeEngine(
        cfg, scope=scope, place=fluid.CPUPlace(), draft_cfg=draft_cfg,
        config=_decode_config(args, buckets))
    failures = []
    arrival_counts = None
    try:
        warm = eng.warmup()
        rng_a = np.random.RandomState(7)
        if args.arrival == "poisson":
            arrival_counts, served, eng_s, _lats = open_loop_drive(
                lambda p: eng.submit(p, timeout=args.request_timeout),
                prompts,
                poisson_arrivals(len(prompts), args.rate, rng_a),
                result_timeout=120.0)
            n_tokens = sum(len(r) for r in served if r is not None)
        else:
            t0 = time.perf_counter()
            reqs = [eng.submit(p, timeout=120.0) for p in prompts]
            served = [r.result(120.0) for r in reqs]
            eng_s = time.perf_counter() - t0
            n_tokens = sum(len(r) for r in served)
        engine_tok_s = n_tokens / eng_s if eng_s > 0 else 0.0
        try:
            eng.assert_no_recompiles()
            recompiled = False
        except AssertionError as exc:
            recompiled = True
            failures.append(str(exc))
        stats = eng.stats()
    finally:
        eng.close()

    # opt-on vs opt-off decode throughput (--opt-compare, closed loop
    # only): a second engine serves the same scope with the rewrite
    # pipeline disabled; both get a fresh closed-loop drive and the
    # better of two rounds each, alternating
    opt_record = None
    if getattr(args, "opt_compare", False) and args.arrival == "closed":
        from paddle_tpu.analysis.optimize import DEFAULT_PASSES

        def _tok_s(engine):
            t0 = time.perf_counter()
            rs = [engine.submit(p, timeout=120.0) for p in prompts]
            toks = sum(len(r.result(120.0)) for r in rs)
            dt = time.perf_counter() - t0
            return toks / dt if dt > 0 else 0.0

        on_tok_s, off_tok_s = engine_tok_s, 0.0
        for flag in (False, True, False, True):
            e2 = serving.DecodeEngine(
                cfg, scope=scope, place=fluid.CPUPlace(),
                draft_cfg=draft_cfg, optimize=flag,
                config=_decode_config(args, buckets))
            try:
                e2.warmup()
                v = _tok_s(e2)
            finally:
                e2.close()
            if flag:
                on_tok_s = max(on_tok_s, v)
            else:
                off_tok_s = max(off_tok_s, v)
        opt_record = {
            "metric": "llama_decode_serving_optimize_speedup",
            "value": (round(on_tok_s / off_tok_s, 3)
                      if off_tok_s else None),
            "unit": "x",
            "opt_on_tok_s": round(on_tok_s, 1),
            "opt_off_tok_s": round(off_tok_s, 1),
            "optimize_passes": ",".join(DEFAULT_PASSES),
            "backend": "cpu", "max_batch": args.max_batch,
        }

    mismatches = None
    if baseline_out is not None:
        mismatches = sum(
            1 for ref, got in zip(baseline_out, served)
            if got is not None and not np.array_equal(ref, got))
        if mismatches:
            failures.append(
                f"{mismatches} request(s) diverged from the "
                "sequential fused-generator baseline")
    if engine_tok_s <= 0:
        failures.append("engine produced no tokens")
    speedup = (engine_tok_s / baseline_tok_s
               if baseline_tok_s else None)
    if args.assert_speedup is not None and speedup is not None \
            and speedup < args.assert_speedup:
        failures.append(
            f"decode speedup {speedup:.2f}x below the "
            f"--assert-speedup {args.assert_speedup}x floor")

    report = {
        "mode": "decode",
        "requests": args.requests,
        "max_batch": args.max_batch,
        "max_new": max_new,
        "decode_block": args.decode_block,
        "spec": bool(args.spec),
        "arrival": args.arrival,
        "warmup": warm,
        "baseline_tok_s": (None if baseline_tok_s is None
                           else round(baseline_tok_s, 1)),
        "engine_tok_s": round(engine_tok_s, 1),
        "speedup": None if speedup is None else round(speedup, 2),
        "mismatched_requests": mismatches,
        "recompiled": recompiled,
        "arrival_counts": arrival_counts,
        "bench_record": {
            "metric": "llama_decode_serving_tok_s",
            "value": round(engine_tok_s, 1), "unit": "tok/s",
            "backend": "cpu", "max_batch": args.max_batch,
            "spec": bool(args.spec),
            "see_also_published": {
                "llama8b_int8_serving_tok_s": 4963.7}},
        "bench_record_optimize": opt_record,
        "serving_stats": stats,
        "failures": failures,
    }
    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if args.json:
        print(text)
    else:
        shed = ("" if arrival_counts is None else
                f", shed {arrival_counts['shed']} / timeout "
                f"{arrival_counts['timeout']}")
        opt_line = ""
        if opt_record is not None:
            opt_line = (f", opt {opt_record['opt_on_tok_s']} vs "
                        f"{opt_record['opt_off_tok_s']} tok/s "
                        f"({opt_record['value']}x)")
        print(f"servebench --decode: baseline "
              f"{report['baseline_tok_s']} tok/s, engine "
              f"{report['engine_tok_s']} tok/s "
              f"({report['speedup']}x), ttft p95 "
              f"{stats['ttft_s']['p95_ms']} ms, tpot p95 "
              f"{stats['tpot_s']['p95_ms']} ms, "
              f"{mismatches} mismatches, "
              f"{warm['compiles']} warmup compiles, "
              f"{'RECOMPILED' if recompiled else '0 recompiles'}"
              f"{shed}{opt_line}")
    if failures:
        for f in failures:
            print(f"servebench --decode: FAILED — {f}",
                  file=sys.stderr)
        return 1
    return 0


# --slo trace shape: longs flood the queue FIRST, then shorts with a
# tight TTFT target arrive behind them. All requests are enqueued
# before the engine starts, so the measured difference is pure
# scheduling order — FIFO must burn through every long before the
# first short prefills (hundreds of decode steps of queue wait),
# while EDF admits the shorts immediately (a couple of dispatches).
# The interactive TTFT target is CALIBRATED, not absolute: an unscored
# FIFO run measures the shorts' queue-wait TTFT on this machine, and
# the scored target is a quarter of it — so FIFO violates with 4x
# margin and the SLO scheduler (measured ~15x lower TTFT) meets with
# comparable margin, on any CPU speed.
_SLO_LONGS, _SLO_SHORTS = 16, 6
_SLO_LONG_NEW, _SLO_SHORT_NEW = 96, 8
_SLO_TTFT_FLOOR_S = 0.02      # never score below dispatch noise


def _slo_classes(ttft_interactive_s):
    interactive = serving.SLOClass(
        ttft_target_s=ttft_interactive_s, tpot_target_s=1.0,
        name="interactive")
    batch = serving.SLOClass(ttft_target_s=30.0, tpot_target_s=5.0,
                             name="batch")
    return interactive, batch


def _slo_trace(cfg):
    rng = np.random.RandomState(11)
    longs = [rng.randint(0, cfg.vocab_size, (16,)).astype(np.int64)
             for _ in range(_SLO_LONGS)]
    shorts = [rng.randint(0, cfg.vocab_size, (8,)).astype(np.int64)
              for _ in range(_SLO_SHORTS)]
    return longs, shorts


def _slo_decode_config(scheduler):
    # 2 slots + small decode blocks keep admission contended: queue
    # order decides everything
    return serving.DecodeConfig(
        max_batch=2, prompt_buckets=(8, 16),
        max_new_tokens=_SLO_LONG_NEW, page_size=8,
        decode_block=8, prefill_batch=2, max_queue=256,
        default_timeout_s=240.0, scheduler=scheduler)


def _ttft_attainment(stats):
    met = stats["slo_ttft_met"]
    total = met + stats["slo_ttft_violated"]
    return round(met / total, 4) if total else None


def _slo_arm(cfg, scope, scheduler, longs, shorts, failures, label,
             classes):
    """One single-engine run of the mixed trace under ``scheduler``.
    Everything is enqueued before start() so admission order is the
    scheduler's choice alone."""
    interactive, batch = classes
    eng = serving.DecodeEngine(
        cfg, scope=scope, place=fluid.CPUPlace(),
        config=_slo_decode_config(scheduler), auto_start=False)
    try:
        eng.warmup()
        handles = [eng.submit(p, max_new=_SLO_LONG_NEW, timeout=240.0,
                              slo=batch) for p in longs]
        handles += [eng.submit(p, max_new=_SLO_SHORT_NEW, timeout=240.0,
                               slo=interactive) for p in shorts]
        t0 = time.perf_counter()
        eng.start()
        outs = [np.asarray(h.result(240.0)) for h in handles]
        wall = time.perf_counter() - t0
        try:
            eng.assert_no_recompiles()
        except AssertionError as exc:
            failures.append(f"{label}: {exc}")
        stats = eng.stats()
    finally:
        eng.close()
    n_tok = sum(len(o) for o in outs)
    return {"outs": outs,
            "tok_s": round(n_tok / wall, 1) if wall > 0 else 0.0,
            "ttft_attainment": _ttft_attainment(stats),
            "stats": stats}


def _slo_disagg_arm(cfg, scope, longs, shorts, ref_outs, failures,
                    classes):
    """The same mixed trace over a disaggregated 2-prefill/2-decode
    pool via Router.generate, then the serving_handoff_drop chaos
    drill on the SAME pool: the prefill replica dies holding the
    finished KV blob, and the router must re-prefill on the survivor
    with zero lost requests."""
    from paddle_tpu.cluster import ReplicaPool, Router
    from paddle_tpu.resilience import faultinject

    interactive, batch = classes
    pool = ReplicaPool(
        lambda: serving.DecodeEngine(
            cfg, scope=scope, place=fluid.CPUPlace(),
            config=_slo_decode_config("slo")),
        replicas=4, warmup=True)
    for i, rep in enumerate(pool.replicas()):
        rep.role = "prefill" if i < 2 else "decode"
    router = Router(pool)
    work = ([(p, _SLO_LONG_NEW, batch) for p in longs]
            + [(p, _SLO_SHORT_NEW, interactive) for p in shorts])

    def one(item):
        p, max_new, slo = item
        return np.asarray(router.generate(p, max_new=max_new,
                                          timeout=240.0, slo=slo))

    try:
        with ThreadPoolExecutor(max_workers=8) as tp:
            t0 = time.perf_counter()
            outs = list(tp.map(one, work))
            wall = time.perf_counter() - t0
        mism = sum(1 for a, b in zip(ref_outs, outs)
                   if not np.array_equal(a, b))
        if mism:
            failures.append(f"disaggregated: {mism} request(s) "
                            "diverged from the single-engine tokens "
                            "(must be bit-exact)")
        snap = pool.stats()
        if not snap["handoffs_total"]:
            failures.append("disaggregated: no handoffs happened — "
                            "the role split did not engage")

        # chaos: drop the first two handoffs mid-flight
        chaos_work = work[:2] + work[-2:]
        chaos_ref = ref_outs[:2] + ref_outs[-2:]
        faultinject.arm("serving_handoff_drop", at=0, times=2)
        try:
            with ThreadPoolExecutor(max_workers=4) as tp:
                chaos_outs = list(tp.map(one, chaos_work))
        finally:
            faultinject.disarm("serving_handoff_drop")
        lost = sum(1 for a, b in zip(chaos_ref, chaos_outs)
                   if not np.array_equal(a, b))
        if lost:
            failures.append(f"handoff chaos: {lost} request(s) lost "
                            "or diverged after the drop")
        snap = pool.stats()
        if not snap["handoff_redrives_total"]:
            failures.append("handoff chaos: the armed drop never "
                            "fired (redrive counter is zero)")
        n_tok = sum(len(o) for o in outs)
        cluster = snap["cluster"] or {}
        return {"tok_s": round(n_tok / wall, 1) if wall > 0 else 0.0,
                "ttft_attainment": (_ttft_attainment(cluster)
                                    if "slo_ttft_met" in cluster
                                    else None),
                "mismatched_requests": mism,
                "chaos_lost": lost,
                "handoffs_total": snap["handoffs_total"],
                "handoff_redrives_total":
                    snap["handoff_redrives_total"]}
    finally:
        router.close()
        pool.close()


def slo_main(args):
    """--decode --slo: SLO-attainment benchmark on a mixed short/long
    interference trace — FIFO vs EDF (SLO scheduler) vs disaggregated
    prefill/decode, plus the serving_handoff_drop chaos drill. Gated:
    the SLO scheduler's TTFT attainment must be STRICTLY better than
    FIFO's on the same trace, tokens must stay bit-identical across
    all three arms, and the chaos drill must lose zero requests."""
    cfg, buckets, scope, exe, gen, prompts = _decode_model(args)
    del buckets, exe, gen, prompts      # scheduling bench builds its own
    longs, shorts = _slo_trace(cfg)
    failures = []

    # calibration: the same trace, FIFO, targets too huge to violate —
    # its interactive-class TTFT window measures what FIFO queue wait
    # costs the shorts on THIS machine
    cal = _slo_arm(cfg, scope, "fifo", longs, shorts, failures,
                   "calibration arm", _slo_classes(1e6))
    cal_win = cal["stats"].get("interactive.ttft_s") or {}
    cal_p50_s = (cal_win.get("p50_ms") or 0.0) / 1e3
    ttft_target_s = max(_SLO_TTFT_FLOOR_S, cal_p50_s / 4.0)
    classes = _slo_classes(ttft_target_s)

    fifo = _slo_arm(cfg, scope, "fifo", longs, shorts, failures,
                    "fifo arm", classes)
    # --slo-force-fifo runs the "slo" arm on the FIFO scheduler too —
    # the attainment gate below must then FAIL (selfcheck stage 13's
    # toothless-gate check)
    slo_sched = "fifo" if args.slo_force_fifo else "slo"
    slo = _slo_arm(cfg, scope, slo_sched, longs, shorts, failures,
                   "slo arm", classes)

    mism = sum(1 for a, b in zip(fifo["outs"], slo["outs"])
               if not np.array_equal(a, b))
    if mism:
        failures.append(f"{mism} request(s) decoded different tokens "
                        "under FIFO vs SLO scheduling (admission "
                        "order must never change greedy outputs)")
    fifo_att, slo_att = fifo["ttft_attainment"], slo["ttft_attainment"]
    if fifo_att is None or slo_att is None:
        failures.append("TTFT attainment was not scored (SLO counters "
                        "empty) — every request carries an SLO class")
    elif slo_att <= fifo_att:
        failures.append(
            f"SLO-scheduler TTFT attainment {slo_att} is not strictly "
            f"better than FIFO's {fifo_att} on the interference trace")

    mism_cal = sum(1 for a, b in zip(cal["outs"], fifo["outs"])
                   if not np.array_equal(a, b))
    if mism_cal:
        failures.append(f"{mism_cal} request(s) decoded different "
                        "tokens across runs on the SAME scheduler")

    disagg = (None if args.skip_disagg else
              _slo_disagg_arm(cfg, scope, longs, shorts, fifo["outs"],
                              failures, classes))

    fifo_stats, slo_stats = fifo.pop("stats"), slo.pop("stats")
    fifo.pop("outs"), slo.pop("outs")
    report = {
        "mode": "decode-slo",
        "trace": {"longs": _SLO_LONGS, "long_new": _SLO_LONG_NEW,
                  "shorts": _SLO_SHORTS, "short_new": _SLO_SHORT_NEW,
                  "calibrated_fifo_ttft_p50_s": round(cal_p50_s, 4),
                  "interactive_ttft_s": round(ttft_target_s, 4)},
        "fifo": fifo, "slo": slo, "disaggregated": disagg,
        "slo_counters": {
            k: slo_stats[k]
            for k in ("slo_ttft_met", "slo_ttft_violated",
                      "slo_tpot_met", "slo_tpot_violated",
                      "chunk_prefill_total")},
        "interactive_ttft_ms": {
            "fifo": fifo_stats.get("interactive.ttft_s"),
            "slo": slo_stats.get("interactive.ttft_s")},
        "bench_records": [
            {"metric": "llama_decode_slo_attainment", "value": slo_att,
             "unit": "frac", "fifo_attainment": fifo_att,
             "disagg_attainment":
                 None if disagg is None else disagg["ttft_attainment"],
             "scheduler": slo_sched, "backend": "cpu"},
            {"metric": "llama_decode_mixed_tok_s",
             "value": slo["tok_s"], "unit": "tok/s",
             "fifo_tok_s": fifo["tok_s"],
             "disagg_tok_s":
                 None if disagg is None else disagg["tok_s"],
             "backend": "cpu"}],
        "failures": failures,
    }
    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if args.json:
        print(text)
    else:
        d = ("skipped" if disagg is None else
             f"{disagg['ttft_attainment']} att / {disagg['tok_s']} "
             f"tok/s, {disagg['handoffs_total']} handoffs, "
             f"{disagg['handoff_redrives_total']} chaos redrives")
        print(f"servebench --decode --slo: ttft attainment fifo "
              f"{fifo_att} vs slo {slo_att}, mixed {slo['tok_s']} "
              f"tok/s (fifo {fifo['tok_s']}), disagg: {d}")
    if failures:
        for f in failures:
            print(f"servebench --decode --slo: FAILED — {f}",
                  file=sys.stderr)
        return 1
    return 0


def chaos_main(args):
    """--chaos: fault-injection drill over the serving engine."""
    from paddle_tpu.resilience import faultinject
    from paddle_tpu.resilience.retry import (RetryPolicy,
                                             TransientDeviceError)
    from paddle_tpu.serving import ServingError

    zp, infer, fetch, _per_row, scope, feeds = _setup(args)
    eng = serving.ServingEngine(
        infer, zp.feed_names, fetch, scope=scope,
        place=fluid.CPUPlace(),
        buckets=serving.BucketSpec(
            batch_sizes=_bucket_sizes(args.max_batch)),
        config=serving.ServingConfig(
            max_wait_ms=args.max_wait_ms,
            max_queue=max(2 * args.requests, 64),
            breaker_threshold=3, breaker_cooldown_s=0.3,
            # no dispatch retries: every injected fault is a terminal
            # batch failure, so the breaker cycle is deterministic
            retry_policy=RetryPolicy(max_attempts=1)))

    def drive(wave, timeout=30.0):
        """Run one request wave; every submission must TERMINATE.
        Returns (counts-by-outcome, wall seconds). 'lost' counts
        untyped failures — the contract violation."""
        counts = {"ok": 0, "lost": 0}

        def one(f):
            try:
                eng.infer(f, timeout=timeout)
                return "ok"
            except (ServingError, TransientDeviceError) as exc:
                return type(exc).__name__
            except Exception as exc:            # noqa: BLE001 — tallied
                return f"lost:{type(exc).__name__}"
        with ThreadPoolExecutor(args.concurrency) as pool:
            t0 = time.perf_counter()
            for outcome in pool.map(one, wave):
                if outcome.startswith("lost:"):
                    counts["lost"] += 1
                counts[outcome] = counts.get(outcome, 0) + 1
            return counts, time.perf_counter() - t0

    failures = []
    try:
        warm = eng.warmup()

        # phase 1 — steady state: all success, zero recompiles
        steady, steady_s = drive(feeds)
        if steady["ok"] != len(feeds):
            failures.append(f"steady-state failures: {steady}")

        # phase 2 — fault window: the breaker must open; nothing lost
        faultinject.arm("serving_device_error", at=0, times=6)
        chaos, _ = drive(feeds)
        faultinject.disarm("serving_device_error")   # fault clears
        mid = eng.stats()
        if mid["breaker_open_total"] < 1:
            failures.append("breaker never opened under injected faults")

        # phase 3 — recovery: cooldown, half-open probe closes, full
        # throughput returns, still zero recompiles
        time.sleep(0.35)
        recovery, rec_s = drive(feeds)
        post = eng.stats()
        if recovery["ok"] != len(feeds):
            failures.append(f"post-recovery failures: {recovery}")
        if post["breaker"]["state"] != "closed":
            failures.append(f"breaker stuck {post['breaker']['state']}")
        try:
            eng.assert_no_recompiles()
        except AssertionError as exc:
            failures.append(str(exc))

        # phase 4 — graceful drain: every queued request completes
        drain_reqs = [eng.submit(f, timeout=30.0) for f in feeds[:8]]
        eng.close(drain=True)
        drained = 0
        for req in drain_reqs:
            try:
                req.result(timeout=1.0)
                drained += 1
            except ServingError:
                pass
        if drained != len(drain_reqs):
            failures.append(
                f"drain completed {drained}/{len(drain_reqs)} requests")
    finally:
        faultinject.disarm()
        eng.close()

    lost = steady["lost"] + chaos["lost"] + recovery["lost"]
    if lost:
        failures.append(f"{lost} request(s) lost (untyped failure)")
    report = {
        "mode": "chaos",
        "model": args.model,
        "requests_per_wave": len(feeds),
        "warmup": warm,
        "steady": steady,
        "chaos": chaos,
        "recovery": recovery,
        "recovery_rps": round(len(feeds) / rec_s, 1),
        "steady_rps": round(len(feeds) / steady_s, 1),
        "breaker_open_total": post["breaker_open_total"],
        "breaker_shed_total": post["breaker_shed_total"],
        "breaker_probe_total": post["breaker_probe_total"],
        "drained": drained,
        "lost": lost,
        "failures": failures,
    }
    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if args.json:
        print(text)
    else:
        print(f"servebench --chaos {args.model}: lost {lost}, breaker "
              f"opened {post['breaker_open_total']}x / shed "
              f"{post['breaker_shed_total']}, recovery "
              f"{report['recovery_rps']} req/s, drained {drained}/8, "
              f"{len(failures)} failure(s)")
    if failures:
        for f in failures:
            print(f"servebench --chaos: FAILED — {f}", file=sys.stderr)
        return 1
    return 0


def _classifier_factory(args, infer, zp, fetch, scope):
    """Engine factory for the pool: identical engines over one
    read-only parameter scope, each with its own worker + compile
    cache. ``--max-queue`` pins the per-engine admission bound (trace
    mode needs a production-like fixed bound — a queue scaled to the
    request count can never exhibit the shed knee)."""
    max_queue = (max(2 * args.requests, 64) if args.max_queue is None
                 else args.max_queue)

    def factory():
        return serving.ServingEngine(
            infer, zp.feed_names, fetch, scope=scope,
            place=fluid.CPUPlace(),
            buckets=serving.BucketSpec(
                batch_sizes=_bucket_sizes(args.max_batch)),
            config=serving.ServingConfig(
                max_wait_ms=args.max_wait_ms,
                max_queue=max_queue))
    return factory


def _closed_loop(infer_fn, items, concurrency, timeout=60.0):
    """Closed-loop drive: ``concurrency`` clients, each re-submitting
    as soon as its request finishes. Returns (results, wall_s)."""
    with ThreadPoolExecutor(concurrency) as pool:
        t0 = time.perf_counter()
        out = list(pool.map(lambda it: infer_fn(it, timeout=timeout),
                            items))
        return out, time.perf_counter() - t0


def _burst_goodput(submit, items, offsets, timeout):
    """One overload-trace drive; returns (ok, shed+timeout+error,
    goodput req/s)."""
    counts, _res, wall, _lats = open_loop_drive(
        submit, items, offsets, result_timeout=timeout + 30.0)
    refused = counts["shed"] + counts["timeout"] + counts["error"]
    return counts["ok"], refused, (counts["ok"] / wall if wall else 0.0)


def cluster_main(args):
    """--cluster N: replica-pool vs ONE engine on the same load —
    closed-loop throughput AND goodput under a bursty overload trace
    (the pool's queues absorb bursts a single engine must shed) —
    plus (--rolling-restart) a zero-downtime restart under sustained
    mixed traffic. The acceptance drill for the cluster subsystem
    (docs/SERVING.md "Running a replica pool")."""
    import argparse as _argparse
    import threading
    from paddle_tpu import cluster
    from paddle_tpu.serving import ServingError

    zp, infer, fetch, per_row, scope, feeds = _setup(args)
    factory = _classifier_factory(args, infer, zp, fetch, scope)
    failures = []

    # ---- reference: ONE engine, same concurrency, same feeds ---------
    eng = factory()
    try:
        eng.warmup()
        single_out, single_s = _closed_loop(eng.infer, feeds,
                                            args.concurrency)
    finally:
        eng.close()
    single_rps = len(feeds) / single_s

    # ---- burst-overload goodput: same offered load, 1 vs N -----------
    # bursts at 8x the sustained rate overflow one engine's bounded
    # queue; the pool's N queues absorb them — the capacity win that
    # holds on ANY host (a 1-core CI box cannot show a parallel-compute
    # win, so the gate lives here; host_cores is recorded)
    bargs = _argparse.Namespace(**vars(args))
    bargs.max_queue = 32
    bfactory = _classifier_factory(bargs, infer, zp, fetch, scope)
    rng_b = np.random.RandomState(13)
    n_over = max(192, args.requests)
    over_feeds = (feeds * ((n_over + len(feeds) - 1)
                           // len(feeds)))[:n_over]
    offsets, _burst = synth_trace(n_over, max(single_rps, 200.0),
                                  rng_b, burst_factor=8.0,
                                  burst_len=32)
    eng_b = bfactory()
    try:
        eng_b.warmup()
        s_ok, s_refused, s_goodput = _burst_goodput(
            lambda f: eng_b.submit(f, timeout=10.0), over_feeds,
            offsets, 10.0)
    finally:
        eng_b.close()
    router_b = cluster.serve_cluster(bfactory, replicas=args.cluster,
                                     warmup=True)
    try:
        c_ok, c_refused, c_goodput = _burst_goodput(
            lambda f: router_b.submit(f, timeout=10.0), over_feeds,
            offsets, 10.0)
    finally:
        router_b.close()
    if c_ok < s_ok:
        failures.append(
            f"pool served fewer requests than one engine on the same "
            f"overload trace ({c_ok} vs {s_ok})")

    # ---- the pool: N replicas behind the router ----------------------
    router = cluster.serve_cluster(factory, replicas=args.cluster,
                                   warmup=True)
    restart_report = None
    min_ready_seen = None
    restart_drive = None
    try:
        served, cluster_s = _closed_loop(router.infer, feeds,
                                         args.concurrency)
        cluster_rps = len(feeds) / cluster_s
        if per_row:
            mismatches = sum(
                1 for ref, got in zip(single_out, served)
                if not np.allclose(np.asarray(ref[0]),
                                   np.asarray(got[0]),
                                   rtol=1e-5, atol=1e-7))
            if mismatches:
                failures.append(
                    f"{mismatches} request(s) diverged between the "
                    "single engine and the pool")
        else:
            mismatches = None

        if args.rolling_restart:
            # sustained MIXED load (1- and 2-row requests) while every
            # replica is drained + rebuilt, one at a time; the
            # contract: zero losses, never fewer than N-1 READY
            rng = np.random.RandomState(3)
            mixed = [synth_feed(infer, zp.feed_names, rows, rng)
                     for rows in ([1, 2] * 8)]
            outcomes = {"ok": 0, "typed": 0, "lost": 0}
            olock = threading.Lock()
            stop = threading.Event()

            def client(idx):
                k = idx
                while not stop.is_set():
                    f = mixed[k % len(mixed)]
                    k += args.concurrency
                    try:
                        router.infer(f, timeout=30.0)
                        key = "ok"
                    except ServingError:
                        key = "typed"
                    except Exception:       # noqa: BLE001 — tallied
                        key = "lost"
                    with olock:
                        outcomes[key] += 1

            ready_samples = []

            def poll_ready():
                while not stop.is_set():
                    ready_samples.append(
                        router.pool.ready_count())
                    stop.wait(0.01)

            clients = [threading.Thread(target=client, args=(i,),
                                        daemon=True)
                       for i in range(args.concurrency)]
            poller = threading.Thread(target=poll_ready, daemon=True)
            for t in clients:
                t.start()
            poller.start()
            time.sleep(0.2)          # load established before restart
            restart_report = router.pool.rolling_restart()
            time.sleep(0.2)          # load continues after restart
            stop.set()
            for t in clients:
                t.join(30.0)
            poller.join(5.0)
            restart_drive = dict(outcomes)
            min_ready_seen = min(
                [restart_report["min_ready_observed"]]
                + (ready_samples or []))
            if outcomes["lost"]:
                failures.append(
                    f"rolling restart lost {outcomes['lost']} "
                    "request(s) (untyped failure)")
            if outcomes["typed"]:
                failures.append(
                    f"rolling restart failed {outcomes['typed']} "
                    "request(s) with typed errors — drain+failover "
                    "should complete every request")
            if outcomes["ok"] == 0:
                failures.append("no traffic flowed during the "
                                "rolling restart")
            if len(restart_report["restarted"]) != args.cluster:
                failures.append(
                    f"rolling restart covered "
                    f"{len(restart_report['restarted'])}/"
                    f"{args.cluster} replicas")
            if min_ready_seen < args.cluster - 1:
                failures.append(
                    f"pool dropped to {min_ready_seen} READY "
                    f"replicas (floor {args.cluster - 1})")
        stats = router.stats()
    finally:
        router.close()

    speedup = cluster_rps / single_rps if single_rps else None
    if args.assert_speedup is not None and speedup is not None \
            and speedup < args.assert_speedup:
        failures.append(
            f"cluster speedup {speedup:.2f}x below the "
            f"--assert-speedup {args.assert_speedup}x floor")
    import os as _os
    report = {
        "mode": "cluster",
        "model": args.model,
        "replicas": args.cluster,
        "requests": args.requests,
        "concurrency": args.concurrency,
        "host_cores": _os.cpu_count(),
        "single_engine_rps": round(single_rps, 1),
        "cluster_rps": round(cluster_rps, 1),
        "cluster_vs_single_speedup": (None if speedup is None
                                      else round(speedup, 2)),
        "burst_overload": {
            "offered": n_over, "queue_per_engine": 32,
            "single": {"ok": s_ok, "refused": s_refused,
                       "goodput_qps": round(s_goodput, 1)},
            "cluster": {"ok": c_ok, "refused": c_refused,
                        "goodput_qps": round(c_goodput, 1)}},
        "mismatched_requests": mismatches,
        "rolling_restart": restart_report,
        "rolling_restart_drive": restart_drive,
        "min_ready_observed": min_ready_seen,
        "bench_record": {
            "metric": "serving_cluster_burst_goodput_qps",
            "value": round(c_goodput, 1), "unit": "req/s",
            "backend": "cpu", "replicas": args.cluster,
            "host_cores": _os.cpu_count(),
            "single_engine_goodput_qps": round(s_goodput, 1),
            "cluster_served": c_ok, "single_served": s_ok,
            "offered": n_over,
            "closed_loop_cluster_rps": round(cluster_rps, 1),
            "closed_loop_single_rps": round(single_rps, 1)},
        "pool_stats": stats,
        "failures": failures,
    }
    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if args.json:
        print(text)
    else:
        rr = ("" if restart_report is None else
              f", rolling restart {len(restart_report['restarted'])}"
              f" replicas in {restart_report['wall_s']}s "
              f"(min ready {min_ready_seen}, "
              f"drive {restart_drive})")
        print(f"servebench --cluster {args.cluster} {args.model}: "
              f"single {single_rps:.0f} req/s, cluster "
              f"{cluster_rps:.0f} req/s ({speedup:.2f}x){rr}")
    if failures:
        for f in failures:
            print(f"servebench --cluster: FAILED — {f}",
                  file=sys.stderr)
        return 1
    return 0


def canary_main(args):
    """--canary: the versioned-deployment drill (selfcheck stage 10).

    Exports the bench model twice (v1/v2, identical weights, embedded
    artifact stores, monotone model_version stamps), serves v1 from a
    replica pool under sustained client load, records a golden set,
    then walks the full deployment gauntlet:

    1. dark-deploy v2 as a canary (zero traffic) — the clean
       pre-traffic numerics gate must PASS (the weights are
       identical);
    2. briefly split traffic 50/50 to prove the per-version metrics
       separation (both versions' counters visible, nothing collides);
    3. arm ``serving_canary_regression`` and ``promote()`` — the 1%
       stage's in-flight numerics re-sample must AUTO-REJECT and roll
       back;
    4. assert the rollback contract: zero lost requests across the
       whole drill, zero XLA compiles on the re-warmed incumbent
       replicas, weights instantly repointed, post-rollback traffic
       all-success.

    BENCH record: ``serving_rollback_s`` — weight repoint + canary
    drain + zero-compile rebuild, wall-clock."""
    import shutil
    import tempfile
    import threading
    from paddle_tpu import cluster
    from paddle_tpu.resilience import faultinject
    from paddle_tpu.serving import ServingError

    failures = []
    workdir = tempfile.mkdtemp(prefix="servebench_canary_")
    router = None
    try:
        zp, infer, fetch, per_row, scope, feeds = _setup(args)
        fetch_names = (fetch if isinstance(fetch[0], str)
                       else [v.name for v in fetch])
        exe = fluid.Executor(fluid.CPUPlace())
        buckets = serving.BucketSpec(
            batch_sizes=_bucket_sizes(args.max_batch))
        v1_dir = os.path.join(workdir, "v1")
        v2_dir = os.path.join(workdir, "v2")
        # racecheck: ok(global-mutation) — driver-thread export before
        # the deployment engine starts; bench-private scope
        with fluid.scope_guard(scope):
            for dirname, mv in ((v1_dir, 1), (v2_dir, 2)):
                fluid.io.save_inference_model(
                    dirname, zp.feed_names, fetch_names, exe,
                    main_program=infer, serving_buckets=buckets,
                    artifact_store=True, model_version=mv)

        replicas = max(2, args.cluster or 2)
        router = cluster.serve_cluster(
            lambda: serving.ServingEngine.from_saved_model(
                v1_dir, place=fluid.CPUPlace()),
            replicas=replicas, warmup=True)
        mgr = cluster.DeploymentManager(router)
        v1 = mgr.register("v1", model_dir=v1_dir)
        v2 = mgr.register("v2", model_dir=v2_dir)
        if (v1.model_version, v2.model_version) != (1, 2):
            failures.append(
                f"model_version stamps wrong: v1={v1.model_version} "
                f"v2={v2.model_version} (expected 1, 2)")
        if not (v1.has_artifacts and v2.has_artifacts):
            failures.append("exports are missing their embedded "
                            "artifact stores")
        mgr.set_incumbent("v1")
        mgr.record_golden(feeds[:8])

        # ---- sustained client load for the whole gauntlet ----------
        outcomes = {"ok": 0, "typed": 0, "lost": 0}
        olock = threading.Lock()
        stop = threading.Event()

        def client(idx):
            k = idx
            while not stop.is_set():
                f = feeds[k % len(feeds)]
                k += args.concurrency
                try:
                    router.infer(f, timeout=30.0)
                    key = "ok"
                except ServingError:
                    key = "typed"
                except Exception:           # noqa: BLE001 — tallied
                    key = "lost"
                with olock:
                    outcomes[key] += 1

        clients = [threading.Thread(target=client, args=(i,),
                                    daemon=True)
                   for i in range(args.concurrency)]
        for t in clients:
            t.start()
        time.sleep(0.2)                  # load established

        # ---- 1. dark deploy + clean pre-traffic gate ---------------
        deploy = mgr.deploy_canary("v2", replicas=1)
        if not deploy["accepted"]:
            failures.append(
                "clean canary (identical weights) was rejected: "
                f"{deploy.get('numerics', {}).get('worst')}")
        if deploy.get("rewarm_compiles"):
            failures.append(
                f"canary conversion compiled "
                f"{deploy['rewarm_compiles']} executables — the v2 "
                "artifact store should make it zero")

        # ---- 2. per-version metrics separation at 50/50 ------------
        status_mid = None
        if deploy["accepted"]:
            router.set_weights({"v1": 0.5, "v2": 0.5})
            time.sleep(0.6)
            status_mid = mgr.status()
            versions = status_mid["versions"] or {}
            for v in ("v1", "v2"):
                if not (versions.get(v) or {}).get("requests_total"):
                    failures.append(
                        f"per-version metrics show no traffic for "
                        f"{v} at 50/50 split")
            combined = status_mid["combined"] or {}
            if not combined.get("v2/requests_total"):
                failures.append(
                    "label-namespaced combined metrics are missing "
                    "v2/requests_total")

            # ---- 3. regression injected → promote must auto-reject -
            faultinject.arm("serving_canary_regression", at=0,
                            times=100)
            promote = mgr.promote(stages=(0.01, 0.5, 1.0),
                                  stage_s=0.4, poll_s=0.02)
            faultinject.disarm()
            if promote["accepted"]:
                failures.append(
                    "promote ACCEPTED a numerics-regressed canary")
            elif promote.get("rejected") != "numerics":
                failures.append(
                    f"canary rejected by {promote.get('rejected')!r}, "
                    "expected the numerics gate")
            rollback = promote.get("rollback") or {}
        else:
            promote = None
            rollback = mgr.rollback(reason="drill: deploy rejected")

        # ---- 4. rollback contract ---------------------------------
        time.sleep(0.2)                  # load continues post-rollback
        stop.set()
        for t in clients:
            t.join(30.0)
        if rollback.get("rewarm_compiles"):
            failures.append(
                f"rollback re-warm compiled "
                f"{rollback['rewarm_compiles']} executables — the "
                "incumbent artifact store must make it ZERO")
        weights = router.weights()
        if weights != {"v1": 1.0}:
            failures.append(
                f"post-rollback weights are {weights}, expected "
                "v1-only")
        wrong = [r.name for r in router.pool.replicas()
                 if r.version != "v1"]
        if wrong:
            failures.append(
                f"replicas {wrong} are not back on the incumbent")
        for name in rollback.get("replicas", []):
            for r in router.pool.replicas():
                if r.name == name and hasattr(r, "engine"):
                    n = r.engine.exe.total_compiles()
                    if n:
                        failures.append(
                            f"re-warmed incumbent {name} shows "
                            f"{n} compiles (expected 0)")
                    if r.engine.model_version != 1:
                        failures.append(
                            f"re-warmed incumbent {name} serves "
                            f"model_version "
                            f"{r.engine.model_version}, expected 1")
        if outcomes["lost"]:
            failures.append(
                f"deployment gauntlet lost {outcomes['lost']} "
                "request(s) (untyped failure)")
        if outcomes["typed"]:
            failures.append(
                f"deployment gauntlet failed {outcomes['typed']} "
                "request(s) with typed errors — drain + weighted "
                "failover should complete every request")
        if outcomes["ok"] == 0:
            failures.append("no traffic flowed during the drill")

        # post-rollback wave: the restored incumbent must serve
        post, _ = _closed_loop(router.infer, feeds[:16],
                               args.concurrency, timeout=30.0)
        if len(post) != 16:
            failures.append("post-rollback wave did not complete")
        stats = router.stats()
    finally:
        if router is not None:
            router.close()
        shutil.rmtree(workdir, ignore_errors=True)

    rollback_s = rollback.get("serving_rollback_s")
    report = {
        "mode": "canary",
        "model": args.model,
        "replicas": replicas,
        "concurrency": args.concurrency,
        "deploy": deploy,
        "status_at_split": status_mid,
        "promote": promote,
        "rollback": rollback,
        "drive": dict(outcomes),
        "bench_record": {
            "metric": "serving_rollback_s",
            "value": rollback_s, "unit": "s", "backend": "cpu",
            "repoint_s": rollback.get("repoint_s"),
            "rewarm_compiles": rollback.get("rewarm_compiles"),
            "lost_requests": outcomes["lost"],
            "replicas": replicas},
        "pool_stats": stats,
        "failures": failures,
    }
    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if args.json:
        print(text)
    else:
        print(f"servebench --canary {args.model}: deploy "
              f"{'accepted' if deploy['accepted'] else 'REJECTED'}, "
              f"regressed canary "
              f"{'auto-rejected' if promote and not promote['accepted'] else 'NOT rejected'}, "
              f"rollback {rollback_s}s "
              f"({rollback.get('rewarm_compiles')} compiles), "
              f"drive {dict(outcomes)}")
    if failures:
        for f in failures:
            print(f"servebench --canary: FAILED — {f}",
                  file=sys.stderr)
        return 1
    return 0


def _export_remote_model(args, workdir):
    """Export the bench model with serving buckets + a seeded embedded
    artifact store — the dir a remote host provisions from."""
    zp, infer, fetch, per_row, scope, feeds = _setup(args)
    model_dir = os.path.join(workdir, "model")
    exe = fluid.Executor(fluid.CPUPlace())
    # racecheck: ok(global-mutation) — driver-thread export before any
    # serving thread starts; bench-private scope
    with fluid.scope_guard(scope):
        fluid.io.save_inference_model(
            model_dir, zp.feed_names,
            fetch if isinstance(fetch[0], str)
            else [v.name for v in fetch],
            exe, main_program=infer,
            serving_buckets=serving.BucketSpec(
                batch_sizes=_bucket_sizes(args.max_batch)),
            artifact_store=True)
    return model_dir, feeds, per_row


def remote_main(args):
    """--remote N: the cross-host serving fabric on loopback sockets —
    N ReplicaServers provisioned from one exported dir, a
    socket-backed pool behind the stock Router, closed-loop QPS
    (``serving_remote_qps``), plus the cold-provision gate: a fresh
    server stood up from the saved-model dir (and another provisioned
    purely OVER THE WIRE) must warm with ZERO XLA compiles and answer
    bit-exact (docs/DISTRIBUTED.md "Serving across hosts")."""
    import os as _os
    import shutil
    import tempfile
    from paddle_tpu import cluster

    failures = []
    workdir = tempfile.mkdtemp(prefix="servebench_remote_")
    servers = []
    router = None
    try:
        model_dir, feeds, per_row = _export_remote_model(args, workdir)

        # ---- reference: a lone local engine on the same artifact ----
        ref_eng = serving.ServingEngine.from_saved_model(
            model_dir, place=fluid.CPUPlace())
        try:
            refs = [ref_eng.infer(f, timeout=60.0) for f in feeds]
            single_out, single_s = _closed_loop(
                ref_eng.infer, feeds, args.concurrency)
        finally:
            ref_eng.close()
        single_rps = len(feeds) / single_s

        # ---- cold provision: saved dir -> serving socket ------------
        t0 = time.perf_counter()
        first = cluster.ReplicaServer(model_dir, name="remote-0")
        cold_provision_s = time.perf_counter() - t0
        servers.append(first)
        if first.total_compiles() != 0:
            failures.append(
                f"cold-provisioned server compiled "
                f"{first.total_compiles()} executables — expected "
                "ZERO (artifact store miss)")

        # ---- wire provision: socket -> fresh dir -> serving socket --
        wire_dir = _os.path.join(workdir, "wire_provisioned")
        t0 = time.perf_counter()
        wire_report = cluster.provision_from_remote(first.addr,
                                                    wire_dir)
        wire = cluster.ReplicaServer(wire_dir, name="remote-1")
        wire_provision_s = time.perf_counter() - t0
        servers.append(wire)
        if wire.total_compiles() != 0:
            failures.append(
                f"wire-provisioned server compiled "
                f"{wire.total_compiles()} executables — expected ZERO")
        for _ in range(max(2, int(args.remote)) - 2):
            servers.append(cluster.ReplicaServer(model_dir))

        # ---- the fabric: Router over socket replicas ----------------
        router = cluster.serve_remotes([s.addr for s in servers],
                                       refresh_interval_s=0.2)
        served, remote_s = _closed_loop(router.infer, feeds,
                                        args.concurrency)
        remote_rps = len(feeds) / remote_s
        lost = sum(1 for out in served if out is None)
        if lost:
            failures.append(f"{lost} request(s) lost on the fabric")
        if per_row:
            # tolerance rule, same as --cluster: concurrent clients
            # co-batch into different bucket shapes than the
            # sequential reference, and XLA legitimately re-tiles per
            # shape — within a bucket the fabric is bit-exact (pinned
            # in tests/test_net_cluster.py)
            mismatches = sum(
                1 for ref, got in zip(refs, served)
                if got is None
                or not np.allclose(np.asarray(ref[0]),
                                   np.asarray(got[0]),
                                   rtol=1e-5, atol=1e-7))
            if mismatches:
                failures.append(
                    f"{mismatches} request(s) diverged beyond float "
                    "tolerance between the local engine and the "
                    "socket fabric")
        else:
            mismatches = None
        stats = router.stats()
        member_view = router.membership.view()
    finally:
        if router is not None:
            router.close()
        for s in servers:
            s.close()
        shutil.rmtree(workdir, ignore_errors=True)

    report = {
        "mode": "remote",
        "model": args.model,
        "remotes": len(servers),
        "requests": args.requests,
        "concurrency": args.concurrency,
        "host_cores": _os.cpu_count(),
        "local_engine_rps": round(single_rps, 1),
        "remote_qps": round(remote_rps, 1),
        "cold_provision_s": round(cold_provision_s, 3),
        "wire_provision_s": round(wire_provision_s, 3),
        "wire_provision": wire_report,
        "mismatched_requests": mismatches,
        "membership": member_view,
        "bench_record": {
            "metric": "serving_remote_qps",
            "value": round(remote_rps, 1), "unit": "req/s",
            "backend": "cpu", "remotes": len(servers),
            "host_cores": _os.cpu_count(),
            "local_engine_rps": round(single_rps, 1),
            "cold_provision_s": round(cold_provision_s, 3),
            "wire_provision_s": round(wire_provision_s, 3)},
        "pool_stats": stats,
        "failures": failures,
    }
    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if args.json:
        print(text)
    else:
        print(f"servebench --remote {len(servers)} {args.model}: "
              f"local {single_rps:.0f} req/s, fabric "
              f"{remote_rps:.0f} req/s, cold provision "
              f"{cold_provision_s:.2f}s, wire provision "
              f"{wire_provision_s:.2f}s "
              f"({wire_report['files']} files, 0 compiles), "
              f"{mismatches} mismatches")
    if failures:
        for f in failures:
            print(f"servebench --remote: FAILED — {f}",
                  file=sys.stderr)
        return 1
    return 0


def remote_chaos_main(args):
    """--chaos --remote N: the partition drill on loopback sockets —
    net_partition + net_frame_drop armed mid-load against a socket
    pool must lose ZERO requests (every submit resolves to a result
    or a typed serving error), open and re-close the per-connection
    breaker, and rejoin the partitioned replica within one membership
    refresh of the fault clearing."""
    import shutil
    import tempfile
    import threading
    from paddle_tpu import cluster
    from paddle_tpu.resilience import faultinject
    from paddle_tpu.serving import ServingError

    n_remotes = max(2, int(args.remote))
    failures = []
    workdir = tempfile.mkdtemp(prefix="servebench_remote_chaos_")
    servers = []
    router = None
    try:
        model_dir, feeds, _per_row = _export_remote_model(args,
                                                          workdir)
        servers = [cluster.ReplicaServer(model_dir)
                   for _ in range(n_remotes)]
        router = cluster.serve_remotes(
            [s.addr for s in servers], refresh_interval_s=0.05,
            breaker_threshold=2, breaker_cooldown_s=0.1,
            reconnect_backoff_s=0.01, reconnect_attempts=2)
        outcomes = {"ok": 0, "typed": 0, "lost": 0}
        lock = threading.Lock()
        stop = threading.Event()

        def client(idx):
            k = idx
            while not stop.is_set():
                feed = feeds[k % len(feeds)]
                k += args.concurrency
                try:
                    router.infer(feed, timeout=5.0)
                    key = "ok"
                except ServingError:
                    key = "typed"
                except Exception:           # noqa: BLE001 — tallied
                    key = "lost"
                with lock:
                    outcomes[key] += 1
                time.sleep(0.002)

        threads = [threading.Thread(target=client, args=(i,),
                                    daemon=True)
                   for i in range(args.concurrency)]
        for t in threads:
            t.start()
        time.sleep(0.3)                     # load established
        # The partition window is progress-gated, not wall-clock: hold
        # the fault until a breaker has provably opened. When the
        # partition blackholes frames instead of erroring fast, the
        # first failures only resolve at the request-deadline sweep —
        # a fixed 1s window could close before any connection saw
        # breaker_threshold consecutive failures, flaking the drill.
        faultinject.arm("net_partition", at=0, times=1_000_000)
        faultinject.arm("net_frame_drop", at=0, times=4)
        gate = time.monotonic() + 30.0
        while time.monotonic() < gate and \
                sum(r.breaker_opens_total()
                    for r in router.pool.replicas()) == 0:
            time.sleep(0.02)
        time.sleep(0.2)                     # let the open breaker shed
        faultinject.disarm()
        time.sleep(1.0)                     # healing window
        stop.set()
        for t in threads:
            t.join(30.0)
        replicas = router.pool.replicas()
        breaker_opens = sum(r.breaker_opens_total()
                            for r in replicas)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and \
                not all(r.alive() for r in replicas):
            time.sleep(0.02)
        rejoined = all(r.alive() for r in replicas)
        reclosed = all(
            r.breaker.state != "open" for r in replicas)
        member = router.membership.stats()
        # post-heal traffic must be clean
        post = 0
        try:
            for feed in feeds[:8]:
                router.infer(feed, timeout=30.0)
                post += 1
        except ServingError as exc:
            failures.append(f"post-heal traffic failed typed: {exc}")
        if outcomes["lost"]:
            failures.append(
                f"{outcomes['lost']} request(s) LOST under partition "
                "(untyped failure — every submit must resolve to a "
                "result or a typed serving error)")
        if outcomes["ok"] == 0:
            failures.append("no traffic flowed during the drill")
        if breaker_opens == 0:
            failures.append("no per-connection breaker opened under "
                            "a full partition")
        if not rejoined:
            failures.append("a partitioned replica failed to rejoin "
                            "after the fault cleared")
        if not reclosed:
            failures.append("a breaker stayed open after recovery")
        stats = router.stats()
    finally:
        faultinject.disarm()
        if router is not None:
            router.close()
        for s in servers:
            s.close()
        shutil.rmtree(workdir, ignore_errors=True)

    report = {
        "mode": "remote-chaos",
        "model": args.model,
        "remotes": n_remotes,
        "drive": outcomes,
        "breaker_opens": breaker_opens,
        "rejoined": rejoined,
        "breakers_reclosed": reclosed,
        "membership": member,
        "post_heal_ok": post,
        "pool_stats": stats,
        "failures": failures,
    }
    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if args.json:
        print(text)
    else:
        print(f"servebench --chaos --remote {n_remotes} "
              f"{args.model}: drive {outcomes}, "
              f"{breaker_opens} breaker opens, "
              f"rejoined={rejoined}, "
              f"rejoins={member['rejoins_total']}, "
              f"post-heal {post} ok")
    if failures:
        for f in failures:
            print(f"servebench --chaos --remote: FAILED — {f}",
                  file=sys.stderr)
        return 1
    return 0


def chaos_cluster_main(args):
    """--chaos --cluster N: the replica-crash drill. A replica is
    killed mid-load via the ``serving_replica_crash`` fault point; the
    router must reroute + fail over (ZERO lost requests, zero typed
    errors surfacing to callers), the pool must revive the dead
    replica, and post-recovery traffic must be all-success."""
    import threading
    from paddle_tpu import cluster
    from paddle_tpu.resilience import faultinject
    from paddle_tpu.serving import ServingError

    zp, infer, fetch, _per_row, scope, feeds = _setup(args)
    factory = _classifier_factory(args, infer, zp, fetch, scope)
    router = cluster.serve_cluster(factory, replicas=args.cluster,
                                   warmup=True,
                                   revive_interval_s=0.05)

    def drive(wave, timeout=30.0):
        counts = {"ok": 0, "typed": 0, "lost": 0}
        lock = threading.Lock()

        def one(f):
            try:
                router.infer(f, timeout=timeout)
                return "ok"
            except ServingError:
                return "typed"
            except Exception:               # noqa: BLE001 — tallied
                return "lost"
        with ThreadPoolExecutor(args.concurrency) as pool:
            for outcome in pool.map(one, wave):
                with lock:
                    counts[outcome] += 1
        return counts

    failures = []
    try:
        # phase 1 — steady state
        steady = drive(feeds)
        if steady["ok"] != len(feeds):
            failures.append(f"steady-state failures: {steady}")

        # phase 2 — a replica dies under the load
        faultinject.arm("serving_replica_crash", at=0)
        chaos = drive(feeds)
        faultinject.disarm("serving_replica_crash")
        if chaos["lost"]:
            failures.append(
                f"{chaos['lost']} request(s) lost in the crash wave")
        if chaos["typed"]:
            failures.append(
                f"{chaos['typed']} request(s) surfaced typed errors "
                "— failover should have absorbed the crash")

        # phase 3 — the pool revives the dead replica
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and (
                router.pool.ready_count() < args.cluster):
            time.sleep(0.02)
        post = router.stats()
        if post["ready_replicas"] < args.cluster:
            failures.append(
                f"pool never recovered: {post['ready_replicas']}/"
                f"{args.cluster} READY")
        if post["revives_total"] < 1:
            failures.append("no revival recorded — did the crash "
                            "fault point fire?")

        # phase 4 — recovery traffic, then graceful drain
        recovery = drive(feeds)
        if recovery["ok"] != len(feeds):
            failures.append(f"post-recovery failures: {recovery}")
        drain_handles = [router.submit(f, timeout=30.0)
                         for f in feeds[:8]]
        router.close(drain=True)
        drained = 0
        for h in drain_handles:
            try:
                h.result(timeout=5.0)
                drained += 1
            except ServingError:
                pass
        if drained != len(drain_handles):
            failures.append(
                f"drain completed {drained}/{len(drain_handles)}")
    finally:
        faultinject.disarm()
        router.close()

    report = {
        "mode": "chaos-cluster",
        "model": args.model,
        "replicas": args.cluster,
        "requests_per_wave": len(feeds),
        "steady": steady,
        "chaos": chaos,
        "recovery": recovery,
        "revives_total": post["revives_total"],
        "reroutes_total": post["reroutes_total"],
        "failovers_total": post["failovers_total"],
        "drained": drained,
        "failures": failures,
    }
    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if args.json:
        print(text)
    else:
        print(f"servebench --chaos --cluster {args.cluster}: "
              f"chaos wave {chaos}, revives "
              f"{post['revives_total']}, failovers "
              f"{post['failovers_total']}, drained {drained}/8, "
              f"{len(failures)} failure(s)")
    if failures:
        for f in failures:
            print(f"servebench --chaos --cluster: FAILED — {f}",
                  file=sys.stderr)
        return 1
    return 0


def trace_main(args):
    """--arrival trace: trace-driven load (ROADMAP item 5) — replay a
    bursty, heavy-tailed arrival trace (synthetic by default,
    ``--trace-file`` to replay a recorded one) at a ladder of rates
    against the engine / router, and record the capacity answers: max
    sustainable QPS before any shed and p99 latency during burst
    phases. Works for both the classifier engine (default) and the
    decode engine (--decode), single-engine or --cluster N."""
    from paddle_tpu import cluster

    failures = []
    rng = np.random.RandomState(11)
    if args.max_queue is None:
        args.max_queue = 32     # a fixed bound makes the knee real
    if args.decode:
        cfg, buckets, scope, _exe, _gen, prompts = _decode_model(args)

        def factory():
            return serving.DecodeEngine(
                cfg, scope=scope, place=fluid.CPUPlace(),
                config=_decode_config(args, buckets))
        items = prompts
        metric = "llama_decode_trace_max_qps"
    else:
        zp, infer, fetch, _per_row, scope, feeds = _setup(args)
        factory = _classifier_factory(args, infer, zp, fetch, scope)
        items = feeds
        metric = "serving_trace_max_qps"

    if args.cluster:
        target = cluster.serve_cluster(factory, replicas=args.cluster,
                                       warmup=True)
    else:
        target = factory()
        target.warmup()
    try:
        ladder = trace_ladder(
            lambda it: target.submit(it,
                                     timeout=args.request_timeout),
            items, args, rng)
    finally:
        target.close()
    if ladder["max_sustained_qps"] is None:
        failures.append(
            "no clean rung: the base --rate already sheds — lower it")
    report = {
        "mode": "trace",
        "decode": bool(args.decode),
        "model": None if args.decode else args.model,
        "replicas": args.cluster or 1,
        "requests_per_rung": len(items),
        "base_rate": args.rate,
        "ladder_growth": args.ladder_growth,
        "burst_factor": args.burst_factor,
        "trace_file": args.trace_file,
        "ladder": ladder,
        "bench_record": {
            "metric": metric,
            "value": ladder["max_sustained_qps"], "unit": "req/s",
            "backend": "cpu", "replicas": args.cluster or 1,
            "p99_burst_ms": ladder["p99_burst_ms"]},
        "failures": failures,
    }
    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if args.json:
        print(text)
    else:
        print(f"servebench --arrival trace"
              f"{' --decode' if args.decode else ''}"
              f"{f' --cluster {args.cluster}' if args.cluster else ''}"
              f": max sustained {ladder['max_sustained_qps']} req/s, "
              f"p99 under burst {ladder['p99_burst_ms']} ms "
              f"({len(ladder['rungs'])} rungs)")
    if failures:
        for f in failures:
            print(f"servebench --arrival trace: FAILED — {f}",
                  file=sys.stderr)
        return 1
    return 0


# Priority-weighted goodput: an answered interactive request is worth
# 4x an answered batch request — the number the graceful-vs-flat-shed
# comparison is scored on.
_GOODPUT_WEIGHTS = {"interactive": 4.0, "standard": 2.0, "batch": 1.0}


def _overload_slo_classes():
    return {
        "interactive": serving.SLOClass(name="chat", ttft_target_s=1.0,
                                        priority="interactive"),
        "standard": serving.SLOClass(name="api", ttft_target_s=4.0,
                                     priority="standard"),
        "batch": serving.SLOClass(name="bulk", priority="batch"),
    }


def _overload_timeouts(request_timeout):
    """Per-class request deadlines: interactive callers give up fast
    (a chat user will not wait out a batch scrape's deadline), batch
    callers wait the full bound. This is what makes flat shedding
    LOSE: a queue-blind pool converts overload into queueing latency,
    which blows exactly the deadlines the valuable traffic carries."""
    rt = float(request_timeout)
    return {"interactive": rt * 0.25, "standard": rt * 0.6, "batch": rt}


def _overload_model(args):
    """A deliberately heavier llama for the overload referee (~an
    order of magnitude more work per token than _decode_model's tiny
    config): the pool's capacity must sit at human-scale req/s so a
    finite trace can genuinely saturate it — against the tiny config,
    any plausible trace drains inside its own deadlines and the knee
    is never real."""
    from paddle_tpu.models.llama import (LlamaConfig,
                                         build_llama_generator)
    # racecheck: ok(global-mutation) — bench CLI entrypoint: pins the
    # backend before any serving thread exists
    fluid.force_cpu()
    cfg = LlamaConfig(vocab_size=256, dim=256, n_layers=4, n_heads=8,
                      n_kv_heads=4, ffn_hidden=512, dtype="float32")
    buckets = (8, 16)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        ptok = fluid.layers.data(name="ptok", shape=[1, buckets[0]],
                                 dtype="int64",
                                 append_batch_size=False)
        build_llama_generator(cfg, ptok, max_new_tokens=2)
    # racecheck: ok(global-mutation) — driver-thread setup, no serving
    # threads yet; bench-private scope
    with fluid.scope_guard(scope):
        exe.run(startup)
    return cfg, buckets, scope


def _drive_overload(router, trace, prompts, rate_scale,
                    request_timeout):
    """Replay the rich trace (offsets scaled by ``rate_scale``)
    through ``router`` open-loop, tagging every request with its
    class's SLO and per-class deadline. Returns (counts, per_class
    {cls: {n, ok}}, wall, goodput) — goodput is the priority-weighted
    answered count."""
    slo_by_class = _overload_slo_classes()
    timeouts = _overload_timeouts(request_timeout)
    items = list(zip(prompts, trace["classes"]))

    def submit(item):
        prompt, cls = item
        return router.submit(prompt, timeout=timeouts[cls],
                             slo=slo_by_class[cls])

    counts, results, wall, _lats = open_loop_drive(
        submit, items, trace["offsets"] * rate_scale,
        result_timeout=float(request_timeout) + 30.0)
    per_class = {cls: {"n": 0, "ok": 0} for cls in _GOODPUT_WEIGHTS}
    for i, cls in enumerate(trace["classes"]):
        per_class[cls]["n"] += 1
        if results[i] is not None:
            per_class[cls]["ok"] += 1
    goodput = sum(_GOODPUT_WEIGHTS[c] * v["ok"]
                  for c, v in per_class.items())
    return counts, per_class, wall, goodput


def overload_main(args):
    """--overload: the graceful-degradation referee (selfcheck stage
    14). One deterministic diurnal/flash-crowd trace drives four
    phases against a decode replica pool:

    1. KNEE — a rate ladder through the graceful router (adaptive
       admission + priority tiers + brownout + retry budget) finds the
       highest rate the pool sustains with zero shed/timeout/error:
       ``serving_overload_knee_qps``.
    2. DRILL — the trace replays at 3x that knee. The counters must
       prove strict priority shedding (ZERO interactive sheds while
       batch sheds), metered brownout (engaged > 0, every step
       reverted, final level 0), and typed outcomes only.
    3. STORM — ``serving_retry_storm`` drops one answer in flight per
       closed-loop request; the retry budget must bound amplification
       (retries <= capacity) and then fail FAST typed
       (RetryBudgetExhaustedError), never storm.
    4. FLAT BASELINE — the same 3x-knee trace through a static-bound
       router (no admission, no tiers, no brownout, no budget);
       priority-weighted goodput graceful/flat must exceed 1.0:
       ``serving_overload_goodput_ratio``.

    ``--overload-flat-shed`` runs phases 1-3 on the FLAT config too —
    the inverted-teeth switch: the drill's shed-ordering, brownout,
    and storm assertions must then FAIL (exit 1), proving the gate
    has teeth."""
    from paddle_tpu.cluster import ReplicaPool, Router
    from paddle_tpu.resilience import faultinject
    from paddle_tpu.serving.overload import (AdmissionController,
                                             RetryBudget,
                                             RetryBudgetExhaustedError)

    failures = []
    flat_main = bool(args.overload_flat_shed)
    replicas = args.cluster or 2
    ceiling = 32 if args.max_queue is None else args.max_queue
    cfg, buckets, scope = _overload_model(args)

    if args.trace_file:
        trace = load_rich_trace(args.trace_file)
        n = len(trace["offsets"])
        if trace["classes"] is None or trace["buckets"] is None:
            fill = np.random.RandomState(23)
            mix = ("interactive", "standard", "batch")
            if trace["classes"] is None:
                trace["classes"] = [mix[int(fill.randint(3))]
                                    for _ in range(n)]
            if trace["buckets"] is None:
                trace["buckets"] = [int(fill.choice(buckets))
                                    for _ in range(n)]
    else:
        trace = gen_overload_trace(args.requests, args.rate,
                                   np.random.RandomState(23),
                                   buckets=buckets)
        n = args.requests
    offered = n / float(trace["offsets"][-1])    # trace's own mean qps
    prng = np.random.RandomState(7)
    prompts = [prng.randint(0, cfg.vocab_size,
                            (int(L),)).astype(np.int64)
               for L in trace["buckets"]]

    brownout_cfg = {"engage_at": 0.8, "revert_at": 0.4,
                    "dwell_s": 0.05, "queue_target_s": 0.15}

    def make_factory(brownout, scheduler):
        def factory():
            return serving.DecodeEngine(
                cfg, scope=scope, place=fluid.CPUPlace(),
                config=serving.DecodeConfig(
                    max_batch=args.max_batch, prompt_buckets=buckets,
                    max_new_tokens=args.max_new, page_size=8,
                    decode_block=args.decode_block,
                    prefill_batch=args.prefill_batch,
                    max_queue=ceiling, default_timeout_s=120.0,
                    scheduler=scheduler, brownout=brownout))
        return factory

    def graceful_router():
        pool = ReplicaPool(make_factory(dict(brownout_cfg), "slo"),
                           replicas=replicas, warmup=True)
        return Router(
            pool, max_cluster_queue=ceiling,
            admission=AdmissionController(hard_ceiling=ceiling,
                                          start_limit=ceiling // 4,
                                          target_delay_s=0.8),
            retry_budget=RetryBudget(capacity=16))

    def flat_router():
        # the pre-PR-19 story: fixed bound, FIFO admission,
        # first-come-first-shed, no brownout, no budget
        pool = ReplicaPool(make_factory(None, None),
                           replicas=replicas, warmup=True)
        return Router(pool, max_cluster_queue=ceiling)

    main_router = flat_router if flat_main else graceful_router

    # ---- phase 1: knee ladder ---------------------------------------
    # Climb EVERY rung (rungs past the knee are the cheapest — their
    # walls shrink with rate). The KNEE is the highest throughput any
    # rung actually achieved: on clean rungs achieved == offered (an
    # under-estimate of capacity), on saturated rungs achieved == the
    # pool's real service rate — so the max across the sweep is the
    # saturation throughput. A barely-dirty rung alone would lag it,
    # under-dosing the 3x-knee drill below.
    ladder = {"rungs": [], "max_sustained_qps": None, "knee_qps": None}
    rate = args.rate
    router = main_router()
    dirty_seen = False
    try:
        for _ in range(args.ladder_rungs):
            counts, per_class, wall, _g = _drive_overload(
                router, trace, prompts, offered / rate,
                args.request_timeout)
            achieved = counts["ok"] / wall if wall > 0 else 0.0
            clean = (counts["shed"] == 0 and counts["timeout"] == 0
                     and counts["error"] == 0)
            ladder["rungs"].append({
                "rate": round(rate, 1),
                "achieved_qps": round(achieved, 1),
                "counts": counts, "clean": clean})
            if clean and not dirty_seen:
                ladder["max_sustained_qps"] = round(achieved, 1)
            dirty_seen = dirty_seen or not clean
            ladder["knee_qps"] = max(ladder["knee_qps"] or 0.0,
                                     round(achieved, 1))
            rate *= args.ladder_growth
    finally:
        router.close()
    if ladder["max_sustained_qps"] is None:
        failures.append("no clean rung: the base --rate already sheds "
                        "— the clean side of the knee was never seen; "
                        "lower --rate")
    if not dirty_seen:
        # every rung clean = the ladder topped out UNDER the knee, so
        # "3x the knee" would not actually overload the pool and the
        # drill below would assert against thin air
        failures.append(
            "ladder exhausted --ladder-rungs with every rung clean — "
            "the knee was never crossed; raise --ladder-rungs or "
            "--rate")
    knee = float(ladder["knee_qps"] or args.rate)

    # ---- phase 2: flash-crowd drill at 3x the knee -------------------
    drill_rate = 3.0 * knee
    router = main_router()
    try:
        counts, per_class, wall, goodput_main = _drive_overload(
            router, trace, prompts, offered / drill_rate,
            args.request_timeout)
        # recovery: with the queues drained, pressure is 0 — every
        # brownout step must walk back down (counted) within seconds
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            levels = [r.engine.brownout.level()
                      for r in router.pool.replicas()
                      if getattr(r.engine, "brownout", None) is not None]
            if all(lv == 0 for lv in levels):
                break
            time.sleep(0.05)
        stats = router.stats()
        merged = stats.get("cluster") or {}

        def both(counter):
            return stats.get(counter, 0) + merged.get(counter, 0)

        shed_by_class = {c: both(f"shed_{c}_total")
                         for c in _GOODPUT_WEIGHTS}
        engaged = merged.get("brownout_engage_total", 0)
        reverted = merged.get("brownout_revert_total", 0)
        levels = [r.engine.brownout.level()
                  for r in router.pool.replicas()
                  if getattr(r.engine, "brownout", None) is not None]
        drill = {
            "rate": round(drill_rate, 1),
            "overload_factor": 3.0,
            "counts": counts,
            "per_class": per_class,
            "shed_by_class": shed_by_class,
            "brownout": {"engaged": engaged, "reverted": reverted,
                         "final_levels": levels,
                         "steps": {k: merged.get(k, 0) for k in
                                   ("brownout_cap_max_new_total",
                                    "brownout_spec_off_total",
                                    "brownout_chunk_defer_total")}},
            "router_overload": stats.get("overload"),
        }
        if counts["error"]:
            failures.append(f"drill: {counts['error']} request(s) "
                            "ended in an untyped/unexpected error — "
                            "overload must stay typed")
        if counts["timeout"]:
            failures.append(f"drill: {counts['timeout']} admitted "
                            "request(s) timed out — admission let in "
                            "more than the pool could serve")
        if shed_by_class["interactive"] != 0:
            failures.append(
                f"drill: {shed_by_class['interactive']} interactive-"
                "tier shed(s) at 3x the knee — priority shedding must "
                "protect the interactive tier")
        if shed_by_class["batch"] == 0:
            failures.append("drill: zero batch-tier sheds at 3x the "
                            "knee — the pool should be shedding batch "
                            "traffic first")
        if engaged == 0:
            failures.append("drill: brownout never engaged at 3x the "
                            "knee — the pressure signal is dead")
        if reverted != engaged or any(lv != 0 for lv in levels):
            failures.append(
                f"drill: brownout did not fully revert (engaged "
                f"{engaged}, reverted {reverted}, final levels "
                f"{levels}) — every degradation step must be undone "
                "on recovery")

        # ---- phase 3: retry-storm teeth (closed loop) ----------------
        before = router.stats()
        budget_cap = 4
        router.retry_budget = (None if flat_main
                               else RetryBudget(capacity=budget_cap))
        storm_calls, storm_ok, storm_exhausted, storm_untyped = 8, 0, 0, 0
        try:
            for _ in range(storm_calls):
                # one dropped answer per request: the retry must pass
                # the budget gate (re-armed so firings never burn
                # through a single call's whole failover ladder)
                faultinject.arm("serving_retry_storm", at=0, times=1)
                try:
                    router.infer(prompts[0],
                                 timeout=args.request_timeout,
                                 priority="standard")
                    storm_ok += 1
                except RetryBudgetExhaustedError:
                    storm_exhausted += 1
                except Exception:               # noqa: BLE001
                    storm_untyped += 1
        finally:
            faultinject.disarm("serving_retry_storm")
        after = router.stats()
        storm_retries = (after.get("failovers_total", 0)
                         - before.get("failovers_total", 0))
        recovered_ok = True
        try:
            router.infer(prompts[0], timeout=args.request_timeout,
                         priority="standard")
        except Exception:                       # noqa: BLE001
            recovered_ok = False
        storm = {"calls": storm_calls, "ok": storm_ok,
                 "budget_capacity": budget_cap,
                 "retries": storm_retries,
                 "exhausted_failfast": storm_exhausted,
                 "untyped": storm_untyped,
                 "exhausted_counter_delta":
                     (after.get("retry_budget_exhausted_total", 0)
                      - before.get("retry_budget_exhausted_total", 0)),
                 "recovered_after_disarm": recovered_ok}
        if storm_untyped:
            failures.append(f"storm: {storm_untyped} call(s) died "
                            "untyped under serving_retry_storm")
        if storm_retries > budget_cap:
            failures.append(
                f"storm: {storm_retries} retries burned against a "
                f"budget of {budget_cap} — the retry budget is not "
                "bounding amplification")
        if storm_exhausted == 0:
            failures.append("storm: RetryBudgetExhaustedError never "
                            "surfaced — beyond-budget retries must "
                            "fail fast typed, not keep retrying")
        if not recovered_ok:
            failures.append("storm: traffic did not recover after the "
                            "fault was disarmed")
    finally:
        faultinject.disarm("serving_retry_storm")
        router.close()

    # ---- phase 4: flat-shed baseline at the same 3x rate -------------
    router = flat_router()
    try:
        flat_counts, flat_per_class, _w, goodput_flat = _drive_overload(
            router, trace, prompts, offered / drill_rate,
            args.request_timeout)
    finally:
        router.close()
    ratio = (round(goodput_main / goodput_flat, 3)
             if goodput_flat > 0 else None)
    if ratio is None or ratio <= 1.0:
        failures.append(
            f"goodput: graceful/flat priority-weighted ratio {ratio} "
            "must exceed 1.0 — priority shedding + brownout must BUY "
            "goodput over flat shedding at the same overload")

    report = {
        "mode": "overload",
        "flat_shed": flat_main,
        "replicas": replicas,
        "requests": n,
        "hard_ceiling": ceiling,
        "trace": {"file": args.trace_file,
                  "offered_qps": round(offered, 2),
                  "classes": {c: trace["classes"].count(c)
                              for c in _GOODPUT_WEIGHTS}},
        "ladder": ladder,
        "drill": drill,
        "storm": storm,
        "flat_baseline": {"counts": flat_counts,
                          "per_class": flat_per_class},
        "goodput": {"graceful": goodput_main, "flat": goodput_flat,
                    "ratio": ratio, "weights": _GOODPUT_WEIGHTS},
        "bench_records": [
            {"metric": "serving_overload_knee_qps",
             "value": ladder["knee_qps"], "unit": "req/s",
             "backend": "cpu", "replicas": replicas,
             "hard_ceiling": ceiling},
            {"metric": "serving_overload_goodput_ratio",
             "value": ratio, "unit": "x", "backend": "cpu",
             "replicas": replicas, "overload_factor": 3.0,
             "weights": _GOODPUT_WEIGHTS},
        ],
        "failures": failures,
    }
    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if args.json:
        print(text)
    else:
        print(f"servebench --overload{' --overload-flat-shed' if flat_main else ''}: "
              f"knee {ladder['knee_qps']} req/s, drill at "
              f"{drill['rate']} req/s -> sheds {drill['shed_by_class']}, "
              f"brownout engaged {drill['brownout']['engaged']}/"
              f"reverted {drill['brownout']['reverted']}, storm "
              f"retries {storm['retries']}/{storm['budget_capacity']} "
              f"(fail-fast {storm['exhausted_failfast']}), goodput "
              f"ratio {ratio}x")
    if failures:
        for f in failures:
            print(f"servebench --overload: FAILED — {f}",
                  file=sys.stderr)
        return 1
    return 0


def cold_start_main(args):
    """--cold-start: engine construction+warmup wall-clock, storeless
    vs cold (empty artifact store — compiles AND seeds) vs warm
    (seeded store — loads only). The warm replica must perform ZERO
    XLA compiles and return bit-exact outputs vs the storeless engine;
    the BENCH records are ``serving_cold_start_s`` (warm wall-clock)
    and ``serving_cold_start_speedup`` (storeless / warm — the
    autoscaling spin-up win). ``--decode`` measures the decode engine
    the same way (``llama_decode_cold_start_*``)."""
    import shutil
    import tempfile

    workdir = tempfile.mkdtemp(prefix="coldstart_")
    try:
        if args.decode:
            report, failures = _cold_start_decode(args, workdir)
        else:
            report, failures = _cold_start_classifier(args, workdir)
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if args.json:
        print(text)
    else:
        r = report
        print(f"servebench --cold-start{' --decode' if args.decode else ''} "
              f"{r['model']}: storeless {r['storeless_warmup_s']}s, "
              f"cold(seed) {r['cold_seed_s']}s, "
              f"warm {r['warm_warmup_s']}s "
              f"({r['cold_start_speedup']}x), "
              f"{r['warm_compiles']} warm compiles, "
              f"bitexact={r['bitexact']}")
    for f in failures:
        print(f"servebench --cold-start: {f}", file=sys.stderr)
    if failures:
        return 1
    if args.assert_speedup is not None and \
            report["cold_start_speedup"] < args.assert_speedup:
        print(f"servebench --cold-start: speedup "
              f"{report['cold_start_speedup']}x below the "
              f"--assert-speedup {args.assert_speedup}x floor",
              file=sys.stderr)
        return 1
    return 0


def _cold_start_records(prefix, storeless_s, cold_s, warm_s, extra):
    speedup = round(storeless_s / warm_s, 2) if warm_s > 0 else None
    base = {"unit": None, "backend": "cpu",
            "storeless_warmup_s": round(storeless_s, 3),
            "cold_seed_s": round(cold_s, 3),
            "warm_warmup_s": round(warm_s, 3)}
    base.update(extra)
    recs = [dict(base, metric=f"{prefix}_cold_start_s",
                 value=round(warm_s, 3), unit="s"),
            dict(base, metric=f"{prefix}_cold_start_speedup",
                 value=speedup, unit="x")]
    return recs, speedup


def _cold_start_classifier(args, workdir):
    zp, infer, fetch, per_row, scope, feeds = _setup(args)
    model_dir = os.path.join(workdir, "model")
    store_dir = os.path.join(workdir, "store")
    startup_exe = fluid.Executor(fluid.CPUPlace())
    # racecheck: ok(global-mutation) — driver-thread export before any
    # serving thread starts; bench-private scope
    with fluid.scope_guard(scope):
        fluid.io.save_inference_model(
            model_dir, zp.feed_names,
            fetch if isinstance(fetch[0], str)
            else [v.name for v in fetch],
            startup_exe, main_program=zp.main,
            serving_buckets=serving.BucketSpec(
                batch_sizes=_bucket_sizes(args.max_batch)))

    def build(compile_store):
        t0 = time.perf_counter()
        eng = serving.ServingEngine.from_saved_model(
            model_dir, compile_store=compile_store, auto_start=False)
        warm = eng.warmup()
        return eng, warm, time.perf_counter() - t0

    failures = []
    ref_eng, _, storeless_s = build(False)          # today's cost
    cold_eng, cold_warm, cold_s = build(store_dir)  # compiles + seeds
    warm_eng, warm_warm, warm_s = build(store_dir)  # loads only
    warm_compiles = warm_eng.exe.total_compiles()
    if warm_compiles != 0:
        failures.append(
            f"warm replica compiled {warm_compiles} executables — "
            f"expected ZERO ({warm_eng.exe.compile_counts()})")
    # bit-exactness: the warm engine's executables came off disk; its
    # rows must equal the storeless engine's bit for bit
    bitexact = True
    from paddle_tpu.core.executor import scope_guard as _sg
    for feed in feeds[:8]:
        # racecheck: ok(run-without-scope, global-mutation) — parity
        # probe in the driver thread while engines are quiesced; each
        # guard binds that engine's own scope
        with _sg(ref_eng.scope):
            a = ref_eng.exe.run(ref_eng.program, feed=feed,
                                fetch_list=ref_eng.fetch_list,
                                mode="test")
        # racecheck: ok(run-without-scope, global-mutation) — ditto
        with _sg(warm_eng.scope):
            b = warm_eng.exe.run(warm_eng.program, feed=feed,
                                 fetch_list=warm_eng.fetch_list,
                                 mode="test")
        for x, y in zip(a, b):
            if not np.array_equal(np.asarray(x), np.asarray(y)):
                bitexact = False
    if not bitexact:
        failures.append("store-loaded outputs diverged from the "
                        "storeless engine (must be bit-exact)")
    store_stats = warm_eng.exe.store_stats()
    for eng in (ref_eng, cold_eng, warm_eng):
        eng.close()
    recs, speedup = _cold_start_records(
        "serving", storeless_s, cold_s, warm_s,
        {"model": args.model, "signatures": warm_warm["signatures"],
         "store_hits": store_stats["hits_total"]})
    report = {"model": args.model, "mode": "classifier",
              "storeless_warmup_s": round(storeless_s, 3),
              "cold_seed_s": round(cold_s, 3),
              "warm_warmup_s": round(warm_s, 3),
              "cold_start_speedup": speedup,
              "warm_compiles": warm_compiles,
              "cold_warmup": cold_warm, "warm_warmup": warm_warm,
              "bitexact": bitexact,
              "artifact_store": store_stats,
              "bench_records": recs}
    return report, failures


def _cold_start_decode(args, workdir):
    from paddle_tpu import serving

    args.requests = min(args.requests, 4)
    cfg, buckets, scope, exe, gen, prompts = _decode_model(args)
    store_dir = os.path.join(workdir, "store")

    def build(compile_store):
        t0 = time.perf_counter()
        eng = serving.DecodeEngine(
            cfg, scope=scope, place=fluid.CPUPlace(),
            config=_decode_config(args, buckets),
            compile_store=compile_store, auto_start=False)
        warm = eng.warmup()
        return eng, warm, time.perf_counter() - t0

    failures = []
    ref_eng, _, storeless_s = build(False)
    cold_eng, cold_warm, cold_s = build(store_dir)
    warm_eng, warm_warm, warm_s = build(store_dir)
    warm_compiles = warm_eng.exe.total_compiles()
    if warm_compiles != 0:
        failures.append(
            f"warm decode replica compiled {warm_compiles} "
            f"executables — expected ZERO "
            f"({warm_eng.exe.compile_counts()})")
    bitexact = True
    ref_eng.start()
    warm_eng.start()
    for p in prompts[:2]:
        a = np.asarray(ref_eng.generate(p, max_new=args.max_new))
        b = np.asarray(warm_eng.generate(p, max_new=args.max_new))
        if not np.array_equal(a, b):
            bitexact = False
    if not bitexact:
        failures.append("store-loaded decode tokens diverged from the "
                        "storeless engine (must be bit-exact)")
    store_stats = warm_eng.exe.store_stats()
    for eng in (ref_eng, cold_eng, warm_eng):
        eng.close()
    recs, speedup = _cold_start_records(
        "llama_decode", storeless_s, cold_s, warm_s,
        {"model": "llama_tiny", "programs": warm_warm["programs"],
         "store_hits": store_stats["hits_total"]})
    report = {"model": "llama_tiny", "mode": "decode",
              "storeless_warmup_s": round(storeless_s, 3),
              "cold_seed_s": round(cold_s, 3),
              "warm_warmup_s": round(warm_s, 3),
              "cold_start_speedup": speedup,
              "warm_compiles": warm_compiles,
              "cold_warmup": cold_warm, "warm_warmup": warm_warm,
              "bitexact": bitexact,
              "artifact_store": store_stats,
              "bench_records": recs}
    return report, failures


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="serving load benchmark: batched vs single-request")
    ap.add_argument("--model", default="mnist_mlp",
                    choices=zoo.zoo_model_names())
    ap.add_argument("--requests", type=int, default=128)
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--max-batch", type=int, default=None,
                    help="batch bucket ceiling (default 8) / decode "
                         "slots (default 16 with --decode)")
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--assert-speedup", type=float, default=None,
                    help="exit 1 unless batched/baseline >= this")
    ap.add_argument("--chaos", action="store_true",
                    help="fault-injection drill instead of the "
                         "speedup race (selfcheck stage 4)")
    ap.add_argument("--cold-start", action="store_true",
                    help="artifact-store cold-start benchmark: "
                         "construction+warmup storeless vs warm "
                         "(zero-compile) replica; with --decode, the "
                         "decode engine (selfcheck stage 8)")
    ap.add_argument("--decode", action="store_true",
                    help="continuous-batching decode benchmark on a "
                         "tiny llama (selfcheck stage 6)")
    ap.add_argument("--max-new", type=int, default=32,
                    help="tokens generated per request (--decode)")
    ap.add_argument("--decode-block", type=int, default=16,
                    help="decode steps per dispatch (--decode)")
    ap.add_argument("--prefill-batch", type=int, default=8,
                    help="same-bucket prompts prefilled per dispatch "
                         "(--decode)")
    ap.add_argument("--spec", action="store_true",
                    help="speculative engine mode, perfect draft "
                         "(--decode)")
    ap.add_argument("--slo", action="store_true",
                    help="with --decode: SLO-attainment benchmark on "
                         "a mixed short/long interference trace — "
                         "FIFO vs SLO scheduler vs disaggregated "
                         "prefill/decode, plus the handoff chaos "
                         "drill (selfcheck stage 13)")
    ap.add_argument("--slo-force-fifo", action="store_true",
                    help="run the --slo comparison arm on the FIFO "
                         "scheduler — the attainment gate must then "
                         "FAIL (selfcheck's toothless-gate check)")
    ap.add_argument("--skip-disagg", action="store_true",
                    help="with --slo: skip the disaggregated pool arm "
                         "and its chaos drill")
    ap.add_argument("--opt-compare", action="store_true",
                    help="with --decode: also measure opt-on vs "
                         "opt-off engine throughput (classifier mode "
                         "always records the comparison)")
    ap.add_argument("--skip-baseline", action="store_true",
                    help="skip the sequential baseline (--decode)")
    ap.add_argument("--arrival", choices=("closed", "poisson", "trace"),
                    default="closed",
                    help="closed loop (default), open-loop Poisson "
                         "arrivals, or trace replay (bursty, "
                         "heavy-tailed; --trace-file to replay a "
                         "recorded trace)")
    ap.add_argument("--rate", type=float, default=50.0,
                    help="open-loop arrival rate, requests/s (trace "
                         "mode: the ladder's base rate)")
    ap.add_argument("--request-timeout", type=float, default=10.0,
                    help="per-request deadline in open-loop mode (s)")
    ap.add_argument("--cluster", type=int, default=0,
                    help="serve through a replica pool of N engines "
                         "behind the cluster router (0 = single "
                         "engine)")
    ap.add_argument("--remote", type=int, default=0,
                    help="N>0: drive N loopback ReplicaServers over "
                    "the socket fabric (serving_remote_qps + the "
                    "zero-compile cold/wire provisioning gates); "
                    "with --chaos, the partition drill instead")
    ap.add_argument("--canary", action="store_true",
                    help="versioned-deployment drill: canary traffic "
                         "shifting, numerics-gated promotion, instant "
                         "zero-compile rollback (selfcheck stage 10)")
    ap.add_argument("--rolling-restart", action="store_true",
                    help="with --cluster: roll-restart every replica "
                         "under sustained mixed load and assert zero "
                         "losses (selfcheck stage 7)")
    ap.add_argument("--overload", action="store_true",
                    help="graceful-degradation referee: knee ladder, "
                         "3x-knee flash-crowd drill (priority shed "
                         "ordering + brownout round-trip), retry-"
                         "storm budget teeth, and the flat-shed "
                         "goodput comparison (selfcheck stage 14)")
    ap.add_argument("--overload-flat-shed", action="store_true",
                    help="run the --overload drill on the static-"
                         "bound flat-shed config — the shed-ordering/"
                         "brownout/goodput gates must then FAIL "
                         "(selfcheck's toothless-gate check)")
    ap.add_argument("--max-queue", type=int, default=None,
                    help="per-engine admission bound (default: scaled "
                         "to --requests; trace mode defaults to 32 so "
                         "the shed knee is observable)")
    ap.add_argument("--trace-file", default=None,
                    help="recorded arrival trace to replay (JSON "
                         "offsets) instead of the synthetic one")
    ap.add_argument("--burst-factor", type=float, default=4.0,
                    help="synthetic-trace burst rate multiplier")
    ap.add_argument("--ladder-rungs", type=int, default=4,
                    help="trace mode: max rate rungs to try")
    ap.add_argument("--ladder-growth", type=float, default=1.6,
                    help="trace mode: rate multiplier per rung")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    if args.max_batch is None:
        args.max_batch = 16 if args.decode else 8

    if args.cold_start:
        return cold_start_main(args)
    if args.canary:
        return canary_main(args)
    if args.chaos and args.remote:
        return remote_chaos_main(args)
    if args.remote:
        return remote_main(args)
    if args.chaos and args.cluster:
        return chaos_cluster_main(args)
    if args.chaos:
        return chaos_main(args)
    if args.overload:
        return overload_main(args)
    if args.arrival == "trace":
        return trace_main(args)
    if args.decode and args.slo:
        return slo_main(args)
    if args.decode:
        return decode_main(args)
    if args.cluster:
        return cluster_main(args)

    zp, infer, fetch, per_row, scope, feeds = _setup(args)

    # ---- baseline: one synchronous Executor.run per request ----------
    base_exe = fluid.Executor(fluid.CPUPlace())
    # racecheck: ok(global-mutation, run-without-scope) — synchronous
    # single-threaded baseline in the driver; bench-private scope
    with fluid.scope_guard(scope):
        base_exe.run(infer, feed=feeds[0], fetch_list=fetch,
                     mode="test")                       # compile once
        t0 = time.perf_counter()
        # racecheck: ok(run-without-scope) — same private scope_guard
        baseline = [np.asarray(base_exe.run(infer, feed=f,
                                            fetch_list=fetch,
                                            mode="test")[0])
                    for f in feeds]
        base_s = time.perf_counter() - t0
    base_rps = args.requests / base_s

    # ---- batched: concurrent clients through the serving engine ------
    eng = serving.ServingEngine(
        infer, zp.feed_names, fetch, scope=scope,
        place=fluid.CPUPlace(),
        buckets=serving.BucketSpec(
            batch_sizes=_bucket_sizes(args.max_batch)),
        config=serving.ServingConfig(
            max_wait_ms=args.max_wait_ms,
            max_queue=max(2 * args.requests, 64)))
    arrival_counts = None
    try:
        warm = eng.warmup()
        if args.arrival == "poisson":
            # open loop: arrivals don't slow down with the server, so
            # overload surfaces as shed/timeout counts, not stretched
            # client think time
            arrival_counts, served, batched_s, _lats = open_loop_drive(
                lambda f: eng.submit(f, timeout=args.request_timeout),
                feeds,
                poisson_arrivals(len(feeds), args.rate,
                                 np.random.RandomState(7)),
                result_timeout=60.0)
            completed = arrival_counts["ok"]
        else:
            with ThreadPoolExecutor(args.concurrency) as pool:
                t0 = time.perf_counter()
                served = list(pool.map(
                    lambda f: eng.infer(f, timeout=60.0), feeds))
                batched_s = time.perf_counter() - t0
            completed = len(served)
        eng.assert_no_recompiles()
        # opt-on vs opt-off (closed loop only: open-loop throughput is
        # arrival-bound, so the comparison would measure the generator)
        opt_record = None
        if args.arrival == "closed":
            opt_record = _opt_compare_classifier(
                args, eng, infer, zp, fetch, scope, feeds)
        stats = eng.stats()
    finally:
        eng.close()
    batched_rps = completed / batched_s if batched_s > 0 else 0.0

    if per_row:
        pairs = [(ref, got) for ref, got in zip(baseline, served)
                 if got is not None]
        bitexact = sum(
            1 for ref, got in pairs
            if np.array_equal(ref, np.asarray(got[0])))
        mismatches = sum(
            1 for ref, got in pairs
            if not np.allclose(ref, np.asarray(got[0]),
                               rtol=1e-5, atol=1e-7))
    else:
        # batch-mean fetches aren't comparable across batch shapes
        bitexact, mismatches = None, None
    speedup = batched_rps / base_rps
    report = {
        "model": args.model,
        "requests": args.requests,
        "arrival": args.arrival,
        "arrival_counts": arrival_counts,
        "concurrency": args.concurrency,
        "fetch": list(fetch if isinstance(fetch[0], str)
                      else [v.name for v in fetch]),
        "per_row_fetch": per_row,
        "warmup": warm,
        "baseline_rps": round(base_rps, 1),
        "batched_rps": round(batched_rps, 1),
        "speedup": round(speedup, 2),
        "bitexact_requests": bitexact,
        "mismatched_requests": mismatches,
        "bench_record": opt_record,
        "serving_stats": stats,
    }
    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if args.json:
        print(text)
    else:
        opt_line = ""
        if opt_record is not None:
            opt_line = (f", opt {opt_record['opt_on_rps']:.0f} vs "
                        f"{opt_record['opt_off_rps']:.0f} req/s "
                        f"({opt_record['value']}x)")
        print(f"servebench {args.model}: baseline {base_rps:.0f} req/s, "
              f"batched {batched_rps:.0f} req/s ({speedup:.2f}x), "
              f"fill {stats['batch_fill_ratio']}, "
              f"p95 {stats['request_latency']['p95_ms']} ms, "
              f"{mismatches} mismatches, "
              f"{warm['compiles']} warmup compiles, 0 recompiles"
              f"{opt_line}")
    if mismatches:
        print(f"servebench: CORRECTNESS DROPPED — {mismatches} of "
              f"{args.requests} requests diverged from the "
              "single-request baseline", file=sys.stderr)
        return 1
    if args.assert_speedup is not None and args.arrival == "closed" \
            and speedup < args.assert_speedup:
        # open-loop throughput is bounded by the arrival rate, not the
        # server, so the closed-loop speedup floor doesn't apply there
        print(f"servebench: speedup {speedup:.2f}x below the "
              f"--assert-speedup {args.assert_speedup}x floor",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
