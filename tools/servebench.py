#!/usr/bin/env python
"""servebench — serving load generator: batched vs single-request.

Builds a tiny model-zoo entry, stands up a
``paddle_tpu.serving.ServingEngine`` over it (warmup pre-compiles
every declared bucket), then drives the same request set two ways:

1. **baseline** — the pre-serving story: one synchronous
   ``Executor.run`` per request, one device dispatch each.
2. **batched** — ``--concurrency`` client threads submitting through
   the engine, which coalesces them into bucket-padded micro-batches.

Reports requests/s for both, the speedup, the engine's metrics
snapshot (batch-fill ratio, latency percentiles), and a correctness
sweep: every request's served rows must match its single-request rows
(the per-row fetch is the cross_entropy input — the model's
prediction head — so batch-mean scalars never blur the comparison).
The cross-shape comparison is tolerance-based (rtol 1e-5): XLA
legitimately re-tiles a matmul per batch shape, so batch-8 rows can
differ from batch-1 rows by an ulp — bit-for-bit equality holds
WITHIN a bucket shape and is pinned that way in tests/test_serving.py;
across buckets "zero dropped-correctness" means zero beyond-float-
tolerance divergences. ``assert_no_recompiles`` additionally proves
zero XLA compiles happened during traffic.

Usage:
  python tools/servebench.py [--model mnist_mlp] [--requests 128]
      [--concurrency 16] [--max-batch 8] [--max-wait-ms 2.0]
      [--assert-speedup 1.0] [--json] [--out FILE]

Exit 0 on success; exit 1 when correctness drops or the measured
speedup falls below ``--assert-speedup`` (tools/selfcheck.sh stage 3
gates on both). CPU-only, seconds.
"""
import argparse
import json
import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.models import zoo  # noqa: E402
from paddle_tpu import serving  # noqa: E402


def synth_feed(program, feed_names, batch, rng):
    """Random single-request feed shaped from the program's data vars
    (-1 dims become ``batch``; int vars get small non-negative ids)."""
    gb = program.global_block()
    feed = {}
    for name in feed_names:
        var = gb.var(name)
        shape = [batch if (d is None or d < 0) else d for d in var.shape]
        shape[0] = batch
        dtype = str(var.dtype)
        if "int" in dtype:
            feed[name] = rng.randint(0, 2, size=shape).astype(dtype)
        else:
            feed[name] = rng.randn(*shape).astype(dtype)
    return feed


# loss-op input slot that carries the model's per-row prediction head
_PRED_SLOTS = {"cross_entropy": "X", "softmax_with_cross_entropy":
               "Logits", "square_error_cost": "X"}


def row_fetch(program, fallback):
    """The per-row output to serve: the first loss op's prediction
    input ([rows, ...] — row independent, so batched vs single
    comparisons are exact). Falls back to the zoo fetch list when no
    known loss op exists — correctness is then NOT comparable (those
    fetches are batch-mean scalars) and the sweep is skipped."""
    for op in program.global_block().ops:
        slot = _PRED_SLOTS.get(op.type)
        if slot is not None:
            return [op.input(slot)[0]], True
    return fallback, False


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="serving load benchmark: batched vs single-request")
    ap.add_argument("--model", default="mnist_mlp",
                    choices=zoo.zoo_model_names())
    ap.add_argument("--requests", type=int, default=128)
    ap.add_argument("--concurrency", type=int, default=16)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--assert-speedup", type=float, default=None,
                    help="exit 1 unless batched/baseline >= this")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    fluid.force_cpu()
    zp = zoo.build_zoo_program(args.model)
    infer = zp.main.clone(for_test=True)
    fetch, per_row = row_fetch(infer, zp.fetch_list)
    scope = fluid.Scope()
    startup_exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        startup_exe.run(zp.startup)

    rng = np.random.RandomState(0)
    feeds = [synth_feed(infer, zp.feed_names, 1, rng)
             for _ in range(args.requests)]

    # ---- baseline: one synchronous Executor.run per request ----------
    base_exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        base_exe.run(infer, feed=feeds[0], fetch_list=fetch,
                     mode="test")                       # compile once
        t0 = time.perf_counter()
        baseline = [np.asarray(base_exe.run(infer, feed=f,
                                            fetch_list=fetch,
                                            mode="test")[0])
                    for f in feeds]
        base_s = time.perf_counter() - t0
    base_rps = args.requests / base_s

    # ---- batched: concurrent clients through the serving engine ------
    sizes = []
    b = 1
    while b < args.max_batch:
        sizes.append(b)
        b *= 2
    sizes.append(args.max_batch)
    eng = serving.ServingEngine(
        infer, zp.feed_names, fetch, scope=scope,
        place=fluid.CPUPlace(),
        buckets=serving.BucketSpec(batch_sizes=tuple(sizes)),
        config=serving.ServingConfig(
            max_wait_ms=args.max_wait_ms,
            max_queue=max(2 * args.requests, 64)))
    try:
        warm = eng.warmup()
        with ThreadPoolExecutor(args.concurrency) as pool:
            t0 = time.perf_counter()
            served = list(pool.map(
                lambda f: eng.infer(f, timeout=60.0), feeds))
            batched_s = time.perf_counter() - t0
        eng.assert_no_recompiles()
        stats = eng.stats()
    finally:
        eng.close()
    batched_rps = args.requests / batched_s

    if per_row:
        bitexact = sum(
            1 for ref, got in zip(baseline, served)
            if np.array_equal(ref, np.asarray(got[0])))
        mismatches = sum(
            1 for ref, got in zip(baseline, served)
            if not np.allclose(ref, np.asarray(got[0]),
                               rtol=1e-5, atol=1e-7))
    else:
        # batch-mean fetches aren't comparable across batch shapes
        bitexact, mismatches = None, None
    speedup = batched_rps / base_rps
    report = {
        "model": args.model,
        "requests": args.requests,
        "concurrency": args.concurrency,
        "fetch": list(fetch if isinstance(fetch[0], str)
                      else [v.name for v in fetch]),
        "per_row_fetch": per_row,
        "warmup": warm,
        "baseline_rps": round(base_rps, 1),
        "batched_rps": round(batched_rps, 1),
        "speedup": round(speedup, 2),
        "bitexact_requests": bitexact,
        "mismatched_requests": mismatches,
        "serving_stats": stats,
    }
    text = json.dumps(report, indent=2)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if args.json:
        print(text)
    else:
        print(f"servebench {args.model}: baseline {base_rps:.0f} req/s, "
              f"batched {batched_rps:.0f} req/s ({speedup:.2f}x), "
              f"fill {stats['batch_fill_ratio']}, "
              f"p95 {stats['request_latency']['p95_ms']} ms, "
              f"{mismatches} mismatches, "
              f"{warm['compiles']} warmup compiles, 0 recompiles")
    if mismatches:
        print(f"servebench: CORRECTNESS DROPPED — {mismatches} of "
              f"{args.requests} requests diverged from the "
              "single-request baseline", file=sys.stderr)
        return 1
    if args.assert_speedup is not None and speedup < args.assert_speedup:
        print(f"servebench: speedup {speedup:.2f}x below the "
              f"--assert-speedup {args.assert_speedup}x floor",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
