#!/usr/bin/env python
"""lintall — every static gate in ONE process, one aggregated verdict.

Runs the full static-analysis battery the way selfcheck used to run it
as four separate interpreter launches, but in a single process with a
single JSON document at the end:

  racelint        analysis/racecheck.py   — concurrency contracts
  fluidlint       --all-models            — IR verifier over the zoo
  numlint         --all-models            — numerics, plain
  numlint-amp-o2  --all-models --amp O2   — numerics under AMP O2
  protolint       analysis/protocheck.py  — distributed-fabric contracts

Everything here is host-CPU static analysis (the AST analyzers import
nothing from the analyzed tree; the zoo sweeps build IR but never
compile), so one process amortizes the interpreter + import cost that
dominated the old four-launch stage layout. Each gate's own CLI is
imported and called in-process with its stdout captured — lintall has
no analysis logic of its own, so the standalone CLIs and this
aggregate can never disagree.

Output: per-gate one-liners, or with --json one document::

    {"target": "lintall", "ok": bool, "n_failed": int,
     "gates": {name: {"ok": bool, "rc": int, "seconds": float,
                      "summary": str, "doc": {...full gate JSON...}}}}

--out DIR additionally writes each gate's own JSON document to
DIR/<gate>.json (the files selfcheck used to produce stage by stage).
--skip NAME ... skips gates (e.g. --skip numlint-amp-o2 for a quick
local loop). Exit status is 1 iff any ran gate failed — the selfcheck
stage 0 gate. The inverted "teeth" fixtures (a jarred bug must still
FAIL each lint) stay in selfcheck as direct single-file invocations;
lintall only aggregates the clean-tree sweeps.
"""
import argparse
import contextlib
import io
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# nothing below may touch an accelerator; pin before any jax import
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _run_cli(mod_name, argv):
    """Import tools/<mod_name>.py and call its main(argv) with stdout
    captured; returns (rc, parsed-json-or-raw, seconds)."""
    import importlib
    mod = importlib.import_module(f"tools.{mod_name}")
    buf = io.StringIO()
    t0 = time.monotonic()
    with contextlib.redirect_stdout(buf):
        rc = mod.main(argv)
    dt = time.monotonic() - t0
    out = buf.getvalue()
    try:
        doc = json.loads(out)
    except ValueError:
        doc = {"raw": out}
    return int(rc or 0), doc, dt


def _summary(name, doc):
    if "raw" in doc:
        return "(unparsed output)"
    if name in ("racelint", "protolint"):
        s = (f"{doc['files']} files, {doc['error_count']} errors, "
             f"{len(doc.get('suppressed', []))} suppressed")
        if "knobs" in doc:
            s += f", {len(doc['knobs'])} knobs"
        return s
    if name == "fluidlint":
        warns = sum(m.get("n_warnings", 0)
                    for m in doc["models"].values())
        return (f"{doc['n_models']} models, {doc['n_errors']} errors, "
                f"{warns} warnings")
    # numlint variants
    safe = sum(1 for m in doc["models"].values()
               if m.get("finite_safe"))
    return (f"{doc['n_models']} models, {doc['n_errors']} unsuppressed "
            f"errors, {safe} finite-safe")


GATES = (
    ("racelint", "racelint", ["--json"]),
    ("fluidlint", "fluidlint", ["--all-models", "--json"]),
    ("numlint", "numlint", ["--all-models", "--json"]),
    ("numlint-amp-o2", "numlint",
     ["--all-models", "--json", "--amp", "O2"]),
    ("protolint", "protolint", ["--json"]),
)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="lintall", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="one aggregated JSON document for CI")
    ap.add_argument("--out", default=None,
                    help="also write each gate's own JSON to "
                         "DIR/<gate>.json")
    ap.add_argument("--skip", nargs="*", default=(),
                    choices=[g[0] for g in GATES],
                    help="gate names to skip")
    args = ap.parse_args(argv)

    if args.out:
        os.makedirs(args.out, exist_ok=True)

    gates = {}
    n_failed = 0
    for name, mod, cli in GATES:
        if name in args.skip:
            gates[name] = {"ok": True, "rc": 0, "seconds": 0.0,
                           "summary": "skipped", "skipped": True}
            continue
        try:
            rc, doc, dt = _run_cli(mod, list(cli))
        except Exception as e:   # a crashed gate IS a failed gate
            rc, doc, dt = 1, {"crash": repr(e)}, 0.0
        summary = (doc.get("crash") and f"CRASH: {doc['crash']}"
                   or _summary(name, doc))
        gates[name] = {"ok": rc == 0, "rc": rc,
                       "seconds": round(dt, 3),
                       "summary": summary, "doc": doc}
        n_failed += rc != 0
        if args.out:
            with open(os.path.join(args.out, f"{name}.json"),
                      "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=2, sort_keys=True)
        if not args.as_json:
            mark = "ok  " if rc == 0 else "FAIL"
            print(f"{mark} {name:15s} {summary}  [{dt:.1f}s]")

    verdict = {"target": "lintall", "ok": n_failed == 0,
               "n_failed": n_failed, "gates": gates}
    if args.as_json:
        print(json.dumps(verdict, indent=2, sort_keys=True))
    else:
        ran = sum(1 for g in gates.values() if not g.get("skipped"))
        print(f"\nlintall: {ran} gate(s) ran, {n_failed} failed")
    return 1 if n_failed else 0


if __name__ == "__main__":
    sys.exit(main())
