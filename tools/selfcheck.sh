#!/usr/bin/env bash
# selfcheck — CI gate: the static-analysis battery over the entire
# model zoo and runtime tree, plus the dynamic smoke/chaos sweeps.
#
# Stage 0 runs `tools/lintall.py --json`: EVERY static gate in ONE
# process — racelint (concurrency, docs/RELIABILITY.md "Static
# concurrency checking"), fluidlint --all-models (IR verifier over
# the zoo), numlint --all-models plain AND under --amp O2 (numerics),
# and protolint (distributed-fabric contracts, "Static protocol
# checking") — exit 1 on ANY unsuppressed error-level finding in any
# gate. Warnings are reported but never fail. Pure host-CPU static
# analysis, one aggregated JSON verdict ($OUT/lintall.json, per-gate
# docs alongside). The PR-12 teeth fixture keeps stage 0 honest; the
# numerics and protocol teeth fixtures live in stages 11 and 15.
#
# Stage 2 runs `tools/faultsmoke.py`: one crash/resume cycle on a zoo
# model through the crash-safe checkpoint store (torn write injected
# mid-save, recovery from the newest verified serial) — the resilience
# subsystem's end-to-end gate (docs/RELIABILITY.md).
#
# Stage 3 runs `tools/servebench.py`: the serving subsystem's smoke
# (docs/SERVING.md) — a tiny zoo model behind the batching engine must
# beat the single-request baseline (--assert-speedup 1.2, deliberately
# below the ~2-3x typically measured so a loaded CI host doesn't
# flake) with zero correctness drops and zero post-warmup recompiles
# (servebench exits 1 on any of those).
#
# Stage 4 runs `tools/servebench.py --chaos`: the serving-hardening
# drill (docs/SERVING.md "Operating under failure") — device faults
# injected mid-load must lose ZERO requests (every submission ends in
# a result or a typed error), the circuit breaker must open and then
# recover, and close(drain=True) must complete all in-flight work.
#
# Usage: tools/selfcheck.sh [output-dir]
set -u -o pipefail
cd "$(dirname "$0")/.."
export JAX_PLATFORMS=cpu

OUT="${1:-/tmp/fluidlint}"
mkdir -p "$OUT"

models=$(python tools/fluidlint.py --list) || {
    echo "selfcheck: failed to enumerate the model zoo" >&2; exit 1; }

# ---- stage 0: the whole static battery, one process (lintall) --------
# racelint + fluidlint --all-models + numlint (plain, --amp O2) +
# protolint, aggregated; each gate's own JSON lands in $OUT/<gate>.json
if python tools/lintall.py --json --out "$OUT" \
        > "$OUT/lintall.json" 2> "$OUT/lintall.err"; then
    summary=$(python - "$OUT/lintall.json" <<'EOF0'
import json, sys
d = json.load(open(sys.argv[1]))
for name, g in d["gates"].items():
    print(f"ok   {name:15s} {g['summary']}")
EOF0
    )
    echo "$summary"
else
    echo "FAIL lintall — see $OUT/lintall.json / $OUT/lintall.err" >&2
    exit 1
fi
# the gate must have teeth: the jarred PR-12 scope bug still fails it
if python tools/racelint.py --json \
        tests/fixtures/racecheck_pr12_scope_bug.py \
        > "$OUT/racelint_pr12.json" 2>&1; then
    echo "FAIL racelint let the PR-12 scope-bug fixture pass — the" \
         "concurrency gate is toothless" >&2
    exit 1
else
    echo "ok   racelint rejects the PR-12 regression fixture"
fi
echo "selfcheck: static battery passed (racelint + fluidlint +" \
     "numlint + numlint/amp + protolint in one process)"

# ---- stage 2: fault-injection smoke (crash/resume cycle) -------------
if python tools/faultsmoke.py --dir "$OUT/faultsmoke" \
        > "$OUT/faultsmoke.log" 2>&1; then
    echo "ok   faultsmoke ($(tail -1 "$OUT/faultsmoke.log"))"
else
    echo "FAIL faultsmoke — see $OUT/faultsmoke.log" >&2
    exit 1
fi
echo "selfcheck: fault-injection smoke passed"

# ---- stage 3: serving smoke (batched > single-request, exact) --------
if python tools/servebench.py --model mnist_mlp --requests 96 \
        --assert-speedup 1.2 --out "$OUT/servebench.json" \
        > "$OUT/servebench.log" 2>&1; then
    echo "ok   servebench ($(tail -1 "$OUT/servebench.log"))"
else
    echo "FAIL servebench — see $OUT/servebench.log / servebench.json" >&2
    exit 1
fi
echo "selfcheck: serving smoke passed"

# ---- stage 4: serving chaos drill (no lost requests under faults) ----
if python tools/servebench.py --chaos --model mnist_mlp --requests 64 \
        --out "$OUT/servebench_chaos.json" \
        > "$OUT/servebench_chaos.log" 2>&1; then
    echo "ok   servebench --chaos ($(tail -1 "$OUT/servebench_chaos.log"))"
else
    echo "FAIL servebench --chaos — see $OUT/servebench_chaos.log /" \
         "servebench_chaos.json" >&2
    exit 1
fi
echo "selfcheck: serving chaos drill passed"

# ---- stage 5: static cost report sweep + rewrite-equivalence gate ----
# `fluidlint --report --json` must produce the cost/residency document
# (now incl. rewrite-pipeline stats) for EVERY zoo model, and
# `optcheck` proves Program.optimize() is bit-exact on one model —
# each rewrite pass in isolation (fold, fuse) and the full pipeline
# in combination.
fail=0
for m in $models; do
    if python tools/fluidlint.py --model "$m" --report --json \
            > "$OUT/${m}_report.json" 2>> "$OUT/$m.err"; then
        summary=$(python - "$OUT/${m}_report.json" <<'EOF2'
import json, sys
d = json.load(open(sys.argv[1]))
r = d.get("report") or {}
assert r.get("peak_residency_bytes", 0) > 0, "missing peak residency"
assert r.get("top_ops"), "missing per-op costs"
print(f"peak {r['peak_residency_bytes']/2**20:.2f} MiB, "
      f"{r['dead_op_count']} dead, remat {r['recommended_remat_policy']}")
EOF2
        ) || { echo "FAIL $m --report (incomplete cost doc)" >&2; fail=1; continue; }
        echo "ok   $m --report ($summary)"
    else
        echo "FAIL $m --report — see $OUT/${m}_report.json / $OUT/$m.err" >&2
        fail=1
    fi
done
if [ "$fail" -ne 0 ]; then
    echo "selfcheck: cost report sweep failed" >&2
    exit 1
fi

rm -f "$OUT/optcheck.log"
for p in fold fuse fold,fuse,cse,dce; do
    if python tools/optcheck.py --model mnist_mlp --passes "$p" \
            >> "$OUT/optcheck.log" 2>&1; then
        echo "ok   optcheck --passes $p ($(tail -1 "$OUT/optcheck.log"))"
    else
        echo "FAIL optcheck --passes $p — see $OUT/optcheck.log" >&2
        exit 1
    fi
done
if python tools/optcheck.py --model mnist_mlp \
        >> "$OUT/optcheck.log" 2>&1; then
    echo "ok   optcheck ($(tail -1 "$OUT/optcheck.log"))"
else
    echo "FAIL optcheck — see $OUT/optcheck.log" >&2
    exit 1
fi
# layout-conversion gate on a conv model: the opt-in NCHW->NHWC pass in
# isolation and combined with the default pipeline (bit-exact on
# transpose-only paths, documented tight tolerance + run-to-run
# stability on converted conv paths — optcheck enforces the split)
for p in layout layout,fold,fuse,cse,dce; do
    if python tools/optcheck.py --model mnist --passes "$p" \
            >> "$OUT/optcheck.log" 2>&1; then
        echo "ok   optcheck --passes $p ($(tail -1 "$OUT/optcheck.log"))"
    else
        echo "FAIL optcheck --passes $p — see $OUT/optcheck.log" >&2
        exit 1
    fi
done
echo "selfcheck: static cost sweep + rewrite-equivalence gate passed"

# ---- stage 6: continuous-batching decode smoke -----------------------
# Tiny-config llama through the paged-KV decode engine
# (docs/SERVING.md "Continuous decode batching"): servebench --decode
# exits 1 unless tok/s > 0, every request's greedy tokens match the
# sequential fused-generator baseline exactly, and ZERO XLA compiles
# happen after warmup while requests churn through the slots. The
# closed-loop speedup race lives in the bench ladder, not here (a
# loaded CI host would flake it); this gate pins correctness + the
# no-recompile contract.
if python tools/servebench.py --decode --requests 16 --max-new 16 \
        --out "$OUT/servebench_decode.json" \
        > "$OUT/servebench_decode.log" 2>&1; then
    echo "ok   servebench --decode ($(tail -1 "$OUT/servebench_decode.log"))"
else
    echo "FAIL servebench --decode — see $OUT/servebench_decode.log /" \
         "servebench_decode.json" >&2
    exit 1
fi
echo "selfcheck: decode serving smoke passed"

# ---- stage 7: replica-pool router smoke ------------------------------
# The cluster subsystem's gate (docs/SERVING.md "Running a replica
# pool"): 2 replicas behind the health-aware router take mixed 1- and
# 2-row traffic while every replica is drained + rebuilt one at a
# time (rolling_restart). servebench exits 1 if ANY request is lost
# or surfaces a typed error during the roll, if the pool ever reports
# fewer than N-1 READY replicas, if pool results diverge from a lone
# engine's, or if the pool serves less of the burst-overload trace
# than one engine (the capacity win that holds on any host — the
# parallel-compute speedup race would flake on a 1-core CI box).
if python tools/servebench.py --cluster 2 --rolling-restart \
        --requests 48 --concurrency 8 \
        --out "$OUT/servebench_cluster.json" \
        > "$OUT/servebench_cluster.log" 2>&1; then
    echo "ok   servebench --cluster ($(tail -1 "$OUT/servebench_cluster.log"))"
else
    echo "FAIL servebench --cluster — see $OUT/servebench_cluster.log /" \
         "servebench_cluster.json" >&2
    exit 1
fi
# replica-crash chaos through the pool: a replica is killed mid-load
# (serving_replica_crash), the router reroutes + fails over with zero
# losses, and the pool's monitor revives the corpse.
if python tools/servebench.py --chaos --cluster 2 --requests 24 \
        --concurrency 8 \
        --out "$OUT/servebench_cluster_chaos.json" \
        > "$OUT/servebench_cluster_chaos.log" 2>&1; then
    echo "ok   servebench --chaos --cluster" \
         "($(tail -1 "$OUT/servebench_cluster_chaos.log"))"
else
    echo "FAIL servebench --chaos --cluster — see" \
         "$OUT/servebench_cluster_chaos.log /" \
         "servebench_cluster_chaos.json" >&2
    exit 1
fi
echo "selfcheck: replica-pool router smoke passed"

# ---- stage 8: compiled-artifact store (zero-compile cold start) ------
# The persistent artifact store's gate (docs/PERFORMANCE.md "Cold
# starts and the artifact store"): export a model with an embedded
# seeded store, then a FRESH subprocess builds a serving engine from
# nothing but the saved-model dir — total_compiles() must stay ZERO
# through warmup of the exporter's full bucket set and outputs must be
# bit-exact vs the seeding process's reference. servebench --cold-start
# additionally records the storeless-vs-warm warmup speedup (>=2x
# gate; typically >10x on this box).
rm -rf "$OUT/coldstart"
if python - "$OUT/coldstart" > "$OUT/coldstart_seed.log" 2>&1 <<'EOF8A'
import sys, os
import numpy as np
import paddle_tpu as fluid
from paddle_tpu.models import zoo
from paddle_tpu import serving

fluid.force_cpu()
model_dir = os.path.join(sys.argv[1], "model")
zp = zoo.build_zoo_program("mnist_mlp")
scope = fluid.Scope()
exe = fluid.Executor(fluid.CPUPlace())
with fluid.scope_guard(scope):
    exe.run(zp.startup)
    fluid.io.save_inference_model(
        model_dir, zp.feed_names, zp.fetch_list, exe,
        main_program=zp.main,
        serving_buckets=serving.BucketSpec(batch_sizes=(1, 2, 4)),
        artifact_store=True)
eng = serving.ServingEngine.from_saved_model(
    model_dir, compile_store=False, auto_start=False)
rng = np.random.RandomState(0)
feed = {"img": rng.randn(2, 784).astype(np.float32),
        "label": np.zeros((2, 1), np.int64)}
from paddle_tpu.core.executor import scope_guard
with scope_guard(eng.scope):
    out = eng.exe.run(eng.program, feed=feed,
                      fetch_list=eng.fetch_list, mode="test")
np.save(os.path.join(sys.argv[1], "ref.npy"), np.asarray(out[0]))
eng.close()
print("seeded:", sorted(os.listdir(os.path.join(model_dir,
                                                "__artifacts__"))))
EOF8A
then
    echo "ok   artifact-store export+seed ($(tail -1 "$OUT/coldstart_seed.log"))"
else
    echo "FAIL artifact-store export+seed — see $OUT/coldstart_seed.log" >&2
    exit 1
fi
if python - "$OUT/coldstart" > "$OUT/coldstart_load.log" 2>&1 <<'EOF8B'
import sys, os
import numpy as np
import paddle_tpu as fluid
from paddle_tpu import serving

fluid.force_cpu()
model_dir = os.path.join(sys.argv[1], "model")
eng = serving.ServingEngine.from_saved_model(model_dir, auto_start=False)
warm = eng.warmup()
assert eng.exe.total_compiles() == 0, \
    f"fresh replica compiled: {eng.exe.compile_counts()}"
st = eng.exe.store_stats()
assert st["misses_total"] == 0 and st["hits_total"] > 0, st
rng = np.random.RandomState(0)
feed = {"img": rng.randn(2, 784).astype(np.float32),
        "label": np.zeros((2, 1), np.int64)}
from paddle_tpu.core.executor import scope_guard
with scope_guard(eng.scope):
    out = eng.exe.run(eng.program, feed=feed,
                      fetch_list=eng.fetch_list, mode="test")
ref = np.load(os.path.join(sys.argv[1], "ref.npy"))
assert np.array_equal(ref, np.asarray(out[0])), \
    "store-loaded outputs diverged from the exporter's reference"
eng.close()
print(f"zero compiles across {warm['signatures']} bucket signatures, "
      f"{st['hits_total']} store hits, bit-exact")
EOF8B
then
    echo "ok   artifact-store fresh-process load ($(tail -1 "$OUT/coldstart_load.log"))"
else
    echo "FAIL artifact-store fresh-process load — see $OUT/coldstart_load.log" >&2
    exit 1
fi
if python tools/servebench.py --cold-start --model mnist_mlp \
        --assert-speedup 2.0 --out "$OUT/servebench_coldstart.json" \
        > "$OUT/servebench_coldstart.log" 2>&1; then
    echo "ok   servebench --cold-start ($(tail -1 "$OUT/servebench_coldstart.log"))"
else
    echo "FAIL servebench --cold-start — see $OUT/servebench_coldstart.log /" \
         "servebench_coldstart.json" >&2
    exit 1
fi
echo "selfcheck: artifact-store cold-start gate passed"

# ---- stage 9: cross-host serving fabric (sockets + partitions) -------
# The network fabric's gate (docs/DISTRIBUTED.md "Serving across
# hosts"): servebench --remote 2 stands up loopback ReplicaServers
# from one exported dir and exits 1 unless (a) a fresh server
# provisioned from the saved-model dir warms with ZERO XLA compiles,
# (b) a second server provisioned purely OVER THE SOCKET
# (fetch_manifest/fetch_artifact, sha256-verified) also warms with
# zero compiles, and (c) the socket pool serves every request within
# float tolerance of a local engine. Then the partition chaos drill:
# net_partition + net_frame_drop armed mid-load must lose ZERO
# requests (typed errors only), open and re-close the per-connection
# breakers, and rejoin the partitioned replicas within one membership
# refresh of the fault clearing.
if python tools/servebench.py --remote 2 --requests 48 \
        --concurrency 8 --out "$OUT/servebench_remote.json" \
        > "$OUT/servebench_remote.log" 2>&1; then
    echo "ok   servebench --remote ($(tail -1 "$OUT/servebench_remote.log"))"
else
    echo "FAIL servebench --remote — see $OUT/servebench_remote.log /" \
         "servebench_remote.json" >&2
    exit 1
fi
if python tools/servebench.py --chaos --remote 2 --requests 24 \
        --concurrency 8 --out "$OUT/servebench_remote_chaos.json" \
        > "$OUT/servebench_remote_chaos.log" 2>&1; then
    echo "ok   servebench --chaos --remote" \
         "($(tail -1 "$OUT/servebench_remote_chaos.log"))"
else
    echo "FAIL servebench --chaos --remote — see" \
         "$OUT/servebench_remote_chaos.log /" \
         "servebench_remote_chaos.json" >&2
    exit 1
fi
echo "selfcheck: cross-host serving fabric gate passed"

# ---- stage 10: versioned-deployment canary drill ---------------------
# The deployment loop's gate (docs/SERVING.md "Deploying a new
# version"): servebench --canary exports two artifact-store versions,
# dark-deploys v2 behind router weights, proves the golden-set
# numerics gate ACCEPTS a faithful canary (zero re-warm compiles),
# then arms serving_canary_regression and exits 1 unless the staged
# promotion auto-REJECTS on the in-flight numerics resample and rolls
# back to v1 with zero lost requests, zero typed errors, and ZERO
# compiles on the restarted replicas (rollback rides the embedded
# artifact store). Records serving_rollback_s.
if python tools/servebench.py --canary --requests 48 \
        --concurrency 8 --out "$OUT/servebench_canary.json" \
        > "$OUT/servebench_canary.log" 2>&1; then
    echo "ok   servebench --canary ($(tail -1 "$OUT/servebench_canary.log"))"
else
    echo "FAIL servebench --canary — see $OUT/servebench_canary.log /" \
         "servebench_canary.json" >&2
    exit 1
fi
echo "selfcheck: versioned-deployment canary gate passed"

# ---- stage 11: static numerics gate teeth (numcheck) -----------------
# The numerics analyzer's gate (docs/RELIABILITY.md "Static numerics
# checking"). The clean-zoo sweeps — plain AND under `--amp O2` —
# already ran inside stage 0's lintall; this stage proves the gate
# has teeth: seeded fp16-overflow and int8-scale-clip fixture
# programs must FAIL the lint (exit 1 with the expected code). Then
# optcheck re-proves the rewrite passes the pipeline previously
# refused wholesale under AMP: fold+fuse held to bit-exact, the
# layout chain to the documented AMP tolerance tier
# (docs/PERFORMANCE.md §9d).
# the gate must have teeth: seeded hazard fixtures must fail the lint
rm -rf "$OUT/numcheck_fixtures"; mkdir -p "$OUT/numcheck_fixtures"
if python - "$OUT/numcheck_fixtures" > "$OUT/numcheck_fixtures.log" 2>&1 <<'EOF11F'
import sys, os
import paddle_tpu as fluid

fluid.force_cpu()
out_dir = sys.argv[1]

def build(hazard):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[8], dtype="float32")
        y = fluid.layers.sigmoid(x)           # provably [0, 1]
        out = hazard(y)
    return main, out.name

for name, hazard in [
    ("fp16_overflow", lambda y: fluid.layers.cast(
        fluid.layers.scale(y, scale=1e6), dtype="float16")),
    ("int8_clip", lambda y: fluid.layers.cast(
        fluid.layers.scale(y, scale=300.0), dtype="int8")),
]:
    main, fetch = build(hazard)
    with open(os.path.join(out_dir, name + ".json"), "w") as f:
        f.write(main.to_json())
    with open(os.path.join(out_dir, name + ".fetch"), "w") as f:
        f.write(fetch)
print("fixtures seeded")
EOF11F
then
    echo "ok   numcheck hazard fixtures seeded"
else
    echo "FAIL numcheck fixture seeding — see $OUT/numcheck_fixtures.log" >&2
    exit 1
fi
for fx in fp16_overflow int8_clip; do
    fetch=$(cat "$OUT/numcheck_fixtures/$fx.fetch")
    if python tools/numlint.py --program "$OUT/numcheck_fixtures/$fx.json" \
            --fetch "$fetch" --json \
            > "$OUT/numlint_$fx.json" 2>&1; then
        echo "FAIL numlint let the $fx fixture pass — the numerics gate" \
             "is toothless" >&2
        exit 1
    else
        echo "ok   numlint rejects the $fx fixture"
    fi
done
# AMP rewrite admission: the configs wholesale-refused before numcheck
rm -f "$OUT/optcheck_amp.log"
for spec in "mnist_mlp fold,fuse,cse,dce" "mnist layout,fold,fuse,cse,dce"; do
    set -- $spec
    if python tools/optcheck.py --model "$1" --passes "$2" --amp O2 \
            >> "$OUT/optcheck_amp.log" 2>&1; then
        echo "ok   optcheck --model $1 --passes $2 --amp O2" \
             "($(tail -1 "$OUT/optcheck_amp.log"))"
    else
        echo "FAIL optcheck --model $1 --passes $2 --amp O2 — see" \
             "$OUT/optcheck_amp.log" >&2
        exit 1
    fi
done
echo "selfcheck: static numerics gate passed"

# ---- stage 12: elastic training-fabric chaos drill -------------------
# The training fabric's gate (docs/DISTRIBUTED.md "Training across
# hosts"): trainbench --chaos runs REAL subprocess workers and fires
# all four trainer fault points against one run — a hard worker crash
# (os._exit mid-step) with an elastic replacement that cold-provisions
# its artifacts over the wire (--task program: total_compiles must be
# ZERO), a straggler evicted typed at the deadline and rejoined after
# healing, a two-call net partition, and a coordinator crash resumed
# by a NEW coordinator from the last committed serial. PASS requires
# the chaos run's committed (serial, sha) sequence to EQUAL the
# uninterrupted reference run's — zero lost committed steps AND
# bit-deterministic resume — plus loss-curve parity. Records
# train_recover_s / train_elastic_resume_s.
if python tools/trainbench.py --chaos --task program \
        --out "$OUT/trainbench_chaos.json" \
        > "$OUT/trainbench_chaos.log" 2>&1; then
    echo "ok   trainbench --chaos ($(tail -1 "$OUT/trainbench_chaos.log"))"
else
    echo "FAIL trainbench --chaos — see $OUT/trainbench_chaos.log /" \
         "trainbench_chaos.json" >&2
    exit 1
fi
# the gate must have teeth: with elasticity OFF the same drill must
# FAIL (a worker crash is then fatal) — proving the assertions above
# actually detect lost runs
if python tools/trainbench.py --chaos --task linreg --no-recover \
        > "$OUT/trainbench_norecover.log" 2>&1; then
    echo "FAIL trainbench --chaos --no-recover PASSED — the elastic" \
         "gate is toothless" >&2
    exit 1
else
    echo "ok   trainbench --chaos --no-recover fails as it must" \
         "($(tail -1 "$OUT/trainbench_norecover.log"))"
fi
echo "selfcheck: elastic training-fabric gate passed"

# ---- stage 13: SLO-aware disaggregated decode serving ----------------
# The disaggregated-serving gate (docs/SERVING.md "Disaggregated
# decode serving"): servebench --decode --slo runs a mixed short/long
# interference trace three ways — FIFO admission, the EDF SLO
# scheduler, and a 2-prefill/2-decode disaggregated pool behind
# Router.generate — and exits 1 unless the SLO scheduler's TTFT
# attainment is STRICTLY better than FIFO's (the interactive target is
# calibrated to a quarter of FIFO's measured queue-wait TTFT, so the
# comparison is scheduling-order-driven on any CPU speed), every arm
# decodes bit-identical greedy tokens, zero XLA compiles happen after
# warmup, and the serving_handoff_drop chaos drill (a prefill replica
# dies holding the finished KV blob mid-handoff) completes every
# request via re-prefill on the survivor.
if python tools/servebench.py --decode --slo \
        --out "$OUT/servebench_slo.json" \
        > "$OUT/servebench_slo.log" 2>&1; then
    echo "ok   servebench --decode --slo" \
         "($(tail -1 "$OUT/servebench_slo.log"))"
else
    echo "FAIL servebench --decode --slo — see $OUT/servebench_slo.log" \
         "/ servebench_slo.json" >&2
    exit 1
fi
# the gate must have teeth: with the comparison arm forced onto the
# FIFO scheduler the attainment cannot be strictly better, so the
# same drill must FAIL — proving the gate detects a scheduler that
# does nothing
if python tools/servebench.py --decode --slo --slo-force-fifo \
        --skip-disagg > "$OUT/servebench_slo_forced.log" 2>&1; then
    echo "FAIL servebench --decode --slo --slo-force-fifo PASSED —" \
         "the SLO-attainment gate is toothless" >&2
    exit 1
else
    echo "ok   servebench --slo --slo-force-fifo fails as it must"
fi
echo "selfcheck: disaggregated SLO serving gate passed"

# ---- stage 14: graceful degradation at the overload knee -------------
# The overload-robustness gate (docs/RELIABILITY.md "Operating at the
# overload knee"): servebench --overload replays the shipped diurnal/
# flash-crowd trace (tools/traces/diurnal_flashcrowd.json) through a
# rate ladder to MEASURE the pool's knee, then drills at 3x that knee
# on the full graceful stack — SLO/EDF scheduling, AIMD adaptive
# admission under the fixed hard ceiling, the brownout ladder, and a
# retry budget — and exits 1 unless the flash crowd sheds ZERO
# interactive requests while batch sheds, every brownout engage is
# matched by a revert (final levels 0), the serving_retry_storm drill
# stays within its budget and fails fast typed beyond it, and the
# priority-weighted goodput beats a flat-FIFO/fixed-bound baseline at
# the same offered load. Records serving_overload_knee_qps and
# serving_overload_goodput_ratio.
OVERLOAD_FLAGS="--trace-file tools/traces/diurnal_flashcrowd.json \
    --rate 3 --ladder-growth 2 --ladder-rungs 4 --max-batch 4 \
    --max-new 96 --decode-block 1 --request-timeout 8"
if python tools/servebench.py --overload $OVERLOAD_FLAGS \
        --out "$OUT/servebench_overload.json" \
        > "$OUT/servebench_overload.log" 2>&1; then
    echo "ok   servebench --overload" \
         "($(tail -1 "$OUT/servebench_overload.log"))"
else
    echo "FAIL servebench --overload — see" \
         "$OUT/servebench_overload.log / servebench_overload.json" >&2
    exit 1
fi
# the gate must have teeth: the SAME drill with every overload control
# stripped (--overload-flat-shed: FIFO admission, fixed bound only, no
# brownout, no retry budget) must FAIL — interactive sheds with the
# rest, the storm retries unbounded — proving the assertions above
# detect a stack that degrades ungracefully
if python tools/servebench.py --overload --overload-flat-shed \
        $OVERLOAD_FLAGS > "$OUT/servebench_overload_flat.log" 2>&1; then
    echo "FAIL servebench --overload --overload-flat-shed PASSED —" \
         "the overload gate is toothless" >&2
    exit 1
else
    echo "ok   servebench --overload --overload-flat-shed fails as" \
         "it must"
fi
echo "selfcheck: overload-knee gate passed"

# ---- stage 15: static protocol gate (protocheck) ---------------------
# The fabric-contract analyzer's gate (docs/RELIABILITY.md "Static
# protocol checking"). The clean-tree sweep already ran inside stage
# 0's lintall; this stage (a) re-runs the standalone gate so a
# lintall wiring bug can't mask it, (b) proves the gate has teeth —
# the jarred unregistered-wire-error + unknown-fault-point fixture
# must FAIL — and (c) diffs the knob table committed in
# docs/RELIABILITY.md against a fresh --knobs-table render, so the
# PADDLE_TPU_* reference can never drift from the tree.
if python tools/protolint.py --json > "$OUT/protolint.json" \
        2> "$OUT/protolint.err"; then
    summary=$(python - "$OUT/protolint.json" <<'EOF15'
import json, sys
d = json.load(open(sys.argv[1]))
print(f"{d['files']} files, {d['error_count']} errors, "
      f"{len(d['suppressed'])} suppressed, {len(d['knobs'])} knobs")
EOF15
    )
    echo "ok   protolint ($summary)"
else
    echo "FAIL protolint — see $OUT/protolint.json /" \
         "$OUT/protolint.err" >&2
    exit 1
fi
if python tools/protolint.py --json tests/fixtures/protocheck_teeth.py \
        > "$OUT/protolint_teeth.json" 2>&1; then
    echo "FAIL protolint let the protocol teeth fixture pass — the" \
         "protocol gate is toothless" >&2
    exit 1
else
    echo "ok   protolint rejects the protocol teeth fixture"
fi
if python - > "$OUT/protolint_knobs.log" 2>&1 <<'EOF15K'
import sys
from paddle_tpu.analysis import protocheck
report = protocheck.run_tree()
fresh = protocheck.render_knobs_table(report.knobs)
text = open("docs/RELIABILITY.md", encoding="utf-8").read()
b = text.find(protocheck.KNOBS_BEGIN)
e = text.find(protocheck.KNOBS_END)
if b < 0 or e < 0:
    print("knob-table markers missing from docs/RELIABILITY.md")
    sys.exit(1)
committed = text[b:e + len(protocheck.KNOBS_END)]
if committed.strip() != fresh.strip():
    print("docs/RELIABILITY.md knob table drifted from the tree —")
    print("regenerate: python tools/protolint.py --knobs-table")
    sys.exit(1)
print(f"{len(report.knobs)} knob(s), committed table in sync")
EOF15K
then
    echo "ok   knob table in docs/RELIABILITY.md matches the tree" \
         "($(tail -1 "$OUT/protolint_knobs.log"))"
else
    echo "FAIL knob-table drift — see $OUT/protolint_knobs.log" >&2
    exit 1
fi
echo "selfcheck: static protocol gate passed"
