"""Input-pipeline microbench: native C++ FixedBatcher vs the python
reader-decorator path on the same recordio bytes. Prints one JSON line
per pipeline; run anywhere (no TPU needed)."""
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np                                         # noqa: E402


def main(n_samples=20000, batch=128, img_elems=3072):
    import jax
    jax.config.update("jax_platforms", "cpu")
    from paddle_tpu.io import recordio
    from paddle_tpu.io.batcher import FixedBatcher, write_fixed
    from paddle_tpu import reader as rdr

    specs = [((img_elems,), "float32"), ((1,), "int64")]
    rng = np.random.RandomState(0)
    samples = [(rng.randn(img_elems).astype(np.float32),
                np.array([i % 10], np.int64)) for i in range(512)]

    tmp = tempfile.mkdtemp()
    fixed_path = os.path.join(tmp, "fixed.rec")
    npy_path = os.path.join(tmp, "npy.rec")
    write_fixed(fixed_path, (samples[i % 512] for i in range(n_samples)),
                specs)
    recordio.write_arrays(npy_path,
                          (samples[i % 512] for i in range(n_samples)))

    t0 = time.perf_counter()
    n = 0
    for imgs, labels in FixedBatcher(fixed_path, specs, batch,
                                     shuffle_buf=4 * batch, n_threads=2):
        n += len(imgs)
    dt_native = time.perf_counter() - t0

    # sharded: one worker thread per file
    shard_paths = [os.path.join(tmp, f"shard-{i}.rec") for i in range(4)]
    per = n_samples // 4
    for i, sp in enumerate(shard_paths):
        write_fixed(sp, (samples[j % 512]
                         for j in range(i * per, (i + 1) * per)), specs)
    t2 = time.perf_counter()
    k = 0
    for imgs, labels in FixedBatcher(shard_paths, specs, batch,
                                     shuffle_buf=4 * batch, n_threads=4):
        k += len(imgs)
    dt_sharded = time.perf_counter() - t2
    assert k == per * 4

    t1 = time.perf_counter()
    m = 0
    batched = rdr.batch(rdr.shuffle(recordio.array_reader(npy_path),
                                    4 * batch), batch)
    for rows in batched():
        imgs = np.stack([r[0] for r in rows])
        labels = np.stack([r[1] for r in rows])
        m += len(imgs)
    dt_python = time.perf_counter() - t1

    assert n == m == n_samples, (n, m)
    for name, dt, cnt in (("native_fixed_batcher", dt_native, n),
                          ("native_fixed_batcher_4shards", dt_sharded,
                           per * 4),
                          ("python_reader_decorators", dt_python, m)):
        print(json.dumps({
            "metric": f"{name}_samples_per_sec",
            "value": round(cnt / dt, 1),
            "unit": "samples/sec",
            "mb_per_sec": round(cnt * (img_elems * 4 + 8)
                                / dt / 1e6, 1)}))
    print(json.dumps({"metric": "native_vs_python_speedup",
                      "value": round(dt_python / dt_native, 2),
                      "sharded": round(dt_python * per * 4
                                       / (n_samples * dt_sharded), 2),
                      "unit": "x"}))


if __name__ == "__main__":
    main()
