#!/usr/bin/env python
"""racelint — CLI for the static concurrency analyzer (racecheck).

Lints the runtime packages (``cluster/``, ``serving/``,
``resilience/``, ``io/``, ``core/executor.py``) for the concurrency
bug classes documented in docs/RELIABILITY.md "Static concurrency
checking": scope discipline, lock discipline, blocking-while-locked,
lock-order cycles, and thread hygiene.

    python tools/racelint.py                 # lint the repo tree
    python tools/racelint.py --json          # machine-readable, for CI
    python tools/racelint.py path.py dir/    # lint explicit paths ONLY
    python tools/racelint.py --paths tools   # defaults + tools/ widened
    python tools/racelint.py --list-rules

Exit status is 1 iff any UNSUPPRESSED error-level finding exists —
the selfcheck gate. Suppressions (`# racecheck: ok(<rule>) — reason`)
are reported but do not fail the lint. Pure AST analysis: nothing is
imported or compiled, so it honors JAX_PLATFORMS=cpu trivially.
"""
import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from paddle_tpu.analysis import racecheck  # noqa: E402
from paddle_tpu.analysis.diagnostics import CODES, ERROR  # noqa: E402


def _expand(paths):
    out = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, _d, filenames in os.walk(p):
                out.extend(os.path.join(dirpath, n)
                           for n in sorted(filenames)
                           if n.endswith(".py"))
        else:
            out.append(p)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="racelint",
        description="static concurrency analyzer for the serving "
                    "runtime (see docs/RELIABILITY.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the repo's "
                         "runtime packages)")
    ap.add_argument("--paths", dest="extra_paths", nargs="+",
                    default=None, metavar="PATH",
                    help="WIDEN the analyzed tree: lint the default "
                         "runtime packages PLUS these files/dirs "
                         "(e.g. --paths tools) — unlike positional "
                         "paths, which replace the defaults")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON document instead of text")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings (text mode)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in racecheck.RULES:
            level, meaning = CODES[rule]
            print(f"{rule:22s} [{level:7s}] {meaning}")
        return 0

    if args.paths:
        files = _expand(args.paths)
        if args.extra_paths:
            files += _expand(args.extra_paths)
        report = racecheck.analyze_files(files)
    elif args.extra_paths:
        files = racecheck.default_target_files()
        extra = [p for p in _expand(args.extra_paths)
                 if p not in set(files)]
        report = racecheck.analyze_files(files + extra)
    else:
        report = racecheck.run_tree()

    errs = report.errors()
    if args.json:
        doc = report.to_dict()
        doc["ok"] = not errs
        json.dump(doc, sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        for d in report.findings:
            print(d.format())
        if args.show_suppressed:
            for d, reason in report.suppressed:
                print(f"suppressed[{d.code}] {d.path}:{d.line} — "
                      f"{reason}")
        warn = len(report.findings) - len(errs)
        print(f"racelint: {len(report.files)} file(s), "
              f"{len(errs)} error(s), {warn} warning(s), "
              f"{len(report.suppressed)} suppressed")
    return 1 if errs else 0


if __name__ == "__main__":
    sys.exit(main())
