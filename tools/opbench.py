"""Per-op microbenchmark harness for the perf work (run on the real
chip when the tunnel is up, or on CPU for plumbing checks).

Times the hot shapes of the headline models — ResNet-50's convolution
spectrum, the flagship's matmul/attention shapes — each as ONE jitted
executable with a forced host-transfer sync (block_until_ready is not
reliable through the tunnel; see BASELINE.json
environment_ceilings_measured). Prints one JSON line per case:
  {"case": ..., "ms": ..., "tflops": ..., "backend": ...}

Usage:  python tools/opbench.py [filter-substring]
"""
import json
import sys
import time

import numpy as np


def _sync(y):
    np.asarray(y.ravel()[0:1])


def bench_case(name, fn, args, flops, inner=10, backend=""):
    """``flops`` is the TOTAL across the dispatch's ``inner``
    iterations; tflops divides by the whole dispatch time, ms reports
    the per-iteration share."""
    import jax
    f = jax.jit(fn)
    y = f(*args)
    _sync(y)
    # best-of-3: a single tunnel hiccup inside the timed window would
    # otherwise be indistinguishable from a real regression
    dt_total = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        y = f(*args)
        _sync(y)
        dt_total = min(dt_total, time.perf_counter() - t0)
    print(json.dumps({
        "case": name, "ms": round(dt_total / inner * 1e3, 3),
        "tflops": round(flops / dt_total / 1e12, 2),
        "backend": backend,
    }), flush=True)


def main(filt=""):
    import os
    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the boot sitecustomize registers the TPU plugin; the config
        # API must also select cpu or backend init hangs on the tunnel
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax import lax

    backend = jax.default_backend()
    on_tpu = backend in ("tpu", "axon")
    dt = jnp.bfloat16 if on_tpu else jnp.float32
    key = jax.random.PRNGKey(0)
    inner = 10

    def chain(op):
        """One dispatch running `inner` dependent iterations, so the
        per-dispatch tunnel overhead amortizes. The dependency rides a
        scalar (acc) so ops whose output shape differs from their input
        still execute every iteration (nothing DCEs)."""
        def run(x, *w):
            def body(carry, _):
                c, acc = carry
                o = op(c * (1.0 + acc * 1e-20).astype(c.dtype), *w)
                return (c, acc + o.mean().astype(jnp.float32)), None
            (_, acc), _ = lax.scan(body, (x, jnp.float32(0.0)), None,
                                   length=inner)
            return acc
        return run

    cases = []

    # ResNet-50 convolution spectrum (NCHW, batch 128)
    n = 128 if on_tpu else 4
    for (cin, cout, hw, k, stride) in [
            (64, 64, 56, 3, 1), (128, 128, 28, 3, 1),
            (256, 256, 14, 3, 1), (512, 512, 7, 3, 1),
            (256, 1024, 14, 1, 1), (1024, 256, 14, 1, 1)]:
        x = jax.random.normal(key, (n, cin, hw, hw)).astype(dt) * 0.1
        w = jax.random.normal(key, (cout, cin, k, k)).astype(dt) * 0.1
        dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NCHW", "OIHW", "NCHW"))
        pad = k // 2

        def conv(c, wv, dn=dn, stride=stride, pad=pad):
            return lax.conv_general_dilated(
                c, wv, (stride, stride), [(pad, pad), (pad, pad)],
                dimension_numbers=dn)

        flops = 2 * n * (hw // stride) ** 2 * cin * cout * k * k * inner
        cases.append((f"conv{k}x{k}_{cin}->{cout}_{hw}px",
                      chain(conv), (x, w), flops))

    # flagship matmuls (batch*seq=4096 rows)
    rows = 4096 if on_tpu else 128
    for (m, kk, nn_) in [(rows, 4096, 4096), (rows, 4096, 14336),
                         (rows, 14336, 4096), (rows, 4096, 16384)]:
        if not on_tpu and max(kk, nn_) > 4096:
            continue
        a = jax.random.normal(key, (m, kk)).astype(dt) * 0.02
        b = jax.random.normal(key, (kk, nn_)).astype(dt) * 0.02

        cases.append((f"matmul_{m}x{kk}x{nn_}",
                      chain(lambda c, bv: c @ bv), (a, b),
                      2 * m * kk * nn_ * inner))

    # flash attention (flagship shape)
    from paddle_tpu.ops.pallas_attention import flash_attention
    bsz, heads, seq, hd = (4, 32, 2048, 128) if on_tpu else (1, 2, 256, 32)
    q = jax.random.normal(key, (bsz, heads, seq, hd)).astype(dt) * 0.1

    def attn(c):
        return flash_attention(c, c, c, True, None)

    # causal: ~half the s^2 score/value work actually runs
    cases.append((f"flash_attn_b{bsz}h{heads}s{seq}",
                  chain(lambda c: attn(c)), (q,),
                  2 * bsz * heads * seq * seq * hd * inner))

    for name, fn, args, flops in cases:
        if filt and filt not in name:
            continue
        try:
            bench_case(name, fn, args, flops, inner, backend)
        except Exception as e:                     # keep sweeping
            print(json.dumps({"case": name,
                              "error": f"{type(e).__name__}: {e}"[:200]}),
                  flush=True)


if __name__ == "__main__":
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    main(sys.argv[1] if len(sys.argv) > 1 else "")
