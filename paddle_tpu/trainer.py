"""High-level train loop with event callbacks and checkpointing.

API parity with the reference's ``python/paddle/fluid/trainer.py``
(Trainer, event classes, CheckpointConfig), re-designed for the XLA
whole-program executor: the train program is built once from
``train_func``, lowered to a single jitted step, and the epoch loop is
pure host-side orchestration — events, metrics fetch, checkpoints.
"""
import os
import shutil

import numpy as np

from . import io as fluid_io
from . import optimizer as optimizer_mod
from .core import framework
from .core.executor import Executor, Scope, TPUPlace, scope_guard
from .data_feeder import DataFeeder

__all__ = ["BeginEpochEvent", "EndEpochEvent", "BeginStepEvent",
           "EndStepEvent", "CheckpointConfig", "Trainer"]


class BeginEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        #: set False in the handler to skip fetching metrics this step
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """Reference trainer.py:100 — periodic checkpoint policy. After a
    crash, a new Trainer with the same ``checkpoint_dir`` auto-resumes
    from the latest checkpoint (reference trainer.py:572
    _load_checkpoint); ``epoch_id``/``step_id`` then hold the resumed
    position."""

    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10):
        self.checkpoint_dir = checkpoint_dir or os.path.join(
            os.getcwd(), "checkpoint")
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(1, int(epoch_interval))
        self.step_interval = max(1, int(step_interval))
        # filled on auto-resume
        self.epoch_id = 0
        self.step_id = 0


class Trainer:
    """Reference trainer.py:169.

    ``train_func`` builds the forward graph and returns the loss variable
    (or a list whose first element is the loss); ``optimizer_func``
    returns an Optimizer. The Trainer owns its Programs and Scope so
    several trainers can coexist.
    """

    def __init__(self, train_func, optimizer_func, param_path=None,
                 place=None, parallel=False, checkpoint_config=None):
        self._place = place or TPUPlace()
        self._parallel = parallel
        self._stop = False
        self._checkpoint_cfg = checkpoint_config
        self._serial = 0

        self.scope = Scope()
        self.startup_program = framework.Program()
        self.train_program = framework.Program()
        with framework.program_guard(self.train_program,
                                     self.startup_program), \
                framework.unique_name.guard():
            out = train_func()
            if isinstance(out, (list, tuple)):
                self.train_outputs = list(out)
            else:
                self.train_outputs = [out]
            loss = self.train_outputs[0]
            opt = optimizer_func()
            if not isinstance(opt, optimizer_mod.Optimizer):
                raise TypeError("optimizer_func must return an Optimizer")
            opt.minimize(loss)
        self.test_program = self.train_program.clone(for_test=True)

        self.exe = Executor(self._place)
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
            if param_path:
                fluid_io.load_persistables(self.exe, param_path,
                                           main_program=self.train_program)
        if self._checkpoint_cfg:
            self._load_checkpoint()

    # ------------------------------------------------------------------
    def stop(self):
        """Ask the running train() loop to exit after the current step."""
        self._stop = True

    def train(self, num_epochs, event_handler, reader=None, feed_order=None):
        feeder = self._feeder(self.train_program, feed_order)
        self._stop = False
        start_epoch = (self._checkpoint_cfg.epoch_id
                       if self._checkpoint_cfg else 0)
        try:
            for epoch_id in range(start_epoch, num_epochs):
                event_handler(BeginEpochEvent(epoch_id))
                for step_id, data in enumerate(reader()):
                    if self._stop:
                        return  # match reference: no epoch-end events
                    begin = BeginStepEvent(epoch_id, step_id)
                    event_handler(begin)
                    fetch = (self.train_outputs if begin.fetch_metrics
                             else [])
                    with scope_guard(self.scope):
                        metrics = self.exe.run(self.train_program,
                                               feed=feeder.feed(data),
                                               fetch_list=fetch)
                    event_handler(EndStepEvent(epoch_id, step_id,
                                               metrics))
                    if (self._checkpoint_cfg and
                            (step_id + 1)
                            % self._checkpoint_cfg.step_interval == 0):
                        self._save_checkpoint(epoch_id, step_id)
                event_handler(EndEpochEvent(epoch_id))
                if (self._checkpoint_cfg and
                        (epoch_id + 1)
                        % self._checkpoint_cfg.epoch_interval == 0):
                    self._save_checkpoint(epoch_id, -1)
        except BaseException:
            # failure hook: persist state before propagating so the
            # next Trainer(checkpoint_config=...) resumes at the crash
            # point instead of epoch 0 (reference trainer.py's
            # checkpoint-on-exit semantics)
            if self._checkpoint_cfg:
                try:
                    self._save_checkpoint(epoch_id, -1)
                except Exception:
                    pass
            raise

    def test(self, reader, feed_order=None):
        """Average the train_func outputs over the reader with the test
        clone (dropout off, batch-norm in inference mode)."""
        feeder = self._feeder(self.test_program, feed_order)
        sums, count = None, 0
        for data in reader():
            with scope_guard(self.scope):
                vals = self.exe.run(self.test_program,
                                    feed=feeder.feed(data),
                                    fetch_list=self.train_outputs)
            n = len(data)
            vals = [float(np.ravel(v)[0]) * n for v in vals]
            sums = vals if sums is None else [a + b
                                              for a, b in zip(sums, vals)]
            count += n
        if not count:
            return [0.0 for _ in self.train_outputs]
        return [s / count for s in sums]

    def save_params(self, param_path):
        with scope_guard(self.scope):
            fluid_io.save_persistables(self.exe, param_path,
                                       main_program=self.train_program)

    # ------------------------------------------------------------------
    def _feeder(self, program, feed_order):
        if feed_order is None:
            feed_order = [name for name, v in
                          program.global_block().vars.items()
                          if getattr(v, "is_data", False)]
        return DataFeeder(list(feed_order), self._place, program=program)

    def _save_checkpoint(self, epoch_id, step_id):
        import json
        cfg = self._checkpoint_cfg
        self._serial += 1
        path = os.path.join(cfg.checkpoint_dir, f"ckpt_{self._serial}")
        with scope_guard(self.scope):
            fluid_io.save_persistables(self.exe, path,
                                       main_program=self.train_program)
        with open(os.path.join(path, "trainer_meta.json"), "w") as f:
            json.dump({"epoch_id": epoch_id, "step_id": step_id,
                       "serial": self._serial}, f)
        # rotate old checkpoints
        if os.path.isdir(cfg.checkpoint_dir):
            serials = sorted(
                int(d.split("_")[1]) for d in os.listdir(cfg.checkpoint_dir)
                if d.startswith("ckpt_") and d.split("_")[1].isdigit())
            for s in serials[:-cfg.max_num_checkpoints]:
                shutil.rmtree(os.path.join(cfg.checkpoint_dir, f"ckpt_{s}"),
                              ignore_errors=True)

    def _load_checkpoint(self):
        """Auto-resume (reference trainer.py:572 _load_checkpoint):
        restore persistables + epoch/step position from the newest
        checkpoint under checkpoint_dir, if any."""
        import json
        cfg = self._checkpoint_cfg
        if not os.path.isdir(cfg.checkpoint_dir):
            return
        serials = sorted(
            int(d.split("_")[1]) for d in os.listdir(cfg.checkpoint_dir)
            if d.startswith("ckpt_") and d.split("_")[1].isdigit())
        if not serials:
            return
        latest = serials[-1]
        path = os.path.join(cfg.checkpoint_dir, f"ckpt_{latest}")
        with scope_guard(self.scope):
            fluid_io.load_persistables(self.exe, path,
                                       main_program=self.train_program)
        self._serial = latest
        meta_path = os.path.join(path, "trainer_meta.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                meta = json.load(f)
            # an epoch-end checkpoint (step -1) resumes at the NEXT
            # epoch; a mid-epoch one replays its epoch from the start
            # (steps are not individually addressable in a generic
            # reader — same stance as the reference's epoch granularity)
            cfg.epoch_id = meta["epoch_id"] + (
                1 if meta["step_id"] == -1 else 0)
            cfg.step_id = max(0, meta["step_id"])
