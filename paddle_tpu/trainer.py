"""High-level train loop with event callbacks and checkpointing.

API parity with the reference's ``python/paddle/fluid/trainer.py``
(Trainer, event classes, CheckpointConfig), re-designed for the XLA
whole-program executor: the train program is built once from
``train_func``, lowered to a single jitted step, and the epoch loop is
pure host-side orchestration — events, metrics fetch, checkpoints.

Checkpoints go through the crash-safe store (resilience/checkpoint.py:
atomic rename + sha256 MANIFEST + quarantine-and-fallback on load),
and the loop carries the resilience hooks — crash/NaN fault-injection
points and the PADDLE_TPU_NAN_GUARD rollback sentinel. Knobs are
documented in docs/RELIABILITY.md.
"""
import os
import warnings

import numpy as np

from . import io as fluid_io
from . import optimizer as optimizer_mod
from .core import framework
from .core.executor import Executor, Scope, TPUPlace, scope_guard
from .data_feeder import DataFeeder
from .resilience import checkpoint as _ckpt
from .resilience import faultinject

__all__ = ["BeginEpochEvent", "EndEpochEvent", "BeginStepEvent",
           "EndStepEvent", "CheckpointConfig", "Trainer"]


class BeginEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        #: set False in the handler to skip fetching metrics this step
        self.fetch_metrics = True


class EndStepEvent:
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """Reference trainer.py:100 — periodic checkpoint policy. After a
    crash, a new Trainer with the same ``checkpoint_dir`` auto-resumes
    from the newest checksum-valid checkpoint (reference trainer.py:572
    _load_checkpoint); ``epoch_id``/``step_id`` then hold the resumed
    position.

    When ``checkpoint_dir`` is None the default honors the
    ``PADDLE_TPU_CHECKPOINT_DIR`` env var (point it at a TMPDIR-style
    location in tests/CI) before falling back to the reference's
    ``<cwd>/checkpoint`` — which pollutes the working directory, so
    prefer either an explicit dir or the env override.

    ``max_num_checkpoints=None`` defers to the ``PADDLE_TPU_CKPT_KEEP``
    env knob (0 there keeps everything), falling back to the
    reference's 3 — the same retention ladder io.save_checkpoint
    uses, so a fleet tunes retention in one place."""

    def __init__(self, checkpoint_dir=None, max_num_checkpoints=None,
                 epoch_interval=1, step_interval=10):
        self.checkpoint_dir = (checkpoint_dir
                               or os.environ.get(
                                   "PADDLE_TPU_CHECKPOINT_DIR")
                               or os.path.join(os.getcwd(), "checkpoint"))
        if max_num_checkpoints is None:
            raw = os.environ.get("PADDLE_TPU_CKPT_KEEP", "").strip()
            max_num_checkpoints = int(raw) if raw else 3
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(1, int(epoch_interval))
        self.step_interval = max(1, int(step_interval))
        # filled on auto-resume
        self.epoch_id = 0
        self.step_id = 0


class Trainer:
    """Reference trainer.py:169.

    ``train_func`` builds the forward graph and returns the loss variable
    (or a list whose first element is the loss); ``optimizer_func``
    returns an Optimizer. The Trainer owns its Programs and Scope so
    several trainers can coexist.
    """

    def __init__(self, train_func, optimizer_func, param_path=None,
                 place=None, parallel=False, checkpoint_config=None):
        self._place = place or TPUPlace()
        self._parallel = parallel
        self._stop = False
        self._checkpoint_cfg = checkpoint_config
        self._serial = 0

        self.scope = Scope()
        self.startup_program = framework.Program()
        self.train_program = framework.Program()
        with framework.program_guard(self.train_program,
                                     self.startup_program), \
                framework.unique_name.guard():
            out = train_func()
            if isinstance(out, (list, tuple)):
                self.train_outputs = list(out)
            else:
                self.train_outputs = [out]
            loss = self.train_outputs[0]
            opt = optimizer_func()
            if not isinstance(opt, optimizer_mod.Optimizer):
                raise TypeError("optimizer_func must return an Optimizer")
            opt.minimize(loss)
        self.test_program = self.train_program.clone(for_test=True)

        self.exe = Executor(self._place)
        with scope_guard(self.scope):
            self.exe.run(self.startup_program)
            if param_path:
                fluid_io.load_persistables(self.exe, param_path,
                                           main_program=self.train_program)
        if self._checkpoint_cfg:
            self._load_checkpoint()

    # ------------------------------------------------------------------
    def stop(self):
        """Ask the running train() loop to exit after the current step."""
        self._stop = True

    def train(self, num_epochs, event_handler, reader=None, feed_order=None):
        feeder = self._feeder(self.train_program, feed_order)
        self._stop = False
        start_epoch = (self._checkpoint_cfg.epoch_id
                       if self._checkpoint_cfg else 0)
        nan_guard = os.environ.get(
            "PADDLE_TPU_NAN_GUARD", "0").lower() not in ("0", "", "off")
        self._nan_rollbacks = 0
        if nan_guard and self._checkpoint_cfg and self._serial == 0:
            # guarantee a rollback target before the first step: without
            # it a NaN on step 0 would have nowhere to go but a crash
            # (step_id=0 meta → resume replays this epoch from the start)
            self._save_checkpoint(start_epoch, 0)
        try:
            for epoch_id in range(start_epoch, num_epochs):
                event_handler(BeginEpochEvent(epoch_id))
                for step_id, data in enumerate(reader()):
                    if self._stop:
                        return  # match reference: no epoch-end events
                    if faultinject.fires("crash_at_step"):
                        raise faultinject.SimulatedCrash(
                            f"injected crash at epoch {epoch_id} "
                            f"step {step_id}")
                    begin = BeginStepEvent(epoch_id, step_id)
                    event_handler(begin)
                    fetch = (self.train_outputs if begin.fetch_metrics
                             else [])
                    with scope_guard(self.scope):
                        metrics = self.exe.run(self.train_program,
                                               feed=feeder.feed(data),
                                               fetch_list=fetch)
                    if metrics and faultinject.fires("nan_step"):
                        # poison the fetched loss exactly as a diverged
                        # step would surface it
                        metrics[0] = np.full_like(
                            np.asarray(metrics[0]), np.nan)
                    if (nan_guard and metrics
                            and not np.isfinite(
                                np.asarray(metrics[0])).all()):
                        # the step is discarded: state rolls back to the
                        # last good checkpoint, no EndStepEvent fires
                        self._handle_nonfinite(epoch_id, step_id)
                        continue
                    event_handler(EndStepEvent(epoch_id, step_id,
                                               metrics))
                    if (self._checkpoint_cfg and
                            (step_id + 1)
                            % self._checkpoint_cfg.step_interval == 0):
                        self._save_checkpoint(epoch_id, step_id)
                event_handler(EndEpochEvent(epoch_id))
                if (self._checkpoint_cfg and
                        (epoch_id + 1)
                        % self._checkpoint_cfg.epoch_interval == 0):
                    self._save_checkpoint(epoch_id, -1)
        except faultinject.SimulatedCrash:
            # a simulated SIGKILL gets NO failure hook — the whole point
            # is to test recovery from what is already on disk
            raise
        except BaseException:
            # failure hook: persist state before propagating so the
            # next Trainer(checkpoint_config=...) resumes at the crash
            # point instead of epoch 0 (reference trainer.py's
            # checkpoint-on-exit semantics)
            if self._checkpoint_cfg:
                try:
                    self._save_checkpoint(epoch_id, -1)
                except Exception:
                    pass
            raise

    def test(self, reader, feed_order=None):
        """Average the train_func outputs over the reader with the test
        clone (dropout off, batch-norm in inference mode)."""
        feeder = self._feeder(self.test_program, feed_order)
        sums, count = None, 0
        for data in reader():
            with scope_guard(self.scope):
                vals = self.exe.run(self.test_program,
                                    feed=feeder.feed(data),
                                    fetch_list=self.train_outputs)
            n = len(data)
            vals = [float(np.ravel(v)[0]) * n for v in vals]
            sums = vals if sums is None else [a + b
                                              for a, b in zip(sums, vals)]
            count += n
        if not count:
            return [0.0 for _ in self.train_outputs]
        return [s / count for s in sums]

    def save_params(self, param_path):
        with scope_guard(self.scope):
            fluid_io.save_persistables(self.exe, param_path,
                                       main_program=self.train_program)

    # ------------------------------------------------------------------
    def _feeder(self, program, feed_order):
        if feed_order is None:
            feed_order = [name for name, v in
                          program.global_block().vars.items()
                          if getattr(v, "is_data", False)]
        return DataFeeder(list(feed_order), self._place, program=program)

    def _train_state(self):
        """Every persistable of the train program that has a value —
        params, optimizer accumulators, LR — as host arrays."""
        persist = sorted(v.name for v in self.train_program.list_vars()
                         if v.persistable)
        return {n: np.asarray(self.scope.find_var(n)) for n in persist
                if self.scope.find_var(n) is not None}

    def _save_checkpoint(self, epoch_id, step_id):
        """Crash-safe periodic checkpoint: the whole train state goes
        through resilience/checkpoint.py (temp dir + per-array sha256
        MANIFEST + fsync + atomic rename), with the resume position in
        the manifest meta; pruning keeps max_num_checkpoints without
        racing this (or any other) in-flight save."""
        cfg = self._checkpoint_cfg
        self._serial += 1
        return _ckpt.save_state(
            cfg.checkpoint_dir, self._train_state(), serial=self._serial,
            meta={"epoch_id": epoch_id, "step_id": step_id,
                  "serial": self._serial},
            max_num_checkpoints=cfg.max_num_checkpoints)

    def _load_checkpoint(self):
        """Auto-resume (reference trainer.py:572 _load_checkpoint):
        restore persistables + epoch/step position from the newest
        CHECKSUM-VALID checkpoint under checkpoint_dir. An empty,
        missing, or partially-created directory (a crash during the
        very first save leaves only a .tmp_* dir) is a fresh run, not
        an error; damaged serials are quarantined and the next older
        valid one wins."""
        cfg = self._checkpoint_cfg
        try:
            state, manifest, serial, _path = _ckpt.load_latest_valid(
                cfg.checkpoint_dir)
        except FileNotFoundError:
            return          # nothing valid on disk — start fresh
        for k, v in state.items():
            self.scope.set(k, v)
        self._serial = serial
        meta = manifest.get("meta", {})
        if "epoch_id" in meta:
            # an epoch-end checkpoint (step -1) resumes at the NEXT
            # epoch; a mid-epoch one replays its epoch from the start
            # (steps are not individually addressable in a generic
            # reader — same stance as the reference's epoch granularity)
            cfg.epoch_id = meta["epoch_id"] + (
                1 if meta.get("step_id") == -1 else 0)
            cfg.step_id = max(0, meta.get("step_id", 0))

    def _handle_nonfinite(self, epoch_id, step_id):
        """The PADDLE_TPU_NAN_GUARD sentinel (see docs/RELIABILITY.md):
        a non-finite fetched loss means the optimizer update that just
        landed is poison, so restore the whole train state from the
        last good checkpoint and scale the learning rate down by
        PADDLE_TPU_NAN_LR_FACTOR (default 0.5; 1.0 disables) before
        continuing. After PADDLE_TPU_NAN_MAX_ROLLBACKS (default 2)
        rollbacks in one train() call, give up loudly."""
        budget = int(os.environ.get("PADDLE_TPU_NAN_MAX_ROLLBACKS", "2"))
        self._nan_rollbacks += 1
        where = f"epoch {epoch_id} step {step_id}"
        if not self._checkpoint_cfg:
            raise FloatingPointError(
                f"non-finite loss at {where} and no checkpoint_config "
                "to roll back to — pass CheckpointConfig(...) or unset "
                "PADDLE_TPU_NAN_GUARD")
        if self._nan_rollbacks > budget:
            raise FloatingPointError(
                f"non-finite loss at {where} after {budget} rollback(s) "
                "— training is diverging; lower the learning rate or "
                "inspect the data")
        cfg = self._checkpoint_cfg
        try:
            state, manifest, serial, _path = _ckpt.load_latest_valid(
                cfg.checkpoint_dir)
        except FileNotFoundError:
            raise FloatingPointError(
                f"non-finite loss at {where} and no valid checkpoint "
                f"under {cfg.checkpoint_dir} to roll back to")
        for k, v in state.items():
            self.scope.set(k, v)
        factor = float(os.environ.get("PADDLE_TPU_NAN_LR_FACTOR", "0.5"))
        if factor != 1.0:
            # the optimizer's global LR lives in the scope as a
            # persistable learning_rate_* var — scale the restored copy
            for name in list(self.scope.keys()):
                if name.startswith("learning_rate"):
                    val = self.scope.find_var(name)
                    if val is not None:
                        self.scope.set(
                            name, np.asarray(val) * np.float32(factor))
        warnings.warn(
            f"NaN guard: non-finite loss at {where}; rolled back to "
            f"checkpoint serial {serial} and scaled learning_rate by "
            f"{factor} (rollback {self._nan_rollbacks}/{budget})",
            stacklevel=2)
