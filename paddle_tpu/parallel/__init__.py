"""Distributed / parallel execution over TPU meshes."""
from .mesh import (DeviceMesh, make_mesh, PartitionSpec, NamedSharding,
                   current_mesh, mesh_scope, init_distributed)  # noqa: F401
from .executor import (ParallelExecutor, ExecutionStrategy,
                       BuildStrategy)                          # noqa: F401
from .transpiler import (ShardingTranspiler, DistributeTranspiler,
                         DistributeTranspilerConfig)           # noqa: F401
from . import collectives                                      # noqa: F401
from .pipeline import gpipe                                    # noqa: F401
