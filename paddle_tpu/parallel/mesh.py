"""Device mesh abstraction.

This replaces the reference's multi-device plumbing — ParallelExecutor's
per-GPU SSA graphs + NCCL rings (reference
paddle/fluid/framework/details/*, platform/nccl_helper.h) and the
go/pserver parameter-server topology — with the TPU-native model: one
logical ``jax.sharding.Mesh`` over all chips, shardings annotated on
values, XLA GSPMD inserting the collectives over ICI/DCN.

Axis conventions (used across the framework):
  dp — data parallel          tp — tensor (model) parallel
  pp — pipeline stages        sp — sequence/context parallel
  ep — expert parallel
"""
import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["DeviceMesh", "make_mesh", "PartitionSpec", "NamedSharding",
           "current_mesh", "mesh_scope"]

P = PartitionSpec


class DeviceMesh:
    """A named mesh over the available devices."""

    def __init__(self, axes, devices=None):
        """axes: dict axis_name -> size (one size may be -1 to absorb the
        remaining devices)."""
        devices = list(devices if devices is not None else jax.devices())
        sizes = dict(axes)
        known = int(np.prod([s for s in sizes.values() if s != -1])) or 1
        for k, v in sizes.items():
            if v == -1:
                sizes[k] = len(devices) // known
        total = int(np.prod(list(sizes.values())))
        if total > len(devices):
            raise ValueError(
                f"mesh {sizes} needs {total} devices, have {len(devices)}")
        arr = np.asarray(devices[:total]).reshape(list(sizes.values()))
        self.mesh = Mesh(arr, tuple(sizes.keys()))
        self.axes = sizes

    @property
    def axis_names(self):
        return self.mesh.axis_names

    def size(self, axis=None):
        if axis is None:
            return int(np.prod(list(self.axes.values())))
        return self.axes[axis]

    def sharding(self, *spec):
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self):
        return NamedSharding(self.mesh, P())

    def __enter__(self):
        self.mesh.__enter__()
        return self

    def __exit__(self, *a):
        return self.mesh.__exit__(*a)

    def __repr__(self):
        return f"DeviceMesh({self.axes})"


_current = None


def make_mesh(axes=None, devices=None):
    """Default: 1-D data-parallel mesh over every device."""
    if axes is None:
        axes = {"dp": -1}
    return DeviceMesh(axes, devices)


def current_mesh():
    return _current


import contextlib


@contextlib.contextmanager
def mesh_scope(mesh):
    global _current
    old = _current
    _current = mesh
    try:
        with mesh.mesh:
            yield mesh
    finally:
        _current = old
