"""Device mesh abstraction.

This replaces the reference's multi-device plumbing — ParallelExecutor's
per-GPU SSA graphs + NCCL rings (reference
paddle/fluid/framework/details/*, platform/nccl_helper.h) and the
go/pserver parameter-server topology — with the TPU-native model: one
logical ``jax.sharding.Mesh`` over all chips, shardings annotated on
values, XLA GSPMD inserting the collectives over ICI/DCN.

Axis conventions (used across the framework):
  dp — data parallel          tp — tensor (model) parallel
  pp — pipeline stages        sp — sequence/context parallel
  ep — expert parallel
"""
import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["DeviceMesh", "make_mesh", "PartitionSpec", "NamedSharding",
           "current_mesh", "mesh_scope", "init_distributed"]

P = PartitionSpec


class DeviceMesh:
    """A named mesh over the available devices."""

    def __init__(self, axes, devices=None):
        """axes: dict axis_name -> size (one size may be -1 to absorb the
        remaining devices)."""
        fallback_pool = None
        if devices is not None:
            pools = [list(devices)]
        else:
            # The default backend may be a single accelerator while the
            # host platform was widened via
            # --xla_force_host_platform_device_count (the driver's
            # multi-chip dryrun path): also consider the CPU pool.
            pools = [list(jax.devices())]
            try:
                cpus = list(jax.devices("cpu"))
            except RuntimeError:
                cpus = []
            # Cross-backend fallback is only for the dryrun case (one
            # tunneled chip + host platform widened via
            # --xla_force_host_platform_device_count); a real
            # multi-accelerator pool never silently falls back to CPU.
            if (len(pools[0]) == 1 and len(cpus) > 1
                    and pools[0][0].platform != "cpu"):
                fallback_pool = cpus
                pools.append(cpus)
                if any(v == -1 for v in axes.values()):
                    # -1 absorbs all remaining devices — the wider CPU
                    # pool wins so the mesh is actually multi-device.
                    pools.reverse()
        last_err = None
        for pool in pools:
            sizes = dict(axes)
            known = int(np.prod([s for s in sizes.values() if s != -1])) or 1
            for k, v in sizes.items():
                if v == -1:
                    sizes[k] = len(pool) // known
            total = int(np.prod(list(sizes.values())))
            if 0 < total <= len(pool):
                if pool is fallback_pool:
                    import warnings
                    warnings.warn(
                        "DeviceMesh: default backend has a single device; "
                        f"building the mesh over {len(pool)} host CPU "
                        "devices instead")
                arr = np.asarray(pool[:total]).reshape(list(sizes.values()))
                self.mesh = Mesh(arr, tuple(sizes.keys()))
                self.axes = sizes
                return
            last_err = ValueError(
                f"mesh axes {axes} cannot be laid out over {len(pool)} "
                f"devices (resolved sizes {sizes} need {total})")
        raise last_err

    @property
    def axis_names(self):
        return self.mesh.axis_names

    def size(self, axis=None):
        if axis is None:
            return int(np.prod(list(self.axes.values())))
        return self.axes[axis]

    def sharding(self, *spec):
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self):
        return NamedSharding(self.mesh, P())

    def __enter__(self):
        self.mesh.__enter__()
        return self

    def __exit__(self, *a):
        return self.mesh.__exit__(*a)

    def __repr__(self):
        return f"DeviceMesh({self.axes})"


_current = None


def make_mesh(axes=None, devices=None):
    """Default: 1-D data-parallel mesh over every device."""
    if axes is None:
        axes = {"dp": -1}
    return DeviceMesh(axes, devices)


def current_mesh():
    return _current


import contextlib


@contextlib.contextmanager
def mesh_scope(mesh):
    global _current
    old = _current
    _current = mesh
    try:
        with mesh.mesh:
            yield mesh
    finally:
        _current = old


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None, local_device_ids=None):
    """Join a multi-host TPU pod slice (reference: the trainer/pserver
    bootstrap read from PADDLE_TRAINER_ID / PADDLE_TRAINERS /
    PADDLE_PSERVER_ENDPOINTS env, reference
    python/paddle/fluid/transpiler/distribute_transpiler.py usage).

    Wraps ``jax.distributed.initialize``: on Cloud TPU the arguments
    are discovered from the pod metadata, elsewhere they come from the
    fluid-style env vars as a fallback. After this, ``jax.devices()``
    spans every host's chips and a DeviceMesh built over them runs one
    SPMD program across the pod — collectives ride ICI within a slice
    and DCN across slices, with no pserver topology needed.

    ``PADDLE_TPU_CPU_COLLECTIVES=gloo`` selects the CPU collectives
    transport for multi-process bring-up on hosts without
    accelerators (docs/DISTRIBUTED.md).
    """
    import os
    if coordinator_address is None:
        eps = os.environ.get("PADDLE_PSERVER_ENDPOINTS") or \
            os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        coordinator_address = eps.split(",")[0] or None
    if num_processes is None and os.environ.get("PADDLE_TRAINERS"):
        num_processes = int(os.environ["PADDLE_TRAINERS"])
    if process_id is None and os.environ.get("PADDLE_TRAINER_ID"):
        process_id = int(os.environ["PADDLE_TRAINER_ID"])
    impl = os.environ.get("PADDLE_TPU_CPU_COLLECTIVES", "")
    if impl:
        # XLA:CPU's default collectives reject multiprocess programs
        # ("Multiprocess computations aren't implemented on the CPU
        # backend"); PADDLE_TPU_CPU_COLLECTIVES=gloo selects the
        # transport that implements them, which is what makes the
        # 2-process bring-up testable on a laptop
        # (tests/test_distributed_bringup.py). Opt-in by env because
        # it must be set before the CPU backend initializes and it
        # requires a live distributed client — flipping it in a
        # single-process run would break backend init.
        jax.config.update("jax_cpu_collectives_implementation", impl)
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id,
        local_device_ids=local_device_ids)
    return len(jax.devices())
