"""Collective communication wrappers.

Capability parity with the reference's NCCL/MPI layer (reference
paddle/fluid/platform/nccl_helper.h, operators/nccl_op.cc,
operators/gen_nccl_id_op.cc): same verbs, but lowered to XLA collectives
that ride ICI within a pod slice and DCN across slices. Usable inside
shard_map-ped functions; under plain GSPMD jit these are rarely needed
explicitly because the partitioner inserts them.
"""
import jax
from jax import lax

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "broadcast",
           "grad_tree_sync", "ppermute", "all_to_all", "axis_index",
           "axis_size", "quantized_all_reduce"]


def all_reduce(x, axis_name, op="sum"):
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unknown reduce op {op!r}")


def all_gather(x, axis_name, axis=0, tiled=True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0):
    return lax.psum_scatter(x, axis_name,
                            scatter_dimension=scatter_dimension, tiled=True)


def broadcast(x, axis_name, root=0):
    """Every device gets root's value: select root shard then gather."""
    idx = lax.axis_index(axis_name)
    masked = jax.numpy.where(idx == root, x, jax.numpy.zeros_like(x))
    return lax.psum(masked, axis_name)


def ppermute(x, axis_name, perm):
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    return lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=tiled)


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    return lax.psum(1, axis_name)


def grad_tree_sync(grads, axis_name, op="mean", bits=None):
    """Synchronize a whole gradient pytree across the data-parallel
    axis in one call — the collectives-tier grad sync the train fabric
    uses when replicas share a jax mesh (the socket tier does the same
    reduction coordinator-side; see cluster/train_fabric.py). ``op``
    is ``"mean"`` (the dp default: every replica ends with the global
    average) or ``"sum"``. ``bits=8`` rides each leaf through
    :func:`quantized_all_reduce` for the EQuARX bandwidth trade;
    ``bits=None`` keeps the exact psum. Use inside shard_map-ped
    step functions::

        grads = collectives.grad_tree_sync(grads, "dp")
    """
    if op not in ("sum", "mean"):
        raise ValueError(f"grad_tree_sync op must be 'sum' or "
                         f"'mean', got {op!r}")
    n = axis_size(axis_name)

    def sync(g):
        if bits is None:
            return all_reduce(g, axis_name, op=op)
        total = quantized_all_reduce(g, axis_name, bits=bits)
        return total / n if op == "mean" else total

    return jax.tree_util.tree_map(sync, grads)


def quantized_all_reduce(x, axis_name, bits=8):
    """Bandwidth-compressed gradient all-reduce (EQuARX,
    arxiv 2506.17615): shards agree on one per-tensor scale (a scalar
    pmax), quantize against it to the int8 value range, and psum the
    result as int16 — 2 bytes/element on the ICI/DCN wire versus the
    exact reduce's 4, at ~1e-2 relative error (the dp-gradient trade
    the paper measures). int16 accumulation of int8-range addends is
    overflow-safe up to 258 shards (127*258 < 2^15). Use inside
    shard_map for explicit-collective training loops; GSPMD paths keep
    the exact psum.

    Only bits=8 is implemented (the paper's sweet spot).
    """
    import jax.numpy as jnp
    if bits != 8:
        raise NotImplementedError("quantized_all_reduce supports bits=8")
    r = 127.0
    # one shared grid so the sum is exact w.r.t. it; per-shard scales
    # would need per-shard dequantization = the full-precision reduce
    local = jnp.maximum(jnp.max(jnp.abs(x)), 1e-30) / r
    common = lax.pmax(local, axis_name)
    q = jnp.clip(jnp.round(x / common), -r, r).astype(jnp.int16)
    total = lax.psum(q, axis_name)
    return total.astype(x.dtype) * common.astype(x.dtype)
