"""Pipeline parallelism over the mesh 'pp' axis — GPipe microbatch
schedule, TPU-native.

Where the reference would time-slice a program across devices with
send/recv ops (its section_worker / pipeline trainer lineage, and the
NCCL send/recv ops in paddle/fluid/operators), the TPU form keeps ONE
SPMD program: stage parameters live stacked with a leading [n_stages]
axis sharded over 'pp', activations rotate between neighbor stages with
``lax.ppermute`` inside ``shard_map``, and a ``lax.scan`` over
n_micro + n_stages - 1 ticks realizes the pipeline (bubbles included).
``jax.grad`` differentiates straight through the scan, giving the GPipe
backward schedule for free; wrap ``stage_fn`` in ``jax.checkpoint`` to
trade recompute for activation memory like the reference's
memory_optimization pass would.
"""
import jax
import jax.numpy as jnp
try:
    from jax import shard_map                      # jax >= 0.8
except ImportError:                                # pragma: no cover
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

__all__ = ["gpipe"]


def gpipe(stage_fn, mesh, axis="pp", checkpoint_stages=True):
    """Build a pipelined apply over ``mesh.axes[axis]`` stages.

    stage_fn(stage_params, x) -> y, the computation of ONE stage; all
    stages must share this shape signature (x and y alike), e.g. a
    block of transformer layers.

    Returns ``pipelined(stacked_params, micro) -> out`` where
    ``stacked_params`` is a pytree whose leaves lead with the
    [n_stages] axis (shard it over 'pp'), ``micro`` is
    [n_micro, micro_batch, ...], and ``out`` is [n_micro, micro_batch,
    ...] — the last stage's outputs in microbatch order, replicated
    across the pipeline group.
    """
    n_stages = mesh.axes[axis]
    fn = jax.checkpoint(stage_fn) if checkpoint_stages else stage_fn
    other_axes = tuple(a for a in mesh.axes if a != axis)

    def per_group(params_local, micro):
        # inside shard_map: params_local leads with a length-1 stage
        # slice; micro is this data-parallel shard's microbatches,
        # replicated along 'pp'
        params_here = jax.tree_util.tree_map(lambda p: p[0], params_local)
        idx = jax.lax.axis_index(axis)
        n_micro = micro.shape[0]
        ticks = n_micro + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            prev_out, outputs = carry
            recv = jax.lax.ppermute(prev_out, axis, perm)
            feed_t = jnp.clip(t, 0, n_micro - 1)
            x_in = jnp.where(idx == 0, micro[feed_t], recv)
            y = fn(params_here, x_in)
            out_t = t - (n_stages - 1)
            write = (idx == n_stages - 1) & (out_t >= 0)
            safe_t = jnp.maximum(out_t, 0)
            cur = jax.lax.dynamic_index_in_dim(outputs, safe_t, 0,
                                               keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(write, y, cur), safe_t, 0)
            return (y, outputs), None

        zero = jnp.zeros_like(micro[0])
        outs0 = jnp.zeros_like(micro)
        (_, outputs), _ = jax.lax.scan(
            tick, (zero, outs0), jnp.arange(ticks))
        # only the last stage holds real outputs — share them along the
        # pipeline axis so every stage returns the same value
        outputs = jax.lax.psum(
            jnp.where(idx == n_stages - 1, outputs, 0.0), axis)
        return outputs

    # stage params enter sharded over 'pp' on their stacked axis; data
    # shards its microbatch dim over 'dp' when the mesh has one
    param_spec = P(axis)

    def pipelined(stacked_params, micro):
        in_specs = (jax.tree_util.tree_map(lambda _: param_spec,
                                           stacked_params),
                    P(None, "dp") if "dp" in other_axes else P())
        kw = {"check_vma": False}
        try:
            sm = shard_map(
                per_group, mesh=mesh.mesh, in_specs=in_specs,
                out_specs=P(None, "dp") if "dp" in other_axes else P(),
                **kw)
        except TypeError:      # older jax spells it check_rep
            sm = shard_map(
                per_group, mesh=mesh.mesh, in_specs=in_specs,
                out_specs=P(None, "dp") if "dp" in other_axes else P(),
                check_rep=False)
        return sm(stacked_params, micro)

    return pipelined
