"""Pipeline parallelism over the mesh 'pp' axis — GPipe microbatch
schedule, TPU-native.

Where the reference would time-slice a program across devices with
send/recv ops (its section_worker / pipeline trainer lineage, and the
NCCL send/recv ops in paddle/fluid/operators), the TPU form keeps ONE
SPMD program: stage parameters live stacked with a leading [n_stages]
axis sharded over 'pp', activations rotate between neighbor stages with
``lax.ppermute`` inside ``shard_map``, and a ``lax.scan`` over
n_micro + n_stages - 1 ticks realizes the pipeline (bubbles included).
``jax.grad`` differentiates straight through the scan, giving the GPipe
backward schedule for free; wrap ``stage_fn`` in ``jax.checkpoint`` to
trade recompute for activation memory like the reference's
memory_optimization pass would.
"""
import jax
import jax.numpy as jnp
try:
    from jax import shard_map                      # jax >= 0.8
except ImportError:                                # pragma: no cover
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

__all__ = ["gpipe", "one_f_one_b"]


def gpipe(stage_fn, mesh, axis="pp", checkpoint_stages=True):
    """Build a pipelined apply over ``mesh.axes[axis]`` stages.

    stage_fn(stage_params, x) -> y, the computation of ONE stage; all
    stages must share this shape signature (x and y alike), e.g. a
    block of transformer layers.

    Returns ``pipelined(stacked_params, micro) -> out`` where
    ``stacked_params`` is a pytree whose leaves lead with the
    [n_stages] axis (shard it over 'pp'), ``micro`` is
    [n_micro, micro_batch, ...], and ``out`` is [n_micro, micro_batch,
    ...] — the last stage's outputs in microbatch order, replicated
    across the pipeline group.
    """
    n_stages = mesh.axes[axis]
    fn = jax.checkpoint(stage_fn) if checkpoint_stages else stage_fn
    other_axes = tuple(a for a in mesh.axes if a != axis)

    def per_group(params_local, micro):
        # inside shard_map: params_local leads with a length-1 stage
        # slice; micro is this data-parallel shard's microbatches,
        # replicated along 'pp'
        params_here = jax.tree_util.tree_map(lambda p: p[0], params_local)
        idx = jax.lax.axis_index(axis)
        n_micro = micro.shape[0]
        ticks = n_micro + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            prev_out, outputs = carry
            recv = jax.lax.ppermute(prev_out, axis, perm)
            feed_t = jnp.clip(t, 0, n_micro - 1)
            x_in = jnp.where(idx == 0, micro[feed_t], recv)
            y = fn(params_here, x_in)
            out_t = t - (n_stages - 1)
            write = (idx == n_stages - 1) & (out_t >= 0)
            safe_t = jnp.maximum(out_t, 0)
            cur = jax.lax.dynamic_index_in_dim(outputs, safe_t, 0,
                                               keepdims=False)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(write, y, cur), safe_t, 0)
            return (y, outputs), None

        zero = jnp.zeros_like(micro[0])
        outs0 = jnp.zeros_like(micro)
        (_, outputs), _ = jax.lax.scan(
            tick, (zero, outs0), jnp.arange(ticks))
        # only the last stage holds real outputs — share them along the
        # pipeline axis so every stage returns the same value
        outputs = jax.lax.psum(
            jnp.where(idx == n_stages - 1, outputs, 0.0), axis)
        return outputs

    # stage params enter sharded over 'pp' on their stacked axis; data
    # shards its microbatch dim over 'dp' when the mesh has one
    param_spec = P(axis)

    def pipelined(stacked_params, micro):
        in_specs = (jax.tree_util.tree_map(lambda _: param_spec,
                                           stacked_params),
                    P(None, "dp") if "dp" in other_axes else P())
        kw = {"check_vma": False}
        try:
            sm = shard_map(
                per_group, mesh=mesh.mesh, in_specs=in_specs,
                out_specs=P(None, "dp") if "dp" in other_axes else P(),
                **kw)
        except TypeError:      # older jax spells it check_rep
            sm = shard_map(
                per_group, mesh=mesh.mesh, in_specs=in_specs,
                out_specs=P(None, "dp") if "dp" in other_axes else P(),
                check_rep=False)
        return sm(stacked_params, micro)

    return pipelined


def one_f_one_b(stage_fn, loss_fn, mesh, axis="pp", loss_params=False,
                return_dx=False):
    """1F1B pipeline schedule (PipeDream-flush) — the GPipe upgrade the
    reference's section-based pipeline trainer never got.

    Where :func:`gpipe` differentiates through the whole forward
    schedule (so every stage holds inputs for ALL ``n_micro``
    microbatches until the backward sweep), 1F1B interleaves each
    microbatch's backward as soon as the last stage finishes its
    forward: stage ``s`` holds at most ``n_stages - s`` in-flight
    stage-inputs, the steady state alternates one-forward/one-backward
    per tick, and parameter gradients accumulate inside the schedule.
    Same bubble as GPipe, ~n_micro/n_stages× less activation memory.

    stage_fn(stage_params, x) -> y (same x/y shape across stages);
    loss_fn(y, target) -> scalar per-microbatch loss (mean-reduced).

    Returns ``step(stacked_params, micro_x, micro_y) -> (loss, grads)``
    where ``stacked_params`` leads with [n_stages] (shard over 'pp'),
    ``micro_x``/``micro_y`` are [n_micro, micro_batch, ...], ``loss``
    is the mean over microbatches, and ``grads`` matches
    ``stacked_params`` — gradients of that mean loss, computed by the
    schedule itself (do NOT wrap in jax.grad).

    ``loss_params=True`` changes ``loss_fn`` to
    ``loss_fn(lparams, y, target)`` (the last stage's head/loss
    weights, replicated across stages) and ``step`` to
    ``step(stacked_params, lparams, micro_x, micro_y)``; the return
    gains ``dlparams``. ``return_dx=True`` appends ``dx_micro``
    (d loss / d micro_x, same [n_micro, ...] layout) — what an
    upstream embedding needs to keep training through the pipeline.

    Tick algebra (stage s, microbatch k, n_stages S): forward of k runs
    at tick ``s + 2k``, backward at ``2S - 1 - s + 2k`` — ticks at a
    stage strictly alternate F/B, values permuted at tick end arrive
    exactly when the neighbor consumes them, and a slot ring of size S
    holds the in-flight stage inputs for backward recomputation
    (jax.vjp re-runs the stage, i.e. remat is built in).
    """
    n_stages = mesh.axes[axis]
    other_axes = tuple(a for a in mesh.axes if a != axis)
    has_dp = "dp" in other_axes

    def per_group(params_local, lparams, micro_x, micro_y):
        params = jax.tree_util.tree_map(lambda p: p[0], params_local)
        idx = jax.lax.axis_index(axis)
        n_micro = micro_x.shape[0]
        # last event: backward of microbatch M-1 at stage 0, tick
        # 2S - 1 + 2(M-1) — so 2(M + S) - 2 ticks run in total
        ticks = 2 * (n_micro + n_stages) - 2
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        bwd_perm = [((i + 1) % n_stages, i) for i in range(n_stages)]

        zero_x = jnp.zeros_like(micro_x[0])
        zero_g = jax.tree_util.tree_map(jnp.zeros_like, params)
        zero_lg = jax.tree_util.tree_map(jnp.zeros_like, lparams)
        dx_buf0 = (jnp.zeros_like(micro_x) if return_dx else ())

        def tick(carry, t):
            y_send, g_send, x_ring, grad_acc, lg_acc, dx_buf, \
                loss_acc = carry
            y_in = jax.lax.ppermute(y_send, axis, fwd_perm)
            g_in = jax.lax.ppermute(g_send, axis, bwd_perm)

            k_f = (t - idx) // 2
            is_f = ((t - idx) % 2 == 0) & (k_f >= 0) & (k_f < n_micro)
            k_b = (t - (2 * n_stages - 1 - idx)) // 2
            is_b = (~((t - idx) % 2 == 0)) & (k_b >= 0) & (k_b < n_micro)

            def fwd_branch(args):
                (y_in, g_in, x_ring, grad_acc, lg_acc, dx_buf,
                 loss_acc) = args
                kf = jnp.clip(k_f, 0, n_micro - 1)
                x_in = jnp.where(idx == 0, micro_x[kf], y_in)
                y = stage_fn(params, x_in)
                x_ring = jax.lax.dynamic_update_index_in_dim(
                    x_ring, x_in, kf % n_stages, 0)
                return (y, zero_x, x_ring, grad_acc, lg_acc, dx_buf,
                        loss_acc)

            def bwd_branch(args):
                (y_in, g_in, x_ring, grad_acc, lg_acc, dx_buf,
                 loss_acc) = args
                kb = jnp.clip(k_b, 0, n_micro - 1)
                x_in = jax.lax.dynamic_index_in_dim(
                    x_ring, kb % n_stages, 0, keepdims=False)
                y, pull = jax.vjp(stage_fn, params, x_in)
                inv_m = jnp.ones((), jnp.float32) / n_micro

                if loss_params:
                    loss_k, pull_l = jax.vjp(
                        lambda lp, yy: loss_fn(lp, yy, micro_y[kb]),
                        lparams, y)
                    dlp_k, g_last = pull_l(inv_m.astype(loss_k.dtype))
                else:
                    loss_k, pull_l = jax.vjp(
                        lambda yy: loss_fn(yy, micro_y[kb]), y)
                    (g_last,) = pull_l(inv_m.astype(loss_k.dtype))
                    dlp_k = zero_lg
                loss_k = loss_k / n_micro

                is_last = idx == n_stages - 1
                cot = jnp.where(is_last, g_last, g_in)
                dparams, dx = pull(cot)
                grad_acc = jax.tree_util.tree_map(
                    lambda a, d: a + d, grad_acc, dparams)
                lg_acc = jax.tree_util.tree_map(
                    lambda a, d: a + jnp.where(is_last, d, 0.0),
                    lg_acc, dlp_k)
                if return_dx:
                    dx_buf = jax.lax.dynamic_update_index_in_dim(
                        dx_buf, jnp.where(idx == 0, dx, 0.0), kb, 0)
                loss_acc = loss_acc + jnp.where(is_last, loss_k, 0.0)
                return (zero_x, dx, x_ring, grad_acc, lg_acc, dx_buf,
                        loss_acc)

            def idle_branch(args):
                (y_in, g_in, x_ring, grad_acc, lg_acc, dx_buf,
                 loss_acc) = args
                return (zero_x, zero_x, x_ring, grad_acc, lg_acc,
                        dx_buf, loss_acc)

            branch = jnp.int32(0) + jnp.where(is_f, 1, 0) \
                + jnp.where(is_b, 2, 0)
            out = jax.lax.switch(
                branch, [idle_branch, fwd_branch, bwd_branch],
                (y_in, g_in, x_ring, grad_acc, lg_acc, dx_buf,
                 loss_acc))
            return out, None

        ring0 = jnp.zeros((n_stages,) + micro_x.shape[1:],
                          micro_x.dtype)
        carry0 = (zero_x, zero_x, ring0, zero_g, zero_lg, dx_buf0,
                  jnp.zeros((), jnp.float32))
        (_, _, _, grads, lgrads, dx_out, loss), _ = jax.lax.scan(
            tick, carry0, jnp.arange(ticks))

        # loss and head grads live on the last stage, dx on stage 0,
        # stage grads on their own stage. Share along 'pp'; average
        # across 'dp' shards.
        loss = jax.lax.psum(loss, axis)
        lgrads = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, axis), lgrads)
        if return_dx:
            dx_out = jax.lax.psum(dx_out, axis)
            if has_dp:
                # dx is per-shard data (not summed over dp): the global
                # loss is the MEAN over dp shards, so each shard's
                # cotangent carries a 1/|dp| factor
                dx_out = dx_out / mesh.axes["dp"]
        if has_dp:
            loss = jax.lax.pmean(loss, "dp")
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, "dp"), grads)
            lgrads = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, "dp"), lgrads)
        # re-stack the local stage grads with the leading [1] axis so
        # the out_spec P(axis) reassembles [n_stages, ...]
        grads = jax.tree_util.tree_map(lambda g: g[None], grads)
        out = (loss, grads)
        if loss_params:
            out = out + (lgrads,)
        if return_dx:
            out = out + (dx_out,)
        return out

    param_spec = P(axis)

    def step(stacked_params, *rest):
        if loss_params:
            lparams, micro_x, micro_y = rest
        else:
            micro_x, micro_y = rest
            lparams = ()
        pspecs = jax.tree_util.tree_map(lambda _: param_spec,
                                        stacked_params)
        lspecs = jax.tree_util.tree_map(lambda _: P(), lparams)
        data_spec = P(None, "dp") if has_dp else P()
        out_specs = (P(), pspecs)
        if loss_params:
            out_specs = out_specs + (lspecs,)
        if return_dx:
            out_specs = out_specs + (data_spec,)
        kw = dict(mesh=mesh.mesh,
                  in_specs=(pspecs, lspecs, data_spec, data_spec),
                  out_specs=out_specs)
        try:
            sm = shard_map(per_group, check_vma=False, **kw)
        except TypeError:                      # older jax: check_rep
            sm = shard_map(per_group, check_rep=False, **kw)
        return sm(stacked_params, lparams, micro_x, micro_y)

    return step
