"""Ring attention — sequence/context parallelism over a mesh axis.

The long-context first-class citizen: sequences sharded over the 'sp'
mesh axis, K/V shards rotated around the ring with ppermute while each
device accumulates its queries' attention against every shard, merging
partial softmax results exactly via log-sum-exp. Peak memory per device
is O(T/sp), enabling contexts the reference framework (whole-sequence
LoDTensor attention) could never hold.

Built on shard_map so XLA schedules the ppermute DMA over ICI
concurrently with the local flash-attention compute (communication/
compute overlap, the standard ring schedule).
"""
import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from ..ops.pallas_attention import attention_with_lse

__all__ = ["ring_attention", "ring_attention_sharded"]


def _merge(o1, lse1, o2, lse2):
    """Exactly combines two partial attention results with their lse."""
    m = jnp.maximum(lse1, lse2)
    w1 = jnp.exp(lse1 - m)[..., None]
    w2 = jnp.exp(lse2 - m)[..., None]
    o = (o1.astype(jnp.float32) * w1 + o2.astype(jnp.float32) * w2) / (w1 + w2)
    lse = m + jnp.log(jnp.exp(lse1 - m) + jnp.exp(lse2 - m))
    return o.astype(o1.dtype), lse


def ring_attention(q, k, v, axis_name, causal=True, scale=None):
    """Per-device body (inside shard_map): q,k,v [B, H, Tlocal, D] shards.

    Device i holds sequence chunk i. At ring step s it attends its queries
    against the K/V chunk that started on device (i - s) mod n, with the
    causal mask applied at chunk granularity via global position offsets.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    scale = scale or (1.0 / np.sqrt(q.shape[-1]))
    t_local = q.shape[2]

    def step(carry, s):
        k_cur, v_cur, o_acc, lse_acc = carry
        src_chunk = (idx - s) % n  # whose chunk we currently hold
        q_off = idx * t_local
        k_off = src_chunk * t_local
        if causal:
            # bias masks keys whose global pos > query global pos
            rows = q_off + lax.broadcasted_iota(jnp.int32,
                                                (t_local, t_local), 0)
            cols = k_off + lax.broadcasted_iota(jnp.int32,
                                                (t_local, t_local), 1)
            bias = jnp.where(rows >= cols, 0.0, -1e30)
        else:
            bias = None
        o_part, lse_part = attention_with_lse_biased(q, k_cur, v_cur, scale,
                                                     bias)
        o_new, lse_new = _merge(o_acc, lse_acc, o_part, lse_part)
        # rotate k/v one step around the ring
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, o_new, lse_new), None

    o0 = jnp.zeros_like(q)
    lse0 = jnp.full(q.shape[:3], -1e30, jnp.float32)
    (_, _, o, _), _ = lax.scan(step, (k, v, o0, lse0), jnp.arange(n))
    return o


def attention_with_lse_biased(q, k, v, scale, bias):
    from ..ops.pallas_attention import _ref_attention_lse
    return _ref_attention_lse(q, k, v, scale, causal=False, bias=bias)


def ring_attention_sharded(q, k, v, mesh, axis="sp", causal=True,
                           scale=None):
    """Global entry: q,k,v [B, H, T, D] with T sharded over ``axis``."""
    spec = P(None, None, axis, None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis, causal=causal,
                          scale=scale),
        mesh=mesh.mesh if hasattr(mesh, "mesh") else mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False)
    return fn(q, k, v)
