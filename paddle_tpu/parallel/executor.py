"""ParallelExecutor — SPMD execution of a Program over a device mesh.

Capability parity with fluid's ParallelExecutor (reference
paddle/fluid/framework/parallel_executor.cc + details/
multi_devices_graph_builder.cc): where the reference replicates the
graph per GPU, scatters batches, and inserts NCCL AllReduceOpHandle on
every gradient, we jit the SAME lowered step function with sharding
annotations — feeds sharded over 'dp', parameters sharded per their
transpiler-assigned PartitionSpec (or replicated) — and XLA GSPMD
partitions the program and places all-reduces on ICI automatically.
Gradient averaging falls out of the math: the loss mean over a
dp-sharded batch axis becomes a psum.
"""
import re

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core import framework
from ..core.executor import (Executor, global_scope, make_stepped,
                             step_arg, check_nan_guard)
from ..core.lowering import lower_program, written_names
from .mesh import make_mesh, DeviceMesh, mesh_scope

# GSPMD collective opcodes in optimized HLO. Each collective counts
# once: the pattern requires "(" directly after the base opcode or its
# "-start" async form, so "all-reduce-done(...)" (whose operand list
# follows "-done", not the base name) can never double-count.
_COLLECTIVE_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-start)?\(")

__all__ = ["ParallelExecutor", "ExecutionStrategy", "BuildStrategy"]


class ExecutionStrategy:
    """fluid-compat knob bag (reference ExecutionStrategy). Most knobs are
    meaningless under XLA (num_threads, allow_op_delay); kept for API
    parity."""

    def __init__(self):
        self.num_threads = 0
        self.use_cuda = False
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 1


class BuildStrategy:
    """fluid-compat build options. gradient_scale maps to loss scaling;
    reduce_strategy is subsumed by GSPMD."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ""


class ParallelExecutor:
    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None, mesh=None):
        self.program = main_program or framework.default_main_program()
        self.scope = scope or global_scope()
        self.mesh = mesh or make_mesh()
        self.loss_name = loss_name
        self._cache = {}
        self._step = 0
        if share_vars_from is not None:
            self.scope = share_vars_from.scope

    # ------------------------------------------------------------------
    def _spec_fits(self, spec, shape):
        """A PartitionSpec only applies if every sharded dim divides by the
        mesh axis size (XLA GSPMD requirement)."""
        if shape is None:
            return True
        for dim, axes in zip(shape, spec):
            if axes is None:
                continue
            axes = (axes,) if isinstance(axes, str) else axes
            n = 1
            for a in axes:
                n *= self.mesh.axes.get(a, 1)
            if dim % n != 0:
                return False
        return True

    def _spec_axes_known(self, spec):
        """A spec naming a mesh axis this mesh doesn't have (e.g. 'ep'
        weights on a dp-only mesh) falls back to replicated."""
        for axes in spec:
            if axes is None:
                continue
            axes = (axes,) if isinstance(axes, str) else axes
            if any(a not in self.mesh.axes for a in axes):
                return False
        return True

    def _var_sharding(self, name):
        gb = self.program.global_block()
        var = gb.vars.get(name)
        spec = getattr(var, "sharding", None) if var is not None else None
        if spec is None or not self._spec_axes_known(spec):
            return self.mesh.replicated()
        shape = None
        if var.shape is not None and -1 not in var.shape:
            shape = var.shape
        else:
            val = self.scope.find_var(name)
            shape = getattr(val, "shape", None)
        if not self._spec_fits(spec, shape):
            return self.mesh.replicated()
        return NamedSharding(self.mesh.mesh, spec)

    def _feed_sharding(self, name):
        gb = self.program.global_block()
        var = gb.vars.get(name)
        spec = getattr(var, "sharding", None) if var is not None else None
        if spec is not None and self._spec_axes_known(spec):
            return NamedSharding(self.mesh.mesh, spec)
        if "dp" in self.mesh.axis_names:
            return NamedSharding(self.mesh.mesh, P("dp"))
        return self.mesh.replicated()

    # ------------------------------------------------------------------
    def _prepare(self, feed, fetch_list):
        """run()/compiled_stats() shared preamble: fetch names, scope
        state split (donated vs read-only), staged + validated feeds.
        One copy so the stats path provably lowers the same executable
        run() dispatches."""
        feed = feed or {}
        fetch_names = [v.name if isinstance(v, framework.Variable) else v
                       for v in fetch_list]
        gb = self.program.global_block()
        written = written_names(gb)
        persistables = {n for n, v in gb.vars.items() if v.persistable}

        state_rw, state_ro = {}, {}
        for n in sorted(persistables):
            val = self.scope.find_var(n)
            if val is None:
                if n not in written:
                    raise RuntimeError(
                        f"persistable variable {n!r} uninitialized — run "
                        "the startup program on a plain Executor first")
                continue
            (state_rw if n in written else state_ro)[n] = val

        feed_vals = {k: jnp.asarray(np.asarray(v)) for k, v in feed.items()}
        for k, v in feed_vals.items():
            sh = self._feed_sharding(k)
            for dim, axes in zip(v.shape, sh.spec):
                if axes is None:
                    continue
                axes = (axes,) if isinstance(axes, str) else axes
                n = int(np.prod([self.mesh.axes.get(a, 1) for a in axes]))
                if dim % n != 0:
                    raise ValueError(
                        f"feed {k!r} dim of size {dim} is not divisible by "
                        f"the mesh axes {axes} (size {n}); pad the batch or "
                        "resize the mesh")
        return fetch_names, state_rw, state_ro, feed_vals

    def _build_fn(self, fetch_names, state_rw, state_ro, feed_vals):
        """jit the lowered step with this mesh's shardings pinned (the
        cache-miss path of run(); also the stats path)."""
        program = self.program
        step_fn = lower_program(program, fetch_names, "train")
        rw_sh = {n: self._var_sharding(n) for n in state_rw}
        ro_sh = {n: self._var_sharding(n) for n in state_ro}
        fd_sh = {n: self._feed_sharding(n) for n in feed_vals}
        rep = self.mesh.replicated()
        # pin the output state to the same shardings as the input state
        # so donated buffers round-trip with a stable placement; the
        # NaN-guard flags vector is an extra (replicated) output key
        rw_sh_out = dict(rw_sh)
        if getattr(program, "_nan_guard", False):
            rw_sh_out["__nan_guard__"] = rep
        fn = jax.jit(
            make_stepped(step_fn),
            in_shardings=(rw_sh, ro_sh, fd_sh, rep),
            out_shardings=(rw_sh_out, None),
            donate_argnums=(0,))
        fn.step_fn = step_fn
        return fn

    # ------------------------------------------------------------------
    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        feed = feed if feed is not None else (feed_dict or {})
        program = self.program
        fetch_names, state_rw, state_ro, feed_vals = \
            self._prepare(feed, fetch_list)

        key = (program.uid, program.version, tuple(fetch_names))
        fn = self._cache.get(key)
        if fn is None:
            fn = self._build_fn(fetch_names, state_rw, state_ro,
                                feed_vals)
            self._cache[key] = fn

        self._step += 1

        with mesh_scope(self.mesh):
            new_state, fetches = fn(state_rw, state_ro, feed_vals,
                                    step_arg(self._step,
                                             program.random_seed))

        # scope first: state_rw was donated, so a guard raise before
        # this write would leave the scope aimed at deleted buffers
        # (same ordering as core Executor.run)
        for n, v in new_state.items():
            self.scope.set(n, v)

        check_nan_guard(new_state, fn)
        if return_numpy:
            fetches = [np.asarray(v) for v in fetches]
        return fetches

    # ------------------------------------------------------------------
    def compiled_stats(self, fetch_list, feed=None, top_k=10):
        """Measured multichip compile evidence: AOT-lowers exactly the
        sharded executable ``run`` would dispatch (same shardings, same
        lowering) and reports XLA's numbers (flops / bytes_accessed /
        n_kernels / kernel_histogram, as Executor.compiled_stats does)
        PLUS a ``collectives`` histogram — how many all-reduce /
        all-gather / reduce-scatter / collective-permute / all-to-all
        ops GSPMD inserted for this mesh. ``collectives`` is OMITTED
        (not ``{}``) when the optimized HLO text is unavailable
        (``n_kernels == -1``), so callers can tell "no collectives
        inserted" from "text unavailable". This is the compile-time
        artifact behind SURVEY §6's allreduce story: single-process
        environments can't measure collective BANDWIDTH, but the
        compiled module proves which collectives a given sharding
        induces (reference: ParallelExecutor's NCCL AllReduce op
        handles, paddle/fluid/framework/details/)."""
        from ..core.executor import compiled_cost_stats
        fetch_names, state_rw, state_ro, feed_vals = \
            self._prepare(feed or {}, fetch_list)
        fn = self._build_fn(fetch_names, state_rw, state_ro, feed_vals)
        with mesh_scope(self.mesh):
            compiled = fn.lower(
                state_rw, state_ro, feed_vals,
                step_arg(1, self.program.random_seed)).compile()
        stats = compiled_cost_stats(compiled, top_k, include_hlo=True)
        stats["mesh"] = dict(self.mesh.axes)
        hlo_text = stats.pop("hlo_text", None)
        if hlo_text is None:
            # n_kernels == -1: the optimized module text was unavailable.
            # Leaving "collectives" out (rather than {}) lets consumers —
            # notably dryrun_multichip, which treats a missing histogram
            # as fatal — distinguish "no collectives inserted" from
            # "HLO text unavailable".
            return stats
        coll = {}
        for m in _COLLECTIVE_RE.finditer(hlo_text):
            coll[m.group(1)] = coll.get(m.group(1), 0) + 1
        stats["collectives"] = coll
        return stats

    @property
    def device_count(self):
        return self.mesh.size()
