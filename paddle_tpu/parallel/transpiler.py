"""Sharding transpiler — the TPU-native distribute transpiler.

Capability parity with python/paddle/fluid/transpiler/
distribute_transpiler.py: where the reference splits the program into
trainer graphs (send/recv ops) + pserver graphs (param shards +
optimizer blocks), here distribution is declarative: the transpiler
walks the program and ANNOTATES variables with PartitionSpecs; the
ParallelExecutor's jit turns those into GSPMD shardings and XLA emits
the all-gathers/reduce-scatters that the pserver send/recv used to do.

Three strategies, mirroring the reference's deployment modes:
  * data_parallel()     — pure replication + dp-sharded batch
                          (≈ NCCL allreduce mode)
  * shard_optimizer()   — ZeRO-style: params replicated, optimizer
                          accumulators sharded over dp
                          (≈ pserver keeping the optimizer state)
  * tensor_parallel()   — fc/embedding weights split over 'tp' with
                          alternating column/row splits
                          (≈ model-parallel pserver sharding)
"""
from jax.sharding import PartitionSpec as P

from ..core import framework

__all__ = ["ShardingTranspiler", "DistributeTranspiler",
           "DistributeTranspilerConfig"]


class DistributeTranspilerConfig:
    """fluid-compat config (reference distribute_transpiler.py). slice size
    maps loosely onto our sharding granularity decisions."""

    slice_var_up = True
    min_block_size = 8192
    split_method = None


class ShardingTranspiler:
    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()

    # ------------------------------------------------------------------
    def data_parallel(self, program=None):
        """All params replicated; batch sharded by the executor's feed
        sharding. Nothing to annotate (replicated is the default)."""
        return program or framework.default_main_program()

    # ------------------------------------------------------------------
    def shard_optimizer(self, program=None, axis="dp"):
        """ZeRO-1: optimizer accumulators sharded on their largest dim over
        ``axis``; params stay replicated. XLA keeps the update math local
        to each shard and all-gathers merged params only where needed."""
        program = program or framework.default_main_program()
        gb = program.global_block()
        acc_names = self._optimizer_state_names(program)
        for name in acc_names:
            var = gb.vars.get(name)
            if var is None or not var.shape or len(var.shape) == 0:
                continue
            if getattr(var, "sharding", None) is not None:
                # already annotated — e.g. moments of a distributed
                # embedding table inherit the param's P('mp', ...) spec;
                # re-annotating over 'dp' would split the state on a
                # different axis than the param it updates
                continue
            shape = var.shape
            if len(shape) >= 1 and shape[0] not in (-1, 0, 1):
                spec = [None] * len(shape)
                spec[0] = axis
                var.sharding = P(*spec)
        return program

    # ------------------------------------------------------------------
    def tensor_parallel(self, program=None, axis="tp"):
        """Megatron-style alternating split for fc chains: even mul ops
        column-split their weight [in, out/tp], odd ones row-split
        [in/tp, out]; embeddings split the vocab dim. XLA inserts the
        single all-reduce after each row-split matmul."""
        program = program or framework.default_main_program()
        gb = program.global_block()
        col = True
        for op in gb.ops:
            if op.type == "mul":
                wname = op.input("Y")[0]
                var = gb.vars.get(wname)
                if isinstance(var, framework.Parameter) and len(var.shape) == 2:
                    var.sharding = P(None, axis) if col else P(axis, None)
                    col = not col
            elif op.type == "lookup_table":
                wname = op.input("W")[0]
                var = gb.vars.get(wname)
                if isinstance(var, framework.Parameter):
                    var.sharding = P(None, axis)
        return program

    # ------------------------------------------------------------------
    @staticmethod
    def _optimizer_state_names(program):
        """Accumulator vars = persistable inputs of optimizer ops other
        than Param/Grad/LearningRate."""
        out = set()
        opt_types = {"sgd", "momentum", "adam", "adamax", "adagrad",
                     "decayed_adagrad", "adadelta", "rmsprop", "ftrl",
                     "lamb"}
        for op in program.global_block().ops:
            if op.type in opt_types:
                for slot, names in op.inputs.items():
                    if slot in ("Param", "Grad", "LearningRate"):
                        continue
                    out.update(names)
        return out


class DistributeTranspiler(ShardingTranspiler):
    """fluid-compat entry point. ``transpile(trainer_id, pservers=...,
    trainers=N)`` maps the pserver deployment onto mesh sharding: the
    param/optimizer-state distribution the pservers provided becomes
    shard_optimizer(); trainer replication becomes data_parallel."""

    def transpile(self, trainer_id, program=None, pservers="", trainers=1,
                  sync_mode=True, startup_program=None):
        self.trainer_id = trainer_id
        self.trainers = trainers
        program = program or framework.default_main_program()
        self.shard_optimizer(program)
        self._program = program
        return program

    def get_trainer_program(self):
        return self._program

    def get_pserver_program(self, endpoint):
        raise NotImplementedError(
            "TPU deployment has no parameter servers: optimizer state is "
            "mesh-sharded (ZeRO) and synced over ICI collectives. Use "
            "transpile() + ParallelExecutor.")

    def get_startup_program(self, endpoint=None, pserver_program=None):
        return framework.default_startup_program()
