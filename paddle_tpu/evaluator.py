"""Legacy in-program Evaluator API (reference
python/paddle/fluid/evaluator.py — deprecated there in favor of
fluid.metrics, kept for API parity).

The reference versions allocate accumulator variables inside the program
and append update ops; here each evaluator keeps its totals host-side
(identical results, no graph mutation) and exposes the same
create/eval/reset surface.
"""
import warnings

import numpy as np

from . import metrics as _metrics
from .core.executor import global_scope

__all__ = ["ChunkEvaluator", "EditDistance", "DetectionMAP"]


class Evaluator:
    def __init__(self, name, **kwargs):
        warnings.warn(
            f"fluid.evaluator.{name} is deprecated — use fluid.metrics."
            f"{name} (parity with the reference's deprecation)")
        self.metrics = []
        self.states = []

    def reset(self, executor, reset_program=None):
        self._m.reset()

    def eval(self, executor, eval_program=None):
        raise NotImplementedError


class ChunkEvaluator(Evaluator):
    """Accumulates chunk counts from layers.chunk_eval outputs
    (reference evaluator.py ChunkEvaluator)."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None):
        super().__init__("ChunkEvaluator")
        from .layers import metric_op
        (self.precision, self.recall, self.f1_score, self._num_infer,
         self._num_label, self._num_correct) = metric_op.chunk_eval(
            input, label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types)
        self.metrics = [self.precision, self.recall, self.f1_score]
        self._m = _metrics.ChunkEvaluator()

    def update(self, num_infer, num_label, num_correct):
        self._m.update(num_infer, num_label, num_correct)

    def eval(self, executor, eval_program=None):
        return self._m.eval()


class EditDistance(Evaluator):
    def __init__(self, input, label, ignored_tokens=None):
        super().__init__("EditDistance")
        from . import layers
        self.distances, self._seq_num = layers.edit_distance(
            input, label, ignored_tokens=ignored_tokens)
        self.metrics = [self.distances]
        self._m = _metrics.EditDistance()

    def update(self, distances, seq_num=None):
        d = np.asarray(distances)
        self._m.update(d, seq_num if seq_num is not None else d.shape[0])

    def eval(self, executor, eval_program=None):
        return self._m.eval()


class DetectionMAP(Evaluator):
    """Streams layers.detection_map minibatch values (reference
    evaluator.py DetectionMAP accumulates in-program)."""

    def __init__(self, input, gt_label, gt_box, gt_difficult=None,
                 class_num=None, background_label=0,
                 overlap_threshold=0.5, evaluate_difficult=True,
                 ap_version="integral"):
        super().__init__("DetectionMAP")
        from . import layers
        from .layers import detection
        # the op's Label input is the concatenated
        # [label, x1, y1, x2, y2(, difficult)] rows (reference
        # evaluator.py DetectionMAP builds the same via concat)
        parts = [layers.cast(gt_label, "float32"), gt_box]
        if gt_difficult is not None:
            parts.append(layers.cast(gt_difficult, "float32"))
        label = layers.concat(parts, axis=-1)
        self.cur_map = detection.detection_map(
            input, label, class_num=class_num,
            background_label=background_label,
            overlap_threshold=overlap_threshold,
            evaluate_difficult=evaluate_difficult,
            ap_version=ap_version)
        self.metrics = [self.cur_map]
        self._values = []

    def update(self, value):
        self._values.append(float(np.asarray(value).reshape(())))

    def reset(self, executor, reset_program=None):
        self._values = []

    def eval(self, executor, eval_program=None):
        return float(np.mean(self._values)) if self._values else 0.0
