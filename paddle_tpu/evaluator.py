"""Legacy in-program Evaluator API (reference
python/paddle/fluid/evaluator.py — deprecated there in favor of
fluid.metrics, kept for API parity).

The reference versions allocate accumulator variables inside the program
and append update ops; here each evaluator keeps its totals host-side
(identical results, no graph mutation) and exposes the same
create/eval/reset surface.
"""
import warnings

import numpy as np

from . import metrics as _metrics
from .core.executor import global_scope

__all__ = ["ChunkEvaluator", "EditDistance", "DetectionMAP"]


class Evaluator:
    def __init__(self, name, **kwargs):
        warnings.warn(
            f"fluid.evaluator.{name} is deprecated — use fluid.metrics."
            f"{name} (parity with the reference's deprecation)")
        self.metrics = []
        self.states = []

    def reset(self, executor, reset_program=None):
        self._m.reset()

    def eval(self, executor, eval_program=None):
        raise NotImplementedError


class ChunkEvaluator(Evaluator):
    """Accumulates chunk counts from layers.chunk_eval outputs
    (reference evaluator.py ChunkEvaluator)."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None):
        super().__init__("ChunkEvaluator")
        from .layers import metric_op
        (self.precision, self.recall, self.f1_score, self._num_infer,
         self._num_label, self._num_correct) = metric_op.chunk_eval(
            input, label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types)
        self.metrics = [self.precision, self.recall, self.f1_score]
        self._m = _metrics.ChunkEvaluator()

    def update(self, num_infer, num_label, num_correct):
        self._m.update(num_infer, num_label, num_correct)

    def eval(self, executor, eval_program=None):
        return self._m.eval()


class EditDistance(Evaluator):
    def __init__(self, input, label, ignored_tokens=None):
        super().__init__("EditDistance")
        from . import layers
        self.distances, self._seq_num = layers.edit_distance(
            input, label, ignored_tokens=ignored_tokens)
        self.metrics = [self.distances]
        self._m = _metrics.EditDistance()

    def update(self, distances, seq_num=None):
        d = np.asarray(distances)
        self._m.update(d, seq_num if seq_num is not None else d.shape[0])

    def eval(self, executor, eval_program=None):
        return self._m.eval()


class DetectionMAP(Evaluator):
    """Dataset-level VOC mAP (reference evaluator.py DetectionMAP).

    The reference accumulates AccumTruePos/AccumFalsePos/AccumPosCount
    in-program; here the detection_map op emits per-batch MatchInfo
    rows [label, score, tp, valid] + per-class GTCount, the evaluator
    accumulates them host-side, and eval() computes the dataset AP —
    the same metric, without in-graph dynamic state."""

    def __init__(self, input, gt_label, gt_box, gt_difficult=None,
                 class_num=None, background_label=0,
                 overlap_threshold=0.5, evaluate_difficult=True,
                 ap_version="integral"):
        super().__init__("DetectionMAP")
        from . import layers
        from .layers import detection
        # the op's 6-wide Label rows are [label, difficult, x1..y2]
        # (reference detection_map_op.h GetBoxes order)
        if gt_difficult is not None:
            parts = [layers.cast(gt_label, "float32"),
                     layers.cast(gt_difficult, "float32"), gt_box]
        else:
            parts = [layers.cast(gt_label, "float32"), gt_box]
        label = layers.concat(parts, axis=-1)
        self.cur_map = detection.detection_map(
            input, label, class_num=class_num,
            background_label=background_label,
            overlap_threshold=overlap_threshold,
            evaluate_difficult=evaluate_difficult,
            ap_version=ap_version)
        # fetch [cur_map, match_info, gt_count] and feed them to update()
        self.metrics = [self.cur_map, self.cur_map.match_info,
                        self.cur_map.gt_count]
        self._class_num = class_num
        self._background = background_label
        self._ap_version = ap_version
        self._values = []
        self._match_rows = []
        self._gt_counts = np.zeros((class_num,), np.int64)

    def update(self, value, match_info=None, gt_count=None):
        self._values.append(float(np.asarray(value).reshape(())))
        if match_info is not None:
            rows = np.asarray(match_info).reshape(-1, 4)
            self._match_rows.append(rows[rows[:, 3] > 0])
        if gt_count is not None:
            self._gt_counts += np.asarray(gt_count).reshape(-1)

    def reset(self, executor, reset_program=None):
        self._values = []
        self._match_rows = []
        self._gt_counts = np.zeros((self._class_num,), np.int64)

    def _dataset_map(self):
        rows = np.concatenate(self._match_rows, axis=0)
        aps = []
        for c in range(self._class_num):
            if c == self._background:
                continue
            n_gt = int(self._gt_counts[c])
            if n_gt == 0:
                continue
            sel = rows[rows[:, 0].astype(np.int64) == c]
            if sel.shape[0] == 0:
                aps.append(0.0)
                continue
            order = np.argsort(-sel[:, 1], kind="stable")
            tp = sel[order, 2]
            tp_cum = np.cumsum(tp)
            fp_cum = np.cumsum(1.0 - tp)
            recall = tp_cum / max(n_gt, 1)
            precision = tp_cum / np.maximum(tp_cum + fp_cum, 1e-12)
            if self._ap_version == "11point":
                ap = float(np.mean([
                    np.max(precision[recall >= t], initial=0.0)
                    for t in np.linspace(0.0, 1.0, 11)]))
            else:
                prev = np.concatenate([[0.0], recall[:-1]])
                ap = float(np.sum((recall - prev) * precision))
            aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0

    def eval(self, executor, eval_program=None):
        if self._match_rows:
            return self._dataset_map()
        return float(np.mean(self._values)) if self._values else 0.0
