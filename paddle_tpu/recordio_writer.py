"""Convert python readers into native recordio files — parity with
python/paddle/fluid/recordio_writer.py (convert_reader_to_recordio_file
:34, convert_reader_to_recordio_files:69).

One record per sample, each record the per-variable arrays encoded by
``paddle_tpu.io.recordio`` (the C++ chunked format in native/recordio.cc)
— exactly what ``layers.open_recordio_file`` / ``open_files`` read back.
The ``feeder`` supplies per-variable dtype/LoD metadata, mirroring the
reference's DataFeeder-mediated serialization.
"""
import numpy as np

from .io.recordio import Writer, _encode_arrays

__all__ = [
    "convert_reader_to_recordio_file", "convert_reader_to_recordio_files",
]


def _map_compressor(name):
    return {"none": "none", "gzip": "gzip", "snappy": "gzip"}[name]


def _sample_arrays(sample, feed_vars):
    out = []
    for value, var in zip(sample, feed_vars):
        dtype = np.dtype(var.dtype)
        arr = np.asarray(value, dtype=dtype)
        if var.lod_level > 0 and arr.ndim == 1:
            arr = arr.reshape(-1, 1)
        out.append(arr)
    return out


def convert_reader_to_recordio_file(filename, reader_creator, feeder,
                                    compressor="snappy",
                                    max_num_records=1000, feed_order=None):
    """Write every sample of ``reader_creator()`` to ``filename``.
    Returns the number of records written. The reference's Snappy codec
    maps onto the native writer's gzip (native/recordio.cc supports
    none|gzip)."""
    feed_vars = feeder.feed_vars
    if feed_order is not None:
        by_name = {v.name: v for v in feed_vars}
        feed_vars = [by_name[n] for n in feed_order]
    n = 0
    with Writer(filename, max_num_records,
                _map_compressor(compressor)) as w:
        for sample in reader_creator():
            w.write(_encode_arrays(_sample_arrays(sample, feed_vars)))
            n += 1
    return n


def convert_reader_to_recordio_files(filename, batch_per_file,
                                     reader_creator, feeder,
                                     compressor="snappy",
                                     max_num_records=1000,
                                     feed_order=None):
    """Shard the reader across files of ``batch_per_file`` records each,
    named ``<filename>-00000`` etc. Returns the list of paths written."""
    feed_vars = feeder.feed_vars
    if feed_order is not None:
        by_name = {v.name: v for v in feed_vars}
        feed_vars = [by_name[n] for n in feed_order]
    paths, w, n = [], None, 0
    try:
        for sample in reader_creator():
            if w is None or n % batch_per_file == 0:
                if w is not None:
                    w.close()
                paths.append("%s-%05d" % (filename, len(paths)))
                w = Writer(paths[-1], max_num_records,
                           _map_compressor(compressor))
            w.write(_encode_arrays(_sample_arrays(sample, feed_vars)))
            n += 1
    finally:
        if w is not None:
            w.close()
    return paths
