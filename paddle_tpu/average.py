"""WeightedAverage (reference python/paddle/fluid/average.py:40)."""
import numpy as np

__all__ = ["WeightedAverage"]


class WeightedAverage:
    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = None
        self.denominator = None

    def add(self, value, weight):
        value = np.asarray(value, dtype=np.float64)
        if value.ndim > 1 or (value.ndim == 1 and value.shape[0] != 1):
            raise ValueError("add() expects a scalar value")
        v = float(value.reshape(-1)[0])
        w = float(weight)
        if self.numerator is None:
            self.numerator, self.denominator = 0.0, 0.0
        self.numerator += v * w
        self.denominator += w

    def eval(self):
        if not self.denominator:
            raise ValueError(
                "there is no data in WeightedAverage; call add() first")
        return self.numerator / self.denominator
