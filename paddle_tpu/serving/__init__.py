"""paddle_tpu.serving — inference serving: dynamic micro-batching over
pre-compiled shape buckets, admission control, serving metrics, and
continuous batching for LLM decode.

The one-executable-per-program design (ARCHITECTURE.md) makes serving
a shape-discipline problem: XLA wants every shape pinned, traffic
arrives one request at a time. This package closes that gap —
``BucketSpec`` declares the padded shapes, ``ServingEngine`` coalesces
concurrent requests into bucket-shaped micro-batches under a deadline,
warms every bucket at load, sheds at capacity, and reports itself via
``stats()``. Failure is a defined state, not an accident (health.py):
a health state machine + hang watchdog, engine- and per-bucket circuit
breakers, graceful drain (``close(drain=True)``), and deadline
propagation into dispatch retries. See docs/SERVING.md.

Autoregressive decode gets its own engine (decode_engine.py):
``DecodeEngine`` schedules at iteration level over a paged KV cache
(kv_pages.py) — requests join and leave the fixed-shape decode batch
every step, the executable compiles once per (model, max_batch) and
never again, and speculative decoding is an engine mode. See
docs/SERVING.md "Continuous decode batching".

    from paddle_tpu import serving
    eng = serving.ServingEngine.from_saved_model("./model_dir",
              buckets=serving.BucketSpec(batch_sizes=(1, 4, 8)))
    eng.warmup()
    out = eng.infer({"img": x})          # x: [1, ...] single sample
"""
from .batching import (MicroBatcher, PendingResult, QueueFullError,  # noqa: F401
                       RequestTimeoutError, ServerClosedError,
                       ServingError)
from .buckets import BucketError, BucketSpec                         # noqa: F401
from .decode_engine import (DecodeConfig, DecodeEngine,              # noqa: F401
                            DecodeRequest)
from .engine import ServingConfig, ServingEngine                     # noqa: F401
from .health import (CircuitBreaker, HealthMonitor, HealthState,     # noqa: F401
                     ServiceUnavailableError, WorkerDiedError)
from .kv_pages import PageAllocator, PagesExhaustedError             # noqa: F401
from .metrics import ServingMetrics                                  # noqa: F401
from .overload import (AdmissionController, BrownoutController,      # noqa: F401
                       RetryBudget, RetryBudgetExhaustedError)
from .sched import (PRIORITIES, FIFOScheduler, SLOClass,             # noqa: F401
                    SLOScheduler, get_scheduler, priority_rank)

__all__ = ["AdmissionController", "BrownoutController", "BucketError",
           "BucketSpec", "CircuitBreaker", "DecodeConfig",
           "DecodeEngine", "DecodeRequest", "FIFOScheduler",
           "HealthMonitor", "HealthState", "MicroBatcher",
           "PRIORITIES", "PageAllocator", "PagesExhaustedError",
           "PendingResult", "QueueFullError", "RequestTimeoutError",
           "RetryBudget", "RetryBudgetExhaustedError", "SLOClass",
           "SLOScheduler", "ServerClosedError",
           "ServiceUnavailableError", "ServingError", "ServingConfig",
           "ServingEngine", "ServingMetrics", "WorkerDiedError",
           "get_scheduler", "priority_rank"]
