"""Paged KV-cache bookkeeping — the host side of continuous batching.

XLA executables are fixed-shape, so the decode engine's KV cache is a
static pool ``[n_layers, n_pages, page_size, kv_heads, head_dim]`` and
all dynamism lives in *integer indices*: each active slot owns a set of
pages, listed in a per-slot page TABLE that is fed to the decode-step
program every dispatch. Joining a batch is allocating pages and writing
a table row; leaving is returning the pages. Nothing about request
churn ever changes a traced shape (the vLLM PagedAttention idea, under
this repo's one-executable-per-program discipline).

Page 0 is reserved as the **null page**: inactive slots point every
table entry at it, so their (discarded) lockstep writes land somewhere
harmless, and the attention length mask guarantees it is never read
back into a real row. Freed pages are NOT zeroed — the mask already
makes stale contents unobservable (pinned by test: a request reusing a
retired request's pages is bit-identical to running it alone); the
allocator only enforces the integer invariants (no double alloc, no
double free, exhaustion is a typed shed).

Pure host-side integers: no jax, no numpy, trivially unit-testable.
"""
from .batching import QueueFullError

__all__ = ["PagesExhaustedError", "PageAllocator"]


class PagesExhaustedError(QueueFullError):
    """The page pool cannot satisfy an allocation. Subclasses
    QueueFullError deliberately: to a client this is the same load-shed
    contract — back off and retry (or the request can NEVER fit, which
    submit() rejects up front)."""


class PageAllocator:
    """Fixed pool of ``n_pages`` KV pages of ``page_size`` positions.

    Page 0 is the reserved null page and is never handed out; the
    usable pool is pages 1..n_pages-1. ``alloc`` returns pages in
    ascending order (determinism for tests), ``free`` returns them.
    """

    def __init__(self, n_pages, page_size):
        if n_pages < 2:
            raise ValueError(
                f"n_pages must be >= 2 (page 0 is the reserved null "
                f"page), got {n_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self._free = set(range(1, self.n_pages))

    # -- capacity queries ------------------------------------------------
    @property
    def usable_pages(self):
        """Total allocatable pages (the pool minus the null page)."""
        return self.n_pages - 1

    @property
    def available(self):
        return len(self._free)

    @property
    def in_use(self):
        return self.usable_pages - len(self._free)

    def pages_for(self, n_positions):
        """Pages needed to cover ``n_positions`` sequence positions."""
        if n_positions < 1:
            raise ValueError(
                f"n_positions must be >= 1, got {n_positions}")
        return -(-int(n_positions) // self.page_size)

    # -- alloc / free ----------------------------------------------------
    def alloc(self, n):
        """Allocate ``n`` pages or raise PagesExhaustedError (leaving
        the pool untouched — no partial grants)."""
        n = int(n)
        if n < 1:
            raise ValueError(f"alloc needs n >= 1, got {n}")
        if n > len(self._free):
            raise PagesExhaustedError(
                f"KV page pool exhausted: need {n} pages, "
                f"{len(self._free)}/{self.usable_pages} free — load "
                "shed, retry with backoff (or grow n_pages)")
        got = sorted(self._free)[:n]
        self._free.difference_update(got)
        return got

    def free(self, pages):
        """Return pages to the pool. Double-free and null-page returns
        are invariant violations and raise."""
        pages = list(pages)
        for p in pages:
            if not 1 <= p < self.n_pages:
                raise ValueError(
                    f"free of page {p} outside the usable pool "
                    f"[1, {self.n_pages})")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
        self._free.update(pages)

    # -- KV handoff hooks ------------------------------------------------
    def export_state(self, pages):
        """Bookkeeping half of a KV handoff export: validate that
        every page is a live allocation of THIS pool (exporting a
        freed or out-of-range page would ship garbage the length mask
        no longer protects) and return the allocator-level state that
        travels with the page contents. Page ids are exporter-local —
        import allocates fresh pages, so the blob is
        location-independent."""
        pages = [int(p) for p in pages]
        for p in pages:
            if not 1 <= p < self.n_pages:
                raise ValueError(
                    f"cannot export page {p}: outside the usable "
                    f"pool [1, {self.n_pages})")
            if p in self._free:
                raise ValueError(
                    f"cannot export page {p}: not a live allocation")
        return {"pages": pages, "page_size": self.page_size}

    def import_alloc(self, state, total=None):
        """Allocation half of a KV handoff import: check geometry
        compatibility (a page_size mismatch would silently misalign
        every position past the first page) and allocate fresh local
        pages — at least as many as the export used, or ``total`` if
        the importer needs headroom for decode. Raises
        PagesExhaustedError like any alloc (the caller requeues)."""
        if int(state.get("page_size", -1)) != self.page_size:
            raise ValueError(
                f"handoff page_size {state.get('page_size')!r} does "
                f"not match this pool's page_size {self.page_size}")
        n = len(state["pages"])
        if total is not None:
            n = max(n, int(total))
        return self.alloc(n)
