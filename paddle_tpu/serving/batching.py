"""Micro-batching queue with admission control.

The throughput story of a TPU server is request coalescing: one
device dispatch amortizes over a device-sized batch (the TF-Serving
batching lesson — Abadi et al., 2016). This module is the host-side
half of that: a bounded, condition-variable-guarded queue that groups
compatible requests into micro-batches under a deadline.

Policy (``MicroBatcher.next_batch``):

- A batch flushes when it holds ``max_batch_size`` rows, OR when
  ``max_wait_s`` has elapsed since its *oldest* member arrived —
  bounded latency even at trickle traffic.
- Only requests with the same shape ``signature`` coalesce (see
  buckets.py): the head-of-queue request picks the signature, and the
  scan takes same-signature followers up to capacity. Different-
  signature requests wait for the next pop (mild head-of-line
  blocking, zero cross-request numeric coupling).
- Admission control is at ``put``: a full queue sheds the request
  *immediately* with :class:`QueueFullError` instead of queueing into
  unbounded latency. Expired requests are swept at pop time and
  fulfilled with :class:`RequestTimeoutError` rather than occupying
  batch slots.

No executor, no numpy — pure queueing, deterministic under an
injectable clock, so tier-1 tests pin the flush/shed/timeout logic
without sleeping.
"""
import threading

__all__ = ["QueueFullError", "RequestTimeoutError", "ServerClosedError",
           "ServingError", "PendingResult", "MicroBatcher"]


class ServingError(RuntimeError):
    """Base class of structured serving-layer failures."""


class QueueFullError(ServingError):
    """Load shed: the admission queue is at capacity. The client should
    back off and retry — queueing deeper would only convert overload
    into unbounded tail latency."""


class RequestTimeoutError(ServingError, TimeoutError):
    """The request's deadline expired before (or while) it could be
    served."""


class ServerClosedError(ServingError):
    """The engine is shut down; no new work is accepted."""


class PendingResult:
    """The caller's handle for an in-flight request: an event the
    worker fulfills with either a result or a structured error.

    Fulfillment is first-writer-wins: the worker and the watchdog may
    race to settle the same request (batch completes just as the
    watchdog declares the worker dead), and the caller must see ONE
    consistent outcome, never a result overwritten by a late error."""

    __slots__ = ("feed", "n_rows", "signature", "deadline", "enqueued_at",
                 "_event", "_result", "_error", "_settle_lock",
                 "_callbacks")

    def __init__(self, feed, n_rows, signature, deadline, enqueued_at):
        self.feed = feed
        self.n_rows = n_rows
        self.signature = signature
        self.deadline = deadline            # monotonic seconds or None
        self.enqueued_at = enqueued_at
        self._event = threading.Event()
        self._result = None
        self._error = None
        self._settle_lock = threading.Lock()
        self._callbacks = []

    def done(self):
        return self._event.is_set()

    def remaining(self, now):
        """Seconds of deadline left at ``now`` (None = no deadline)."""
        if self.deadline is None:
            return None
        return self.deadline - now

    def add_done_callback(self, fn):
        """Call ``fn(self)`` exactly once when this handle settles
        (result OR error); immediately if it already has. The router
        uses this to observe sojourn and release per-class admission
        accounting without polling. Callback exceptions are swallowed
        — settlement must never fail because an observer did."""
        with self._settle_lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        self._run_callback(fn)

    def _run_callback(self, fn):
        try:
            fn(self)
        except Exception:       # noqa: BLE001 — observer must not break settle
            pass

    def set_result(self, value):
        with self._settle_lock:
            if self._event.is_set():
                return False
            self._result = value
            self._event.set()
            cbs, self._callbacks = self._callbacks, []
        for fn in cbs:           # outside the lock: observers may block
            self._run_callback(fn)
        return True

    def set_error(self, exc):
        with self._settle_lock:
            if self._event.is_set():
                return False
            self._error = exc
            self._event.set()
            cbs, self._callbacks = self._callbacks, []
        for fn in cbs:
            self._run_callback(fn)
        return True

    def wait(self, timeout=None):
        """Block up to ``timeout`` for settlement; True iff settled.
        Unlike :meth:`result` this never raises — the liveness-aware
        wait loop in ``ServingEngine.infer`` polls it between worker
        health checks."""
        return self._event.wait(timeout)

    def result(self, timeout=None):
        """Block for the outcome; raises the structured error on
        failure. ``timeout`` here is a wait bound on the *caller's*
        side (the serving deadline lives in the engine)."""
        if not self._event.wait(timeout):
            raise RequestTimeoutError(
                "result not ready within the wait bound")
        if self._error is not None:
            raise self._error
        return self._result


class MicroBatcher:
    """Bounded request queue + deadline-driven micro-batch assembly.

    ``max_batch_size`` counts ROWS (a request may carry several rows).
    ``max_wait_s`` bounds how long the oldest queued request may wait
    for peers before its batch flushes partially filled. ``max_queue``
    bounds queued requests; beyond it, ``put`` sheds. ``clock`` is
    injectable (monotonic seconds) for deterministic tests.
    """

    def __init__(self, max_batch_size, max_wait_s=0.002, max_queue=64,
                 clock=None):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        import time
        self.max_batch_size = int(max_batch_size)
        self.max_wait_s = float(max_wait_s)
        self.max_queue = int(max_queue)
        self.clock = clock or time.monotonic
        self._q = []
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._closed = False

    # -- producer side ---------------------------------------------------
    def put(self, request):
        """Admit ``request`` or shed it. Raises QueueFullError (queue at
        capacity) or ServerClosedError (after close)."""
        with self._lock:
            if self._closed:
                raise ServerClosedError("serving engine is closed")
            if len(self._q) >= self.max_queue:
                raise QueueFullError(
                    f"admission queue full ({self.max_queue} requests) "
                    "— load shed, retry with backoff")
            self._q.append(request)
            self._nonempty.notify()

    def depth(self):
        with self._lock:
            return len(self._q)

    def close(self):
        """Stop admitting; wake any blocked consumer."""
        with self._lock:
            self._closed = True
            self._nonempty.notify_all()

    @property
    def closed(self):
        return self._closed

    def drain(self):
        """Remove and return everything still queued (engine shutdown
        fulfills these with ServerClosedError)."""
        with self._lock:
            q, self._q = self._q, []
            return q

    # -- consumer side ---------------------------------------------------
    def next_batch(self, poll_s=0.05, on_poll=None):
        """Block until a batch is ready; returns ``(batch, expired)``.

        ``batch`` is a same-signature request list whose rows fit
        ``max_batch_size`` (empty only when closed and drained).
        ``expired`` are deadline-blown requests swept from the queue —
        the caller fulfills them with RequestTimeoutError and serves
        the rest. ``poll_s`` caps each internal wait so a closed flag
        is always noticed promptly. ``on_poll`` (if given) is invoked
        once per internal wait iteration — the serving worker passes
        its heartbeat here so liveness keeps ticking while the
        consumer idles inside this call (a heartbeat only at the
        call boundary would read as a hang on an idle queue)."""
        with self._lock:
            while True:
                if on_poll is not None:
                    on_poll()
                now = self.clock()
                expired = [r for r in self._q
                           if r.deadline is not None and now >= r.deadline]
                if expired:
                    # sweep first and report: blown deadlines must be
                    # fulfilled before any compute is spent on peers
                    self._q = [r for r in self._q if r not in expired]
                    return [], expired
                if self._q:
                    head_age_flush = (
                        self._q[0].enqueued_at + self.max_wait_s <= now)
                    rows = 0
                    batch = []
                    sig = self._q[0].signature
                    for r in self._q:
                        if r.signature != sig:
                            continue
                        if rows + r.n_rows > self.max_batch_size \
                                and batch:
                            break
                        batch.append(r)
                        rows += r.n_rows
                        if rows >= self.max_batch_size:
                            break
                    if rows >= self.max_batch_size or head_age_flush \
                            or self._closed:
                        self._q = [r for r in self._q if r not in batch]
                        return batch, expired
                    # not full yet: wait out the remainder of the
                    # head's deadline window (or a queue change)
                    remaining = (self._q[0].enqueued_at
                                 + self.max_wait_s - now)
                    self._nonempty.wait(min(max(remaining, 1e-4),
                                            poll_s))
                    continue
                if self._closed:
                    return [], []
                self._nonempty.wait(poll_s)
