"""Serving metrics registry — the latency/throughput instruments an
operator tunes batching with.

One lock-guarded registry per engine: monotonic counters (requests,
responses, batches, sheds, timeouts, errors, retries, breaker
opens/sheds/probes, watchdog firings, drained requests), row accounting
for the batch-fill ratio (real rows vs padded bucket capacity — THE
number that says whether max_wait is too short or buckets too coarse),
a queue-depth gauge sampled by the worker, and a bounded reservoir of
per-request latencies for p50/p95/p99. ``stats()`` returns a plain
dict snapshot (json-serializable — tools/servebench.py prints it
verbatim); ``counter_deltas`` helps tests assert exact increments.

Deliberately not the fluid-parity training metrics in
paddle_tpu/metrics.py (accuracy/auc over minibatches): these are
server-side operational metrics, a different axis entirely.
"""
import threading

import numpy as np

__all__ = ["ServingMetrics"]

_COUNTERS = ("requests_total", "responses_total", "batches_total",
             "shed_total", "timeouts_total", "errors_total",
             "retries_total", "rows_total", "padded_rows_total",
             "warmup_compiles",
             # hardening counters (docs/SERVING.md "Operating under
             # failure"): breaker lifecycle, watchdog firings, drain
             "breaker_open_total", "breaker_shed_total",
             "breaker_probe_total", "worker_died_total",
             "drained_total")

# bounded latency reservoir: enough samples for stable tail estimates,
# O(1) memory under sustained traffic (newest-window semantics)
_LATENCY_WINDOW = 4096


class ServingMetrics:
    """Thread-safe counters + latency percentiles for one engine.

    ``extra_counters`` extends the counter vocabulary for specialized
    engines (the continuous-batching decode engine counts prefills,
    decode dispatches, generated tokens, speculation acceptance);
    ``observe_window``/named windows do the same for latency axes
    beyond request latency (TTFT, TPOT, per-step service time).
    """

    def __init__(self, extra_counters=()):
        self._lock = threading.Lock()
        self._counters = {name: 0
                          for name in _COUNTERS + tuple(extra_counters)}
        self._latencies = []          # seconds, newest-window bounded
        self._batch_latencies = []
        self._windows = {}            # name -> bounded sample list
        self._queue_depth = 0
        self._queue_depth_peak = 0

    # -- recording -------------------------------------------------------
    def incr(self, name, n=1):
        with self._lock:
            if name not in self._counters:
                raise KeyError(f"unknown serving counter {name!r}; one "
                               f"of {sorted(self._counters)}")
            self._counters[name] += n

    def observe_batch(self, n_rows, bucket_rows, batch_latency_s):
        """One executed micro-batch: real rows, padded bucket capacity,
        and the worker-side batch service time."""
        with self._lock:
            self._counters["batches_total"] += 1
            self._counters["rows_total"] += int(n_rows)
            self._counters["padded_rows_total"] += int(bucket_rows)
            self._batch_latencies.append(float(batch_latency_s))
            del self._batch_latencies[:-_LATENCY_WINDOW]

    def observe_latency(self, seconds):
        """One fulfilled request's enqueue→response latency."""
        with self._lock:
            self._latencies.append(float(seconds))
            del self._latencies[:-_LATENCY_WINDOW]

    def observe_window(self, name, seconds):
        """One sample into the named latency window (created on first
        use; bounded like the request-latency reservoir). Non-finite
        samples are dropped at the door — a single NaN must never
        poison every percentile in the snapshot."""
        v = float(seconds)
        if not np.isfinite(v):
            return
        with self._lock:
            w = self._windows.setdefault(name, [])
            w.append(v)
            del w[:-_LATENCY_WINDOW]

    def set_queue_depth(self, depth):
        with self._lock:
            self._queue_depth = int(depth)
            self._queue_depth_peak = max(self._queue_depth_peak, depth)

    # -- snapshot --------------------------------------------------------
    @staticmethod
    def _percentiles(samples):
        """Percentile summary that is safe on an empty or one-sample
        window and in the presence of non-finite samples: an engine's
        stats() must be callable from the first instant of its life
        (servebench polls it mid-warmup) without IndexError/NaN."""
        arr = np.asarray(samples, dtype=np.float64)
        if arr.size:
            arr = arr[np.isfinite(arr)]
        if not arr.size:
            return {"p50_ms": None, "p95_ms": None, "p99_ms": None,
                    "count": 0}
        arr = arr * 1e3
        p50, p95, p99 = np.percentile(arr, [50.0, 95.0, 99.0])
        return {"p50_ms": round(float(p50), 3),
                "p95_ms": round(float(p95), 3),
                "p99_ms": round(float(p99), 3),
                "count": int(arr.size)}

    def stats(self):
        """Plain-dict snapshot: counters, batch-fill ratio, queue
        depth, request-latency percentiles."""
        with self._lock:
            counters = dict(self._counters)
            padded = counters["padded_rows_total"]
            snap = dict(counters)
            snap["batch_fill_ratio"] = (
                round(counters["rows_total"] / padded, 4) if padded
                else None)
            snap["mean_batch_rows"] = (
                round(counters["rows_total"]
                      / counters["batches_total"], 3)
                if counters["batches_total"] else None)
            snap["queue_depth"] = self._queue_depth
            snap["queue_depth_peak"] = self._queue_depth_peak
            snap["request_latency"] = self._percentiles(self._latencies)
            snap["batch_latency"] = self._percentiles(
                self._batch_latencies)
            for name, w in sorted(self._windows.items()):
                snap[name] = self._percentiles(w)
            return snap

    @classmethod
    def merge(cls, *others, label=None):
        """Combine per-replica registries into one cluster-level view
        (paddle_tpu/cluster/ pool ``stats()`` builds its pool-wide
        p50/p95/p99 with this). Counters sum over the UNION of the
        vocabularies (a pool may mix classifier and decode replicas,
        whose extra counters differ); latency reservoirs and named
        windows concatenate and re-bound to the newest
        ``_LATENCY_WINDOW`` samples, so the merged percentiles weight
        each replica by how many samples it actually served. Queue
        depth sums (the cluster's total backlog); the peak sum is an
        upper bound, not a witnessed instant — replicas peak at
        different times. Empty registries and non-finite samples merge
        harmlessly (``_percentiles`` already filters non-finite).

        ``label`` namespaces the merge: every merged counter and
        latency window lands under ``"<label>/<name>"`` (the base
        request/batch reservoirs become the ``<label>/request_latency``
        and ``<label>/batch_latency`` windows) so a pool serving two
        model versions side by side can merge each version under its
        own prefix and then merge THOSE into one registry without the
        versions' counters colliding — the canary's error count must
        never be laundered into the incumbent's."""
        merged = cls()
        prefix = "" if label is None else f"{label}/"
        for o in others:
            with o._lock:
                counters = dict(o._counters)
                lat = list(o._latencies)
                blat = list(o._batch_latencies)
                windows = {n: list(w) for n, w in o._windows.items()}
                depth = o._queue_depth
                peak = o._queue_depth_peak
            for name, v in counters.items():
                key = prefix + name
                merged._counters[key] = \
                    merged._counters.get(key, 0) + v
            if label is None:
                merged._latencies.extend(lat)
                merged._batch_latencies.extend(blat)
            else:
                merged._windows.setdefault(
                    prefix + "request_latency", []).extend(lat)
                merged._windows.setdefault(
                    prefix + "batch_latency", []).extend(blat)
            for name, w in windows.items():
                merged._windows.setdefault(prefix + name, []).extend(w)
            merged._queue_depth += depth
            merged._queue_depth_peak += peak
        del merged._latencies[:-_LATENCY_WINDOW]
        del merged._batch_latencies[:-_LATENCY_WINDOW]
        for w in merged._windows.values():
            del w[:-_LATENCY_WINDOW]
        return merged

    def counter_deltas(self, before):
        """Counter changes since a previous ``stats()`` snapshot —
        tests assert exact shed/timeout increments with this."""
        now = self.stats()
        with self._lock:
            names = tuple(self._counters)
        return {k: now[k] - before.get(k, 0) for k in names}
