"""Shape buckets: the fixed-shape contract between serving and XLA.

paddle_tpu compiles one executable per program *and feed-shape
signature* (jax.jit re-specializes on shapes), so a server that let
request shapes float would recompile — seconds to minutes — in the
middle of traffic. The Julia→TPU full-compilation work (Fischer &
Saba, 2018) hits the identical constraint: whole-program XLA wants
every shape pinned ahead of time. The serving answer is a small,
pre-declared set of shape buckets:

- **batch buckets** — allowed padded batch sizes (e.g. 1, 2, 4, 8). A
  micro-batch of 3 requests pads up to the 4-bucket by replicating a
  real row (replication, not zeros, so models with data-dependent
  numerics never see synthetic garbage), runs, and the pad rows are
  sliced off before results return to callers.
- **length buckets** — for sequence inputs (dim 1), allowed padded
  lengths per input name. Requests are *grouped* by their length
  signature before coalescing (batching.py), so a request's numbers
  never depend on which peers it shared a batch with.

``BucketSpec`` is pure policy + padding math: no threads, no executor,
fully unit-testable. ``ServingEngine.warmup`` walks
``all_signatures()`` to pre-compile every executable the spec can ever
produce, and steady-state traffic then hits only those.
"""
import numpy as np

__all__ = ["BucketError", "BucketSpec"]


class BucketError(ValueError):
    """A request does not fit any declared bucket (batch rows or a
    sequence length exceed the largest declared size). Structured —
    admission control rejects the request up front rather than letting
    it poison the compile cache with a novel shape."""


def _validate_sizes(sizes, what):
    sizes = tuple(sorted(set(int(s) for s in sizes)))
    if not sizes or sizes[0] < 1:
        raise ValueError(f"{what} must be a non-empty set of positive "
                         f"ints, got {sizes!r}")
    return sizes


class BucketSpec:
    """Declares the padded-shape lattice the server may run.

    ``batch_sizes``: allowed padded batch sizes, e.g. ``(1, 2, 4, 8)``.
    ``seq_lens``: optional ``{input_name: (lens...)}`` — inputs whose
    dim 1 is variable and must pad up to a declared length.
    ``pad_values``: optional ``{input_name: scalar}`` used when padding
    sequence positions (default 0 — a pad/eos id for token inputs).
    """

    def __init__(self, batch_sizes=(1, 2, 4, 8), seq_lens=None,
                 pad_values=None):
        self.batch_sizes = _validate_sizes(batch_sizes, "batch_sizes")
        self.seq_lens = {name: _validate_sizes(lens, f"seq_lens[{name}]")
                         for name, lens in (seq_lens or {}).items()}
        self.pad_values = dict(pad_values or {})

    @property
    def max_batch(self):
        return self.batch_sizes[-1]

    # -- persistence -----------------------------------------------------
    def to_manifest(self):
        """Plain-JSON form for the save_inference_model serving
        manifest: a fresh replica rebuilds the exact warmup compile
        set from this instead of guessing buckets (io/__init__.py
        writes it, from_saved_model reads it)."""
        return {"batch_sizes": list(self.batch_sizes),
                "seq_lens": {n: list(l)
                             for n, l in self.seq_lens.items()},
                "pad_values": dict(self.pad_values)}

    @classmethod
    def from_manifest(cls, manifest):
        return cls(batch_sizes=manifest["batch_sizes"],
                   seq_lens=manifest.get("seq_lens") or None,
                   pad_values=manifest.get("pad_values") or None)

    # -- bucket selection ------------------------------------------------
    def batch_bucket(self, n_rows):
        """Smallest declared batch size >= n_rows."""
        for b in self.batch_sizes:
            if b >= n_rows:
                return b
        raise BucketError(
            f"batch of {n_rows} rows exceeds the largest declared "
            f"batch bucket {self.max_batch} — declare a bigger bucket "
            f"or split the request")

    def seq_bucket(self, name, length):
        """Smallest declared length bucket >= length for input ``name``
        (inputs without declared length buckets pass through)."""
        lens = self.seq_lens.get(name)
        if lens is None:
            return length
        for l in lens:
            if l >= length:
                return l
        raise BucketError(
            f"input {name!r} length {length} exceeds the largest "
            f"declared length bucket {lens[-1]}")

    def signature(self, feed):
        """The shape-group key for a request feed: a sorted tuple of
        (input_name, padded_seq_len) for every length-bucketed input.
        Only requests with EQUAL signatures may share a micro-batch —
        that keeps each request's padded shapes (hence its numerics)
        independent of its co-batched peers."""
        sig = []
        for name in sorted(self.seq_lens):
            if name in feed:
                arr = np.asarray(feed[name])
                if arr.ndim < 2:
                    raise BucketError(
                        f"input {name!r} declares length buckets but "
                        f"the fed array has no dim 1 (shape "
                        f"{arr.shape})")
                sig.append((name, self.seq_bucket(name, arr.shape[1])))
        return tuple(sig)

    def all_signatures(self, names=None):
        """Every (batch_bucket, signature) pair this spec can produce —
        the warmup compile set. ``names`` restricts which declared
        seq inputs apply (default: all of them)."""
        seq_names = sorted(n for n in self.seq_lens
                           if names is None or n in names)
        sigs = [()]
        for name in seq_names:
            sigs = [s + ((name, l),) for s in sigs
                    for l in self.seq_lens[name]]
        return [(b, s) for b in self.batch_sizes for s in sigs]

    # -- padding / unpadding ---------------------------------------------
    def pad_seq(self, name, arr):
        """Pad ``arr``'s dim 1 up to its length bucket with the input's
        pad value (default 0). No-op for non-bucketed inputs."""
        arr = np.asarray(arr)
        if name not in self.seq_lens:
            return arr
        target = self.seq_bucket(name, arr.shape[1])
        if arr.shape[1] == target:
            return arr
        pad = np.full(
            (arr.shape[0], target - arr.shape[1]) + arr.shape[2:],
            self.pad_values.get(name, 0), dtype=arr.dtype)
        return np.concatenate([arr, pad], axis=1)

    def pad_batch(self, feeds):
        """Coalesce per-request feeds (same signature, each value an
        array with a leading rows dim) into ONE bucket-shaped feed.

        Returns ``(batch_feed, n_real_rows, bucket_rows)``. Pad rows
        replicate row 0 of the assembled batch — real data, so
        numerics of real rows cannot be perturbed and the pad rows
        cannot produce NaN side effects in models that reduce over the
        batch. Callers slice results back with :meth:`unpad_rows`.
        """
        if not feeds:
            raise ValueError("pad_batch needs at least one request feed")
        names = sorted(feeds[0])
        for f in feeds[1:]:
            if sorted(f) != names:
                raise ValueError(
                    f"coalesced requests disagree on feed names: "
                    f"{names} vs {sorted(f)}")
        batch_feed = {}
        n_rows = None
        for name in names:
            parts = [self.pad_seq(name, f[name]) for f in feeds]
            stacked = np.concatenate(parts, axis=0)
            if n_rows is None:
                n_rows = stacked.shape[0]
            elif stacked.shape[0] != n_rows:
                raise ValueError(
                    f"input {name!r} has {stacked.shape[0]} rows but "
                    f"other inputs have {n_rows}")
            batch_feed[name] = stacked
        bucket_rows = self.batch_bucket(n_rows)
        if bucket_rows > n_rows:
            for name in names:
                arr = batch_feed[name]
                fill = np.broadcast_to(
                    arr[:1], (bucket_rows - n_rows,) + arr.shape[1:])
                batch_feed[name] = np.concatenate([arr, fill], axis=0)
        return batch_feed, n_rows, bucket_rows

    @staticmethod
    def unpad_rows(fetches, row_counts):
        """Split batched fetch arrays back into per-request slices.
        ``row_counts`` is the real row count per coalesced request, in
        batch order; trailing pad rows are dropped. Fetches without a
        batch dim that covers the rows (e.g. scalar metrics) are
        replicated to every request as-is."""
        total = sum(row_counts)
        out = [[] for _ in row_counts]
        for arr in fetches:
            arr = np.asarray(arr)
            if arr.ndim >= 1 and arr.shape[0] >= total:
                ofs = 0
                for i, n in enumerate(row_counts):
                    out[i].append(arr[ofs:ofs + n])
                    ofs += n
            else:
                for slot in out:
                    slot.append(arr)
        return out
