"""SLO-aware admission scheduling for the decode engine.

FIFO admission is the wrong policy under mixed prompt lengths: a long
prompt at the head of the queue prefills for many engine iterations
(even chunked), while short interactive requests behind it blow their
time-to-first-token budgets waiting — and every admitted prefill slice
steals a step from the running streams' time-per-output-token. This
module makes the trade explicit: each request carries an
:class:`SLOClass` (TTFT + TPOT targets), queued prefills are ordered
earliest-deadline-first over their TTFT deadlines, and a TPOT budget
guard skips prefill admission on iterations where a running stream is
about to blow its per-token budget (decode runs first, prefill waits
one block) — unless a queued request's own TTFT deadline is at
imminent risk, in which case admission wins (a violated TPOT step
costs one token's latency; a violated TTFT costs the user-visible
first paint).

The scheduler is deliberately engine-agnostic and clock-injectable:
``order`` and ``admit_now`` see plain objects with a few attributes
(``enqueued_at``, ``slo`` on queued requests; ``req``,
``first_token_at``, ``emitted`` on running slots), so the policy unit
tests drive it on fake clocks with synthetic requests — no engine, no
threads, no XLA (tests/test_slo_sched.py).

Deadline semantics reuse the PR 3 vocabulary: an SLO target is NOT a
hard deadline (the request still completes; the breaker/deadline
machinery is untouched) — it is the threshold the attainment counters
(``slo_ttft_met/violated``, ``slo_tpot_met/violated``) and servebench's
SLO-attainment gate are scored against.
"""
import time

__all__ = ["PRIORITIES", "SLOClass", "FIFOScheduler", "SLOScheduler",
           "get_scheduler", "priority_rank"]

# Priority tiers, best (shed last, served first among deadline ties)
# to worst. The rank is the sort key everywhere — shedding, queue
# eviction, scheduler tie-breaks — so the ordering contract is a
# single table, not N comparisons.
PRIORITIES = {"interactive": 0, "standard": 1, "batch": 2}


def priority_rank(obj):
    """The priority rank of a request / SLOClass / priority name:
    0 = interactive (shed last), 1 = standard, 2 = batch (shed
    first). Anything without an explicit priority is ``standard`` —
    pre-priority traffic keeps its old position in every ordering."""
    if isinstance(obj, str):
        try:
            return PRIORITIES[obj]
        except KeyError:
            raise ValueError(
                f"unknown priority {obj!r}; one of "
                f"{sorted(PRIORITIES)}") from None
    pri = getattr(obj, "priority", None)
    if pri is None:
        slo = getattr(obj, "slo", None)
        pri = getattr(slo, "priority", None)
    return PRIORITIES.get(pri, PRIORITIES["standard"])


class SLOClass:
    """One request class's service-level objectives.

    ``ttft_target_s``: seconds from submit to first token;
    ``tpot_target_s``: seconds per generated token after the first.
    Either may be None (that half is not scored). ``name`` keys the
    per-class latency windows in ServingMetrics (``<name>.ttft_s`` /
    ``<name>.tpot_s``). ``priority`` is the overload tier
    (``interactive`` > ``standard`` > ``batch``): under pressure,
    batch sheds first and interactive last. It crosses the wire with
    the rest of the SLO — transports serialize an SLOClass as a plain
    dict and rebuild with ``SLOClass(**d)``, so every field here must
    round-trip through ``to_dict()``."""

    __slots__ = ("name", "ttft_target_s", "tpot_target_s", "priority")

    def __init__(self, ttft_target_s=None, tpot_target_s=None,
                 name="default", priority="standard"):
        if ttft_target_s is not None and float(ttft_target_s) <= 0:
            raise ValueError("ttft_target_s must be positive or None")
        if tpot_target_s is not None and float(tpot_target_s) <= 0:
            raise ValueError("tpot_target_s must be positive or None")
        if priority not in PRIORITIES:
            raise ValueError(
                f"unknown priority {priority!r}; one of "
                f"{sorted(PRIORITIES)}")
        self.name = str(name)
        self.ttft_target_s = (None if ttft_target_s is None
                              else float(ttft_target_s))
        self.tpot_target_s = (None if tpot_target_s is None
                              else float(tpot_target_s))
        self.priority = priority

    def to_dict(self):
        """The wire form: a plain dict that ``SLOClass(**d)`` rebuilds
        bit-identically on the far side of a pipe or socket."""
        return {"ttft_target_s": self.ttft_target_s,
                "tpot_target_s": self.tpot_target_s,
                "name": self.name, "priority": self.priority}

    def __repr__(self):
        return (f"SLOClass({self.name!r}, "
                f"ttft={self.ttft_target_s}, tpot={self.tpot_target_s}, "
                f"priority={self.priority!r})")


def _ttft_deadline(req):
    """The absolute monotonic time by which this queued request wants
    its first token. Requests without an SLO (or without a TTFT half)
    sort LAST among equals — explicit targets always outrank
    best-effort traffic — and FIFO among themselves."""
    slo = getattr(req, "slo", None)
    if slo is not None and slo.ttft_target_s is not None:
        return req.enqueued_at + slo.ttft_target_s
    return float("inf")


class FIFOScheduler:
    """Arrival-order admission, always willing to prefill — exactly
    the pre-SLO engine behavior, kept as a first-class policy so
    servebench can A/B it against the SLO scheduler on one code
    path."""

    name = "fifo"

    def order(self, queue, now):
        return list(queue)

    def admit_now(self, queue, running, now):
        return True


class SLOScheduler:
    """EDF-over-TTFT admission ordering plus a TPOT budget guard.

    ``order``: queued requests sorted by TTFT deadline (earliest
    first), arrival order among ties — classic earliest-deadline-first,
    which is optimal for meeting deadlines on a single resource when
    the load is feasible.

    ``admit_now``: False (run the decode batch first, admit next
    iteration) when some running stream's TPOT budget is already spent
    — i.e. admitting a prefill slice now would push its next token past
    ``tpot_target_s * tokens`` of elapsed generation time — UNLESS the
    most urgent queued request's TTFT slack has dropped below
    ``urgency_s`` (then TTFT outranks TPOT, see module docstring).

    ``urgency_s`` defaults to one decode block's worth of leeway; pass
    the engine's measured block time for tighter control. ``clock`` is
    injectable for the fake-clock policy units."""

    name = "slo"

    def __init__(self, urgency_s=0.05, clock=None):
        self.urgency_s = float(urgency_s)
        self.clock = clock or time.monotonic

    def order(self, queue, now):
        # EDF first; priority breaks deadline ties (which includes
        # ALL best-effort traffic — no TTFT target sorts at +inf, so
        # among it interactive runs before standard before batch);
        # arrival order last.
        return sorted(queue, key=lambda r: (_ttft_deadline(r),
                                            priority_rank(r),
                                            r.enqueued_at))

    def _tpot_exhausted(self, slot, now):
        req = getattr(slot, "req", slot)
        slo = getattr(req, "slo", None)
        if slo is None or slo.tpot_target_s is None:
            return False
        first = getattr(slot, "first_token_at", None)
        if first is None:
            return False
        # budget through the NEXT token: n generated so far, token
        # n+1 due within n * tpot_target of the first token
        n = max(1, len(getattr(slot, "emitted", ()) or ()))
        return (now - first) >= slo.tpot_target_s * n

    def admit_now(self, queue, running, now):
        if not queue:
            return False
        urgent = min((_ttft_deadline(r) for r in queue),
                     default=float("inf"))
        if urgent - now <= self.urgency_s:
            return True
        return not any(self._tpot_exhausted(s, now) for s in running
                       if s is not None)


def get_scheduler(spec):
    """Resolve a scheduler from a config knob: None/'fifo' →
    FIFOScheduler, 'slo' → SLOScheduler, or an instance (anything with
    ``order`` + ``admit_now``) passed through."""
    if spec is None or spec == "fifo":
        return FIFOScheduler()
    if spec == "slo":
        return SLOScheduler()
    if hasattr(spec, "order") and hasattr(spec, "admit_now"):
        return spec
    raise ValueError(
        f"unknown scheduler {spec!r}; use 'fifo', 'slo', or an object "
        "with order()/admit_now()")
