"""ServingEngine — the model-server core.

Concurrent callers submit feeds; a single worker thread coalesces them
into micro-batches (batching.py), pads each batch to a pre-declared
shape bucket (buckets.py) so every dispatch hits an already-compiled
XLA executable, runs the batch through the ordinary
:class:`~paddle_tpu.core.executor.Executor`, and splits the fetch rows
back to callers. Around that core:

- **warmup** — pre-compiles every bucket the spec can produce and
  records the executor's compile counts; ``assert_no_recompiles``
  then turns "no recompiles during steady-state traffic" into a hard
  check (Executor.compile_counts exposes jax.jit's shape-cache sizes).
- **admission control** — a bounded queue that sheds at capacity
  (QueueFullError) and per-request deadlines that convert queue decay
  into structured RequestTimeoutError instead of unbounded latency.
- **resilience** — the worker wraps each dispatch in
  resilience.retry.with_retries; the engine's executor itself runs
  with retries disabled so every transient-device retry is owned (and
  counted — ``retries_total``) at the serving layer.
- **hardening** (health.py, docs/SERVING.md "Operating under
  failure") — a HealthMonitor state machine (STARTING → READY →
  DEGRADED → DRAINING → STOPPED) fed by a worker heartbeat; a
  watchdog thread that detects a dead/stuck worker and fails pending
  requests with WorkerDiedError; engine- and per-bucket circuit
  breakers that shed with ServiceUnavailableError after repeated
  batch failures and half-open probe on a cooldown; ``close(
  drain=True)`` graceful drain; and per-batch deadline propagation so
  dispatch retries never outlive the tightest caller timeout.
- **metrics** — a ServingMetrics registry behind ``stats()``.

The engine serves ONE program; put one engine per model (they share
nothing mutable). Single worker by design: the device executes one
program at a time anyway, and one consumer keeps batch assembly
trivially racefree — parallelism belongs to the batch dimension.
"""
import json
import os
import threading
import time

import numpy as np

from ..core.executor import CPUPlace, Executor, Scope, global_scope
from ..resilience import faultinject as _faultinject
from ..resilience.retry import (RetryPolicy, TransientDeviceError,
                                default_policy, with_retries)
from .batching import (MicroBatcher, PendingResult, QueueFullError,
                       RequestTimeoutError, ServerClosedError)
from .buckets import BucketError, BucketSpec
from .health import (CircuitBreaker, HealthMonitor, HealthState,
                     ServiceUnavailableError, WorkerDiedError)
from .metrics import ServingMetrics

__all__ = ["ServingConfig", "ServingEngine"]


def _env_float(name, default):
    return float(os.environ.get(name, default))


class _ReplicaCrashed(BaseException):
    """Internal: tears the worker thread down ungracefully when the
    cluster chaos hook (``_simulate_worker_crash``) fires while the
    worker idles inside the batcher poll. BaseException so no recovery
    path can swallow the simulated SIGKILL."""


class ServingConfig:
    """Tuning knobs for one engine (docs/SERVING.md walks the
    tradeoffs).

    ``max_wait_ms`` — how long the oldest queued request may wait for
    batch peers; the latency you trade for fill ratio.
    ``max_queue`` — admission bound; arrivals beyond it shed.
    ``default_timeout_s`` — per-request deadline when the caller gives
    none (None = requests never expire).
    ``retry_policy`` — transient-device-error policy for the worker
    dispatch (None = resilience.default_policy(), env-tunable).

    Hardening knobs (each defaults from an env var so operators tune a
    deployment without code changes; docs/SERVING.md "Operating under
    failure"):

    ``breaker_threshold`` (PADDLE_TPU_BREAKER_THRESHOLD, 5) —
    consecutive terminal batch failures that open a circuit breaker.
    ``breaker_cooldown_s`` (PADDLE_TPU_BREAKER_COOLDOWN, 1.0) — open
    time before a half-open probe batch is let through.
    ``drain_timeout_s`` (PADDLE_TPU_DRAIN_TIMEOUT, 10.0) — default
    budget for ``close(drain=True)`` to finish queued work.
    ``watchdog_interval_s`` (PADDLE_TPU_WATCHDOG_INTERVAL, 0.1) — how
    often the watchdog checks worker liveness.
    ``hang_timeout_s`` (PADDLE_TPU_HANG_TIMEOUT, 30.0) — heartbeat
    staleness that declares a live-but-stuck worker dead; 0 disables
    hang detection (thread-death detection stays on).
    """

    def __init__(self, max_wait_ms=2.0, max_queue=64,
                 default_timeout_s=30.0, retry_policy=None,
                 breaker_threshold=None, breaker_cooldown_s=None,
                 drain_timeout_s=None, watchdog_interval_s=None,
                 hang_timeout_s=None):
        self.max_wait_ms = float(max_wait_ms)
        self.max_queue = int(max_queue)
        self.default_timeout_s = default_timeout_s
        self.retry_policy = retry_policy
        self.breaker_threshold = int(
            _env_float("PADDLE_TPU_BREAKER_THRESHOLD", 5)
            if breaker_threshold is None else breaker_threshold)
        self.breaker_cooldown_s = (
            _env_float("PADDLE_TPU_BREAKER_COOLDOWN", 1.0)
            if breaker_cooldown_s is None else float(breaker_cooldown_s))
        self.drain_timeout_s = (
            _env_float("PADDLE_TPU_DRAIN_TIMEOUT", 10.0)
            if drain_timeout_s is None else float(drain_timeout_s))
        self.watchdog_interval_s = (
            _env_float("PADDLE_TPU_WATCHDOG_INTERVAL", 0.1)
            if watchdog_interval_s is None else float(watchdog_interval_s))
        self.hang_timeout_s = (
            _env_float("PADDLE_TPU_HANG_TIMEOUT", 30.0)
            if hang_timeout_s is None else float(hang_timeout_s))


class ServingEngine:
    """Serve ``program``'s ``fetch_list`` from batched feeds.

    ``program`` must be inference-form (clone(for_test=True) or a
    load_inference_model result); ``feed_names`` fixes the request
    contract — every request must feed exactly these, each array with
    a leading rows dim. ``scope`` holds the parameters (defaults to
    the ambient global scope at construction). ``buckets`` defaults to
    batch buckets ``(1, 2, 4, 8)`` with no sequence bucketing.
    """

    def __init__(self, program, feed_names, fetch_list, scope=None,
                 place=None, buckets=None, config=None, auto_start=True,
                 optimize=True, compile_store=None, model_version=None):
        self.feed_names = list(feed_names)
        self.fetch_list = list(fetch_list)
        # deployment identity from the export's __meta__.json (None
        # for engines built straight from a Program) — surfaced in
        # stats() / the membership view so operators can see which
        # version each replica is actually serving
        self.model_version = model_version
        # graph rewrites on the serving hot path (analysis/optimize.py:
        # fold + fuse + cse + dce, proven bit-exact by optcheck): the
        # engine compiles an optimized CLONE — the caller's program is
        # never mutated, and the clone's own (uid, version) keys the
        # executor compile cache, so warmup()/assert_no_recompiles()
        # pin the optimized executables exactly as before. A rewrite
        # failure degrades to serving the original program.
        self.optimize_report = None
        if optimize:
            try:
                fetch_names = [v.name if hasattr(v, "name") else v
                               for v in self.fetch_list]
                clone = program.clone(for_test=program._is_test)
                self.optimize_report = clone.optimize(
                    fetch_list=fetch_names)
                program = clone
            except Exception as e:   # pragma: no cover - safety net
                import warnings
                warnings.warn(
                    f"serving optimize rewrite failed ({e!r}); "
                    "serving the program unoptimized", stacklevel=2)
        self.program = program
        self.scope = scope or global_scope()
        self.buckets = buckets or BucketSpec()
        self.config = config or ServingConfig()
        # all retries surface here (counted in metrics); the inner
        # executor must not also retry or attempts would multiply.
        # donate_state=False: replicas of a cluster pool share one
        # read-only parameter scope — a donated (hence deleted) state
        # buffer in one replica would be a dangling buffer in the rest
        # compile_store: persistent compiled-artifact store
        # (io/artifact_store.py) — warmup() then LOADS this engine's
        # bucket executables instead of compiling them when a peer
        # process (or an export-time seeding pass) already persisted
        # them: the zero-compile cold start. None defers to
        # PADDLE_TPU_ARTIFACT_DIR; False disables outright.
        self.exe = Executor(place or CPUPlace(),
                            retry_policy=RetryPolicy(max_attempts=1),
                            donate_state=False,
                            compile_store=compile_store)
        self.metrics = ServingMetrics()
        self.batcher = MicroBatcher(
            max_batch_size=self.buckets.max_batch,
            max_wait_s=self.config.max_wait_ms / 1e3,
            max_queue=self.config.max_queue)
        self.health = HealthMonitor()
        self.breaker = CircuitBreaker(
            failure_threshold=self.config.breaker_threshold,
            cooldown_s=self.config.breaker_cooldown_s)
        self._sig_breakers = {}   # bucket signature -> CircuitBreaker
        self._inflight = []       # batch currently in dispatch
        self._warmed = None       # compile snapshot after warmup()
        self._worker = None
        self._watchdog = None
        self._worker_death_seen = False
        self._stop = threading.Event()
        self._watchdog_stop = threading.Event()
        # chaos hook: lets the cluster layer kill THIS engine's worker
        # ungracefully (the global serving_worker_crash fault point
        # cannot target one replica of a pool)
        self._crash = threading.Event()
        if auto_start:
            self.start()

    # -- construction from artifacts -------------------------------------
    @classmethod
    def from_saved_model(cls, dirname, place=None, **kw):
        """Serve a ``save_inference_model`` directory: loads the pruned
        program + params into a PRIVATE scope (two engines from the
        same dir never share state). When the artifact carries a
        serving manifest (``save_inference_model(...,
        serving_buckets=...)``) and the caller passes no ``buckets``,
        the exported BucketSpec is used — ``warmup()`` then
        pre-compiles exactly the bucket signatures the exporter saw,
        instead of guessing (the replica scale-out path).

        When the artifact carries an embedded compiled-artifact store
        (``save_inference_model(..., artifact_store=True)`` writes
        ``__artifacts__/`` beside the params) and the caller passes no
        ``compile_store``, that store is used — warmup() then performs
        ZERO XLA compiles: the saved-model dir alone carries
        everything a fresh replica host needs."""
        from .. import io as fluid_io
        from ..io.artifact_store import EMBEDDED_DIRNAME
        scope = Scope()
        exe = Executor(place or CPUPlace())
        # the target scope is passed explicitly — a guard swap of the
        # process-global scope here would race the worker threads of
        # every other live engine (a canary rebuild under traffic
        # could load its params into a neighbor's scope)
        program, feed_names, fetch_vars = \
            fluid_io.load_inference_model(dirname, exe, scope=scope)
        if kw.get("buckets") is None:
            manifest = fluid_io.load_serving_manifest(dirname)
            if manifest.get("buckets"):
                kw["buckets"] = BucketSpec.from_manifest(
                    manifest["buckets"])
        if kw.get("compile_store") is None:
            embedded = os.path.join(dirname, EMBEDDED_DIRNAME)
            if os.path.isdir(embedded):
                kw["compile_store"] = embedded
        if kw.get("model_version") is None:
            try:
                with open(os.path.join(dirname, "__meta__.json")) as f:
                    kw["model_version"] = json.load(f).get(
                        "model_version")
            except (OSError, ValueError):
                pass
        return cls(program, feed_names, fetch_vars, scope=scope,
                   place=place, **kw)

    # -- lifecycle -------------------------------------------------------
    def start(self):
        """Start (or restart, e.g. after the watchdog declared the
        previous worker dead) the worker + watchdog threads."""
        if self._worker is not None and self._worker.is_alive():
            return self
        self._stop.clear()
        self._crash.clear()
        self._worker_death_seen = False
        self.health.beat()        # fresh heartbeat epoch for the watchdog
        self._worker = threading.Thread(
            target=self._worker_loop, name="paddle-tpu-serving-worker",
            daemon=True)
        self._worker.start()
        if self._watchdog is None or not self._watchdog.is_alive():
            self._watchdog_stop.clear()
            self._watchdog = threading.Thread(
                target=self._watchdog_loop,
                name="paddle-tpu-serving-watchdog", daemon=True)
            self._watchdog.start()
        self.health.to(HealthState.READY)
        return self

    def close(self, timeout=5.0, drain=False, drain_timeout=None):
        """Shut the engine down.

        ``drain=False`` (default, the pre-hardening behavior): stop
        admitting, fulfill everything still queued with
        ServerClosedError, join the worker.

        ``drain=True``: stop admitting, then let the worker FINISH all
        queued and in-flight requests before joining — no admitted
        request is refused. ``drain_timeout`` (default
        ``config.drain_timeout_s``) bounds the drain; whatever is
        still queued when it expires gets ServerClosedError, so a
        wedged device cannot turn shutdown into a hang. Per-request
        deadlines stay live during the drain (an expired request is
        still swept as RequestTimeoutError, never served stale)."""
        worker = self._worker
        if drain and worker is not None and worker.is_alive() \
                and not self._stop.is_set():
            self.health.to(HealthState.DRAINING)
            self.batcher.close()     # stop admission; keep serving
            budget = (self.config.drain_timeout_s
                      if drain_timeout is None else float(drain_timeout))
            # the worker exits by itself once closed AND empty
            worker.join(max(budget, 0.0))
        self.batcher.close()
        self._stop.set()
        for req in self.batcher.drain():
            req.set_error(ServerClosedError("engine closed"))
        if self._worker is not None:
            self._worker.join(timeout)
        self._watchdog_stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout)
            self._watchdog = None
        self.health.to(HealthState.STOPPED)
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- warmup ----------------------------------------------------------
    def warmup(self):
        """Pre-compile every declared bucket: one dummy run per
        (batch bucket × length-bucket signature). Returns
        ``{"signatures": n, "compiles": total_xla_executables}`` and
        snapshots the compile counts that
        :meth:`assert_no_recompiles` later compares against. Load-time
        cost, bought back as a steady state that never compiles."""
        sigs = self.buckets.all_signatures(names=set(self.feed_names))
        for batch_rows, sig in sigs:
            feed = self._dummy_feed(batch_rows, dict(sig))
            # scope passed explicitly (NOT via the process-global
            # scope_guard): engine runs happen on worker threads
            # concurrent with other engines' loads/rebuilds, and the
            # global guard is not thread-safe
            self.exe.run(self.program, feed=feed,
                         fetch_list=self.fetch_list, mode="test",
                         scope=self.scope)
        self._warmed = self.exe.compile_counts()
        compiles = self.exe.total_compiles()
        self.metrics.incr("warmup_compiles", compiles)
        return {"signatures": len(sigs), "compiles": compiles}

    def assert_no_recompiles(self):
        """Raise AssertionError if any XLA compile happened after
        warmup() — the steady-state contract. No-op before warmup."""
        if self._warmed is None:
            return
        now = self.exe.compile_counts()
        if now != self._warmed:
            raise AssertionError(
                f"serving executables changed after warmup: "
                f"{self._warmed} -> {now} — a request shape escaped "
                "the declared buckets")

    def _dummy_feed(self, batch_rows, seq_by_name):
        """Zero-valued feed shaped for one bucket signature, derived
        from the program's data-var declarations."""
        gb = self.program.global_block()
        feed = {}
        for name in self.feed_names:
            var = gb.var(name)
            shape = list(var.shape)
            shape[0] = batch_rows
            if name in seq_by_name and len(shape) > 1:
                shape[1] = seq_by_name[name]
            shape = [1 if (d is None or d < 0) else int(d)
                     for d in shape]
            shape[0] = batch_rows
            feed[name] = np.zeros(shape, dtype=str(var.dtype))
        return feed

    # -- request path ----------------------------------------------------
    def submit(self, feed, timeout=None):
        """Enqueue one request; returns a PendingResult immediately.

        ``feed`` maps every declared feed name to an array whose
        leading dim is this request's row count (1 for a single
        sample). Raises BucketError (shape outside every declared
        bucket), QueueFullError (shed), ServiceUnavailableError (the
        engine-level or this bucket's circuit breaker is open),
        ServerClosedError — all before any queueing, so a rejected
        request costs nothing."""
        missing = [n for n in self.feed_names if n not in feed]
        extra = [n for n in feed if n not in self.feed_names]
        if missing or extra:
            raise ValueError(
                f"request feed must supply exactly {self.feed_names}; "
                f"missing {missing}, unexpected {extra}")
        arrs = {n: np.asarray(feed[n]) for n in self.feed_names}
        rows = {n: a.shape[0] if a.ndim else 0 for n, a in arrs.items()}
        n_rows = rows[self.feed_names[0]]
        if n_rows < 1 or len(set(rows.values())) != 1:
            raise ValueError(
                f"request arrays must agree on a leading rows dim >= 1, "
                f"got {rows}")
        try:
            signature = self.buckets.signature(arrs)
            self.buckets.batch_bucket(n_rows)    # fits some bucket?
        except BucketError:
            self.metrics.incr("shed_total")
            raise
        # breaker fast-shed: read-only (state transitions belong to the
        # worker) — a cooled-down open breaker admits, and those
        # requests become the half-open probe batch
        sig_breaker = self._sig_breakers.get(signature)
        if not self.breaker.admits() or (
                sig_breaker is not None and not sig_breaker.admits()):
            self.metrics.incr("breaker_shed_total")
            raise ServiceUnavailableError(
                "circuit breaker open — the engine (or this request's "
                "bucket) is failing; back off at least "
                f"{self.config.breaker_cooldown_s}s and retry")
        if timeout is None:
            timeout = self.config.default_timeout_s
        now = time.monotonic()
        req = PendingResult(
            feed=arrs, n_rows=n_rows, signature=signature,
            deadline=None if timeout is None else now + float(timeout),
            enqueued_at=now)
        try:
            self.batcher.put(req)
        except QueueFullError:
            self.metrics.incr("shed_total")
            raise
        # admitted only: shed/oversize rejections count in shed_total
        self.metrics.incr("requests_total")
        self.metrics.set_queue_depth(self.batcher.depth())
        return req

    def infer(self, feed, timeout=None):
        """Synchronous convenience: submit + wait. Returns the fetch
        list for THIS request's rows (numpy arrays).

        The wait is liveness-aware: it polls the worker thread while
        waiting and raises WorkerDiedError promptly if the worker is
        gone, instead of sitting out the full grace bound (the
        watchdog fails queued requests too, but this direct check
        holds even with a long watchdog interval)."""
        req = self.submit(feed, timeout=timeout)
        # caller-side wait is the serving deadline plus grace — the
        # structured RequestTimeoutError from the worker is the real
        # signal; the grace bound only guards a silently-lost request
        end = None if req.deadline is None else req.deadline + 10.0
        while True:
            if req.wait(0.05):
                return req.result(0)
            worker = self._worker
            if worker is None or not worker.is_alive():
                # the worker may have settled it on its way out (drain
                # tail, close()) — give settlement a beat to land
                if req.wait(0.2):
                    return req.result(0)
                raise WorkerDiedError(
                    "serving worker died while this request waited "
                    "(restart the engine with start())")
            if end is not None and time.monotonic() >= end:
                return req.result(0)   # structured wait-bound timeout

    def outstanding(self):
        """Admitted-but-unfinished requests right now: queued plus the
        batch in dispatch. The cluster router's least-outstanding /
        health-aware balancing reads this per pick — it must stay a
        couple of O(1) reads, never a stats() snapshot."""
        return self.batcher.depth() + len(self._inflight)

    def worker_alive(self):
        """True iff the worker thread exists and is running (the
        liveness read infer() and the cluster revival monitor share)."""
        w = self._worker
        return w is not None and w.is_alive()

    def _simulate_worker_crash(self):
        """Kill THIS engine's worker ungracefully on its next loop
        iteration (no cleanup — models SIGKILL, like the global
        serving_worker_crash point, but per-engine so cluster chaos
        can take down one replica of a pool). start() revives."""
        self._crash.set()

    def stats(self):
        """Metrics snapshot + compile-cache evidence + health/breaker
        state."""
        snap = self.metrics.stats()
        snap["compiles_now"] = self.exe.total_compiles()
        snap["queue_depth"] = self.batcher.depth()
        snap["health_state"] = self.health.state
        snap["model_version"] = self.model_version
        snap["optimize"] = (self.optimize_report.to_dict()
                            if self.optimize_report is not None
                            else None)
        snap["breaker"] = self.breaker.snapshot()
        snap["artifact_store"] = self.exe.store_stats()
        open_sigs = {str(sig): br.snapshot()
                     for sig, br in self._sig_breakers.items()
                     if br.state != CircuitBreaker.CLOSED}
        snap["bucket_breakers_not_closed"] = open_sigs
        return snap

    # -- watchdog --------------------------------------------------------
    def _watchdog_loop(self):
        """Liveness sentinel: periodically checks that the worker
        thread exists and its heartbeat moves; on death (or a stalled
        heartbeat past hang_timeout_s) fails everything pending with
        WorkerDiedError so no caller ever waits out a grace bound on a
        server that cannot answer."""
        while not self._watchdog_stop.wait(self.config.watchdog_interval_s):
            if self._stop.is_set() or self.batcher.closed:
                continue          # shutdown/drain: worker exit is expected
            worker = self._worker
            if worker is None:
                continue
            if not worker.is_alive():
                self._on_worker_dead("serving worker thread died")
                continue
            age = self.health.heartbeat_age()
            hang = self.config.hang_timeout_s
            if hang and age is not None and age > hang:
                self._on_worker_dead(
                    f"serving worker heartbeat stalled {age:.1f}s "
                    f"(hang timeout {hang:g}s) — worker is stuck")

    def _on_worker_dead(self, reason):
        """Fail pending (queued + in-flight) requests with a typed
        error; flip health to DEGRADED once per death event."""
        if not self._worker_death_seen:
            self._worker_death_seen = True
            self.metrics.incr("worker_died_total")
            self.health.to(HealthState.DEGRADED)
        inflight, self._inflight = self._inflight, []
        pending = list(inflight) + self.batcher.drain()
        for req in pending:
            req.set_error(WorkerDiedError(reason))

    # -- worker ----------------------------------------------------------
    def _beat_or_crash(self):
        """The worker heartbeat, doubling as the per-engine crash
        point: called once per queue-poll iteration, so a simulated
        crash kills even an IDLE worker promptly (the plain loop-top
        check only runs between batches)."""
        if self._crash.is_set():
            raise _ReplicaCrashed()
        self.health.beat()

    def _worker_loop(self):
        try:
            self._worker_loop_impl()
        except _ReplicaCrashed:
            return   # models SIGKILL: no cleanup — the watchdog's job

    def _worker_loop_impl(self):
        policy = self.config.retry_policy or default_policy()
        while not (self._stop.is_set() and self.batcher.depth() == 0):
            if self._crash.is_set() \
                    or _faultinject.fires("serving_worker_crash"):
                return   # models SIGKILL: no cleanup — the watchdog's job
            self.health.beat()
            batch, expired = self.batcher.next_batch(
                on_poll=self._beat_or_crash)
            for req in expired:
                self.metrics.incr("timeouts_total")
                req.set_error(RequestTimeoutError(
                    "request deadline expired before it was served "
                    f"(waited >= {self.config.max_wait_ms} ms window; "
                    "queue saturated or timeout too tight)"))
            if not batch:
                if self.batcher.closed and self.batcher.depth() == 0:
                    break
                continue
            self.metrics.set_queue_depth(self.batcher.depth())
            self._serve_batch(batch, policy)
        # engine closing: anything left gets a structured refusal
        for req in self.batcher.drain():
            req.set_error(ServerClosedError("engine closed"))

    def _sig_breaker(self, signature):
        br = self._sig_breakers.get(signature)
        if br is None:
            br = CircuitBreaker(
                failure_threshold=self.config.breaker_threshold,
                cooldown_s=self.config.breaker_cooldown_s)
            self._sig_breakers[signature] = br
        return br

    def _serve_batch(self, batch, policy):
        sig_breaker = self._sig_breaker(batch[0].signature)
        # dispatch-side breaker gate: an open breaker sheds the batch
        # without compute; a cooled-down one lets it through half-open
        # as the probe whose outcome closes or re-opens the breaker
        if not (self.breaker.allow() and sig_breaker.allow()):
            self.metrics.incr("breaker_shed_total", len(batch))
            for req in batch:
                req.set_error(ServiceUnavailableError(
                    "circuit breaker open — batch shed without dispatch; "
                    f"back off {self.config.breaker_cooldown_s}s"))
            return
        if CircuitBreaker.HALF_OPEN in (self.breaker.state,
                                        sig_breaker.state):
            self.metrics.incr("breaker_probe_total")
        # deadline propagation: the tightest member deadline caps the
        # retry loop, so re-dispatching never outlives any caller
        deadlines = [r.deadline for r in batch if r.deadline is not None]
        batch_deadline = min(deadlines) if deadlines else None
        t0 = time.monotonic()
        self._inflight = batch
        try:
            feeds = [r.feed for r in batch]
            batch_feed, n_rows, bucket_rows = \
                self.buckets.pad_batch(feeds)

            def _dispatch():
                if _faultinject.fires("serving_slow_batch"):
                    # models a wedged/slow device dispatch (tunable so
                    # drain-under-fire tests stay fast)
                    time.sleep(_env_float("PADDLE_TPU_FAULT_SLOW_S",
                                          0.25))
                if _faultinject.fires("serving_device_error"):
                    raise TransientDeviceError(
                        "injected serving-layer transient device error "
                        "(UNAVAILABLE)")
                return self.exe.run(
                    self.program, feed=batch_feed,
                    fetch_list=self.fetch_list, mode="test",
                    scope=self.scope)

            fetches = with_retries(
                _dispatch, policy=policy, deadline=batch_deadline,
                on_retry=lambda exc, n, delay:
                    self.metrics.incr("retries_total"))
            per_req = BucketSpec.unpad_rows(
                fetches, [r.n_rows for r in batch])
        except BaseException as exc:     # noqa: BLE001 — forwarded
            # a failed batch fails its requests, never the worker;
            # breakers count the terminal (post-retry) failure FIRST so
            # a caller seeing the error and immediately resubmitting
            # meets an already-open breaker
            self._inflight = []
            opened = self.breaker.record_failure()
            opened_sig = sig_breaker.record_failure()
            if opened:
                self.metrics.incr("breaker_open_total")
            if opened_sig:
                self.metrics.incr("breaker_open_total")
            if opened or opened_sig:
                self.health.to(HealthState.DEGRADED)
            self.metrics.incr("errors_total", len(batch))
            for req in batch:
                req.set_error(exc)
            return
        self._inflight = []
        self.breaker.record_success()
        sig_breaker.record_success()
        if self.health.state == HealthState.DEGRADED:
            self.health.to(HealthState.READY)   # breaker recovered
        done = time.monotonic()
        self.metrics.observe_batch(n_rows, bucket_rows, done - t0)
        draining = self.batcher.closed and not self._stop.is_set()
        for req, res in zip(batch, per_req):
            self.metrics.incr("responses_total")
            if draining:
                self.metrics.incr("drained_total")
            self.metrics.observe_latency(done - req.enqueued_at)
            req.set_result(res)
