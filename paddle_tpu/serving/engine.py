"""ServingEngine — the model-server core.

Concurrent callers submit feeds; a single worker thread coalesces them
into micro-batches (batching.py), pads each batch to a pre-declared
shape bucket (buckets.py) so every dispatch hits an already-compiled
XLA executable, runs the batch through the ordinary
:class:`~paddle_tpu.core.executor.Executor`, and splits the fetch rows
back to callers. Around that core:

- **warmup** — pre-compiles every bucket the spec can produce and
  records the executor's compile counts; ``assert_no_recompiles``
  then turns "no recompiles during steady-state traffic" into a hard
  check (Executor.compile_counts exposes jax.jit's shape-cache sizes).
- **admission control** — a bounded queue that sheds at capacity
  (QueueFullError) and per-request deadlines that convert queue decay
  into structured RequestTimeoutError instead of unbounded latency.
- **resilience** — the worker wraps each dispatch in
  resilience.retry.with_retries; the engine's executor itself runs
  with retries disabled so every transient-device retry is owned (and
  counted — ``retries_total``) at the serving layer.
- **metrics** — a ServingMetrics registry behind ``stats()``.

The engine serves ONE program; put one engine per model (they share
nothing mutable). Single worker by design: the device executes one
program at a time anyway, and one consumer keeps batch assembly
trivially racefree — parallelism belongs to the batch dimension.
"""
import threading
import time

import numpy as np

from ..core.executor import CPUPlace, Executor, Scope, global_scope, \
    scope_guard
from ..resilience.retry import RetryPolicy, default_policy, with_retries
from .batching import (MicroBatcher, PendingResult, QueueFullError,
                       RequestTimeoutError, ServerClosedError)
from .buckets import BucketError, BucketSpec
from .metrics import ServingMetrics

__all__ = ["ServingConfig", "ServingEngine"]


class ServingConfig:
    """Tuning knobs for one engine (docs/SERVING.md walks the
    tradeoffs).

    ``max_wait_ms`` — how long the oldest queued request may wait for
    batch peers; the latency you trade for fill ratio.
    ``max_queue`` — admission bound; arrivals beyond it shed.
    ``default_timeout_s`` — per-request deadline when the caller gives
    none (None = requests never expire).
    ``retry_policy`` — transient-device-error policy for the worker
    dispatch (None = resilience.default_policy(), env-tunable).
    """

    def __init__(self, max_wait_ms=2.0, max_queue=64,
                 default_timeout_s=30.0, retry_policy=None):
        self.max_wait_ms = float(max_wait_ms)
        self.max_queue = int(max_queue)
        self.default_timeout_s = default_timeout_s
        self.retry_policy = retry_policy


class ServingEngine:
    """Serve ``program``'s ``fetch_list`` from batched feeds.

    ``program`` must be inference-form (clone(for_test=True) or a
    load_inference_model result); ``feed_names`` fixes the request
    contract — every request must feed exactly these, each array with
    a leading rows dim. ``scope`` holds the parameters (defaults to
    the ambient global scope at construction). ``buckets`` defaults to
    batch buckets ``(1, 2, 4, 8)`` with no sequence bucketing.
    """

    def __init__(self, program, feed_names, fetch_list, scope=None,
                 place=None, buckets=None, config=None, auto_start=True):
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_list = list(fetch_list)
        self.scope = scope or global_scope()
        self.buckets = buckets or BucketSpec()
        self.config = config or ServingConfig()
        # all retries surface here (counted in metrics); the inner
        # executor must not also retry or attempts would multiply
        self.exe = Executor(place or CPUPlace(),
                            retry_policy=RetryPolicy(max_attempts=1))
        self.metrics = ServingMetrics()
        self.batcher = MicroBatcher(
            max_batch_size=self.buckets.max_batch,
            max_wait_s=self.config.max_wait_ms / 1e3,
            max_queue=self.config.max_queue)
        self._warmed = None       # compile snapshot after warmup()
        self._worker = None
        self._stop = threading.Event()
        if auto_start:
            self.start()

    # -- construction from artifacts -------------------------------------
    @classmethod
    def from_saved_model(cls, dirname, place=None, **kw):
        """Serve a ``save_inference_model`` directory: loads the pruned
        program + params into a PRIVATE scope (two engines from the
        same dir never share state)."""
        from .. import io as fluid_io
        scope = Scope()
        exe = Executor(place or CPUPlace())
        with scope_guard(scope):
            program, feed_names, fetch_vars = \
                fluid_io.load_inference_model(dirname, exe)
        return cls(program, feed_names, fetch_vars, scope=scope,
                   place=place, **kw)

    # -- lifecycle -------------------------------------------------------
    def start(self):
        if self._worker is not None and self._worker.is_alive():
            return self
        self._stop.clear()
        self._worker = threading.Thread(
            target=self._worker_loop, name="paddle-tpu-serving-worker",
            daemon=True)
        self._worker.start()
        return self

    def close(self, timeout=5.0):
        """Stop admitting, fulfill queued requests with
        ServerClosedError, join the worker."""
        self.batcher.close()
        self._stop.set()
        for req in self.batcher.drain():
            req.set_error(ServerClosedError("engine closed"))
        if self._worker is not None:
            self._worker.join(timeout)
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- warmup ----------------------------------------------------------
    def warmup(self):
        """Pre-compile every declared bucket: one dummy run per
        (batch bucket × length-bucket signature). Returns
        ``{"signatures": n, "compiles": total_xla_executables}`` and
        snapshots the compile counts that
        :meth:`assert_no_recompiles` later compares against. Load-time
        cost, bought back as a steady state that never compiles."""
        sigs = self.buckets.all_signatures(names=set(self.feed_names))
        for batch_rows, sig in sigs:
            feed = self._dummy_feed(batch_rows, dict(sig))
            with scope_guard(self.scope):
                self.exe.run(self.program, feed=feed,
                             fetch_list=self.fetch_list, mode="test")
        self._warmed = self.exe.compile_counts()
        compiles = self.exe.total_compiles()
        self.metrics.incr("warmup_compiles", compiles)
        return {"signatures": len(sigs), "compiles": compiles}

    def assert_no_recompiles(self):
        """Raise AssertionError if any XLA compile happened after
        warmup() — the steady-state contract. No-op before warmup."""
        if self._warmed is None:
            return
        now = self.exe.compile_counts()
        if now != self._warmed:
            raise AssertionError(
                f"serving executables changed after warmup: "
                f"{self._warmed} -> {now} — a request shape escaped "
                "the declared buckets")

    def _dummy_feed(self, batch_rows, seq_by_name):
        """Zero-valued feed shaped for one bucket signature, derived
        from the program's data-var declarations."""
        gb = self.program.global_block()
        feed = {}
        for name in self.feed_names:
            var = gb.var(name)
            shape = list(var.shape)
            shape[0] = batch_rows
            if name in seq_by_name and len(shape) > 1:
                shape[1] = seq_by_name[name]
            shape = [1 if (d is None or d < 0) else int(d)
                     for d in shape]
            shape[0] = batch_rows
            feed[name] = np.zeros(shape, dtype=str(var.dtype))
        return feed

    # -- request path ----------------------------------------------------
    def submit(self, feed, timeout=None):
        """Enqueue one request; returns a PendingResult immediately.

        ``feed`` maps every declared feed name to an array whose
        leading dim is this request's row count (1 for a single
        sample). Raises BucketError (shape outside every declared
        bucket), QueueFullError (shed), ServerClosedError — all before
        any queueing, so a rejected request costs nothing."""
        missing = [n for n in self.feed_names if n not in feed]
        extra = [n for n in feed if n not in self.feed_names]
        if missing or extra:
            raise ValueError(
                f"request feed must supply exactly {self.feed_names}; "
                f"missing {missing}, unexpected {extra}")
        arrs = {n: np.asarray(feed[n]) for n in self.feed_names}
        rows = {n: a.shape[0] if a.ndim else 0 for n, a in arrs.items()}
        n_rows = rows[self.feed_names[0]]
        if n_rows < 1 or len(set(rows.values())) != 1:
            raise ValueError(
                f"request arrays must agree on a leading rows dim >= 1, "
                f"got {rows}")
        try:
            signature = self.buckets.signature(arrs)
            self.buckets.batch_bucket(n_rows)    # fits some bucket?
        except BucketError:
            self.metrics.incr("shed_total")
            raise
        if timeout is None:
            timeout = self.config.default_timeout_s
        now = time.monotonic()
        req = PendingResult(
            feed=arrs, n_rows=n_rows, signature=signature,
            deadline=None if timeout is None else now + float(timeout),
            enqueued_at=now)
        try:
            self.batcher.put(req)
        except QueueFullError:
            self.metrics.incr("shed_total")
            raise
        # admitted only: shed/oversize rejections count in shed_total
        self.metrics.incr("requests_total")
        self.metrics.set_queue_depth(self.batcher.depth())
        return req

    def infer(self, feed, timeout=None):
        """Synchronous convenience: submit + wait. Returns the fetch
        list for THIS request's rows (numpy arrays)."""
        req = self.submit(feed, timeout=timeout)
        # caller-side wait is the serving deadline plus grace — the
        # structured RequestTimeoutError from the worker is the real
        # signal; the grace bound only guards a dead worker
        grace = None if req.deadline is None else \
            max(req.deadline - time.monotonic(), 0.0) + 10.0
        return req.result(timeout=grace)

    def stats(self):
        """Metrics snapshot + compile-cache evidence."""
        snap = self.metrics.stats()
        snap["compiles_now"] = self.exe.total_compiles()
        snap["queue_depth"] = self.batcher.depth()
        return snap

    # -- worker ----------------------------------------------------------
    def _worker_loop(self):
        policy = self.config.retry_policy or default_policy()
        while not (self._stop.is_set() and self.batcher.depth() == 0):
            batch, expired = self.batcher.next_batch()
            for req in expired:
                self.metrics.incr("timeouts_total")
                req.set_error(RequestTimeoutError(
                    "request deadline expired before it was served "
                    f"(waited >= {self.config.max_wait_ms} ms window; "
                    "queue saturated or timeout too tight)"))
            if not batch:
                if self.batcher.closed and self.batcher.depth() == 0:
                    break
                continue
            self.metrics.set_queue_depth(self.batcher.depth())
            self._serve_batch(batch, policy)
        # engine closing: anything left gets a structured refusal
        for req in self.batcher.drain():
            req.set_error(ServerClosedError("engine closed"))

    def _serve_batch(self, batch, policy):
        t0 = time.monotonic()
        try:
            feeds = [r.feed for r in batch]
            batch_feed, n_rows, bucket_rows = \
                self.buckets.pad_batch(feeds)

            def _dispatch():
                with scope_guard(self.scope):
                    return self.exe.run(
                        self.program, feed=batch_feed,
                        fetch_list=self.fetch_list, mode="test")

            fetches = with_retries(
                _dispatch, policy=policy,
                on_retry=lambda exc, n, delay:
                    self.metrics.incr("retries_total"))
            per_req = BucketSpec.unpad_rows(
                fetches, [r.n_rows for r in batch])
        except BaseException as exc:     # noqa: BLE001 — forwarded
            # a failed batch fails its requests, never the worker
            self.metrics.incr("errors_total", len(batch))
            for req in batch:
                req.set_error(exc)
            return
        done = time.monotonic()
        self.metrics.observe_batch(n_rows, bucket_rows, done - t0)
        for req, res in zip(batch, per_req):
            self.metrics.incr("responses_total")
            self.metrics.observe_latency(done - req.enqueued_at)
            req.set_result(res)
