"""DecodeEngine — continuous batching for autoregressive LLM decode.

The batching engine (engine.py) coalesces fixed-shape requests: right
for classifiers, wrong for decode, where a batch member finishes when
IT emits eos, not when its peers do. This engine schedules at
**iteration level** (Orca/vLLM style, under this repo's
one-executable-per-program rule): every step, queued prompts are
admitted into free slots of a fixed ``max_batch``-wide decode program,
finished sequences retire and free their slots, and the XLA executable
never changes shape — request churn is pure host-side integer
bookkeeping over a paged KV cache (kv_pages.py).

The step programs (models/llama.py build_llama_paged_programs):

- **prefill-into-slot** — one program per declared prompt-length
  bucket, batch 1: runs the prompt through the stack, writes its KV
  into the slot's pages, returns the first greedy token (TTFT is
  measured here).
- **decode-step** — ONE program at [max_batch] that advances every
  slot ``decode_block`` tokens per dispatch. Inactive slots ride along
  masked (null page table, outputs discarded); each row's math depends
  only on its own row and pages, so a request's greedy tokens are
  bit-identical alone or co-scheduled — the same
  numerics-never-depend-on-peers discipline as PR 3's signature
  grouping, enforced structurally instead of by grouping.
- **spec-step** (``draft_cfg``) — speculative decoding as an engine
  mode: per round the draft proposes ``gamma`` tokens per slot and the
  target verifies them in one forward, with PER-ROW acceptance (rows
  advance at their own rate; the fused llama_spec_generate op is
  batch-lockstep).

Hardening is the PR 3/4 machinery at request level: bounded admission
(QueueFullError / PagesExhaustedError), per-request deadlines swept to
RequestTimeoutError, engine circuit breaker, HealthMonitor + watchdog
(worker death fails everything pending with WorkerDiedError — the
``serving_worker_crash`` fault point drills this), graceful
``close(drain=True)``, deadline propagation into dispatch retries, and
``warmup()`` + ``assert_no_recompiles()`` pinning the zero-recompile
steady state. Metrics add TTFT/TPOT windows and token counters —
tools/servebench.py --decode turns them into
``llama_decode_serving_tok_s``.
"""
import os
import threading
import time

import numpy as np

from ..core.executor import CPUPlace, Executor, global_scope
from ..resilience import faultinject as _faultinject
from ..resilience.retry import RetryPolicy, default_policy, with_retries
from .batching import (QueueFullError, RequestTimeoutError,
                       ServerClosedError)
from .buckets import BucketError
from .health import (CircuitBreaker, HealthMonitor, HealthState,
                     ServiceUnavailableError, WorkerDiedError)
from .batching import ServingError
from .kv_pages import PageAllocator, PagesExhaustedError
from .metrics import ServingMetrics
from .overload import BrownoutController
from .sched import get_scheduler, priority_rank, PRIORITIES

__all__ = ["DecodeConfig", "DecodeRequest", "DecodeEngine"]

_DECODE_COUNTERS = (
    "prefill_total", "decode_batches_total", "generated_tokens_total",
    "retired_total", "spec_rounds_total", "spec_tokens_accepted_total",
    "page_wait_total",
    # chunked prefill + SLO attainment + disaggregation (PR 18):
    # chunk_prefill_total counts chunk DISPATCHES (a long prompt is
    # several); the slo_* counters score each SLO-carrying request
    # once per target half; handoffs count exports (prefill side) and
    # imports (decode side) separately so a disaggregated pool's books
    # balance end to end
    "chunk_prefill_total",
    "slo_ttft_met", "slo_ttft_violated",
    "slo_tpot_met", "slo_tpot_violated",
    "handoff_export_total", "handoff_import_total",
    # overload robustness (PR 19): sheds broken out by priority tier
    # (the strict shed-ordering proof reads these), queue evictions
    # (a higher-priority arrival displacing a queued batch request),
    # and the brownout ladder — engage/revert transitions plus one
    # counter per degradation step so every brownout action is
    # metered and its full revert is checkable
    "shed_interactive_total", "shed_standard_total",
    "shed_batch_total", "evictions_total",
    "brownout_engage_total", "brownout_revert_total",
    "brownout_cap_max_new_total", "brownout_spec_off_total",
    "brownout_chunk_defer_total")

# priority rank -> the per-class shed counter it lands in
_SHED_BY_RANK = {rank: f"shed_{name}_total"
                 for name, rank in PRIORITIES.items()}


def _env_float(name, default):
    return float(os.environ.get(name, default))


class DecodeConfig:
    """Tuning knobs for one decode engine.

    Geometry — fixed at build time, every executable derives from it:
    ``max_batch`` concurrent decode slots; ``prompt_buckets`` declared
    prompt-length pads (one prefill executable each);
    ``max_new_tokens`` the per-request generation cap; ``page_size``
    positions per KV page; ``n_pages`` pool size (None → full
    residency: every slot can hold its longest sequence — smaller
    values overcommit and admission waits for pages);
    ``decode_block`` tokens generated per decode dispatch (the
    dispatch-overhead amortizer; admission/retirement happen at block
    boundaries); ``gamma`` draft tokens per speculative round.

    Traffic: ``eos_id`` retires a sequence early (None = generate to
    max_new); ``max_queue`` admission bound; ``default_timeout_s``
    per-request deadline when the caller gives none. Hardening knobs
    mirror ServingConfig (same env vars)."""

    def __init__(self, max_batch=4, prompt_buckets=(16, 32),
                 max_new_tokens=32, page_size=16, n_pages=None,
                 decode_block=4, prefill_batch=4, gamma=4,
                 eos_id=None, quantize=False,
                 max_queue=64, default_timeout_s=30.0,
                 retry_policy=None, breaker_threshold=None,
                 breaker_cooldown_s=None, drain_timeout_s=None,
                 watchdog_interval_s=None, hang_timeout_s=None,
                 chunk_size=None, scheduler=None, brownout=None):
        self.max_batch = int(max_batch)
        self.prompt_buckets = tuple(
            sorted(set(int(b) for b in prompt_buckets)))
        if not self.prompt_buckets or self.prompt_buckets[0] < 1:
            raise ValueError("prompt_buckets must be positive ints")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.page_size = int(page_size)
        self.n_pages = n_pages
        self.decode_block = max(1, int(decode_block))
        self.prefill_batch = max(1, int(prefill_batch))
        self.gamma = max(1, int(gamma))
        self.eos_id = eos_id
        self.quantize = bool(quantize)
        self.max_queue = int(max_queue)
        self.default_timeout_s = default_timeout_s
        self.retry_policy = retry_policy
        self.breaker_threshold = int(
            _env_float("PADDLE_TPU_BREAKER_THRESHOLD", 5)
            if breaker_threshold is None else breaker_threshold)
        self.breaker_cooldown_s = (
            _env_float("PADDLE_TPU_BREAKER_COOLDOWN", 1.0)
            if breaker_cooldown_s is None else float(breaker_cooldown_s))
        self.drain_timeout_s = (
            _env_float("PADDLE_TPU_DRAIN_TIMEOUT", 10.0)
            if drain_timeout_s is None else float(drain_timeout_s))
        self.watchdog_interval_s = (
            _env_float("PADDLE_TPU_WATCHDOG_INTERVAL", 0.1)
            if watchdog_interval_s is None else float(watchdog_interval_s))
        self.hang_timeout_s = (
            _env_float("PADDLE_TPU_HANG_TIMEOUT", 30.0)
            if hang_timeout_s is None else float(hang_timeout_s))
        # chunked prefill: prompts LONGER than chunk_size are prefilled
        # as chunk_size-token slices, one slice per engine iteration,
        # co-scheduled with the decode batch (None = whole-prompt
        # prefill only). scheduler: None/'fifo', 'slo', or an object
        # with order()/admit_now() (serving/sched.py)
        self.chunk_size = None if chunk_size is None else int(chunk_size)
        if self.chunk_size is not None and self.chunk_size < 1:
            raise ValueError(
                f"chunk_size must be >= 1, got {self.chunk_size}")
        self.scheduler = scheduler
        # brownout: None/False = off; True = ladder with defaults; a
        # dict = BrownoutController kwargs, plus the engine-side
        # "queue_target_s" (seconds of queue delay that count as full
        # pressure) and "max_new_cap" (batch-tier max_new under
        # level >= 1; default max_new_tokens // 4)
        self.brownout = brownout


class DecodeRequest:
    """Caller handle for one generation request. Settlement is
    first-writer-wins (the worker and the watchdog can race, exactly
    as in batching.PendingResult). ``result()`` returns the generated
    tokens as a 1-D int64 array (prompt not included; ends at eos_id
    inclusive when one was emitted)."""

    __slots__ = ("prompt", "max_new", "deadline", "enqueued_at",
                 "ttft_s", "slo", "prefill_only", "handoff_state",
                 "_event", "_result", "_error", "_settle_lock",
                 "_callbacks")

    def __init__(self, prompt, max_new, deadline, enqueued_at,
                 slo=None, prefill_only=False, handoff_state=None):
        self.prompt = prompt
        self.max_new = max_new
        self.deadline = deadline
        self.enqueued_at = enqueued_at
        self.slo = slo               # SLOClass or None (best effort)
        self.prefill_only = bool(prefill_only)
        self.handoff_state = handoff_state   # imported KV blob or None
        self.ttft_s = None           # set when the first token lands
        self._event = threading.Event()
        self._result = None
        self._error = None
        self._settle_lock = threading.Lock()
        self._callbacks = []

    def done(self):
        return self._event.is_set()

    def add_done_callback(self, fn):
        """Call ``fn(self)`` exactly once on settlement (result OR
        error); immediately if already settled. Same contract as
        PendingResult.add_done_callback — the router's admission
        accounting hangs off this. Callback exceptions are
        swallowed."""
        with self._settle_lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        self._run_callback(fn)

    def _run_callback(self, fn):
        try:
            fn(self)
        except Exception:       # noqa: BLE001 — observer must not break settle
            pass

    def set_result(self, value):
        with self._settle_lock:
            if self._event.is_set():
                return False
            self._result = value
            self._event.set()
            cbs, self._callbacks = self._callbacks, []
        for fn in cbs:           # outside the lock: observers may block
            self._run_callback(fn)
        return True

    def set_error(self, exc):
        with self._settle_lock:
            if self._event.is_set():
                return False
            self._error = exc
            self._event.set()
            cbs, self._callbacks = self._callbacks, []
        for fn in cbs:
            self._run_callback(fn)
        return True

    def wait(self, timeout=None):
        return self._event.wait(timeout)

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise RequestTimeoutError(
                "result not ready within the wait bound")
        if self._error is not None:
            raise self._error
        return self._result


class _Slot:
    """One active decode slot: the request, its page set / table row,
    and the per-sequence scheduler state."""

    __slots__ = ("req", "pages", "table", "pos", "cur", "prev",
                 "emitted", "first_token_at")

    def __init__(self, req, pages, table, pos, cur, prev, emitted,
                 first_token_at):
        self.req = req
        self.pages = pages
        self.table = table            # np int32 [pages_per_seq]
        self.pos = pos                # cache length (cur not cached yet)
        self.cur = cur                # last emitted token
        self.prev = prev              # token at pos - 1
        self.emitted = emitted        # generated tokens so far
        self.first_token_at = first_token_at


class _ChunkJob:
    """One in-progress chunked prefill: the request, its (already
    allocated) page set / table row, and the next slice offset. The
    job reserves a slot index (the slot stays None until the final
    chunk installs it), so free-slot accounting and the decode batch
    never see a half-prefilled sequence."""

    __slots__ = ("req", "pages", "table", "off")

    def __init__(self, req, pages, table, off=0):
        self.req = req
        self.pages = pages
        self.table = table            # np int32 [pages_per_seq]
        self.off = off                # prompt tokens prefilled so far


class DecodeEngine:
    """Continuous-batching decode server for one dense Llama-family
    config. ``scope`` must already hold the generator-layout weights
    (``build_llama_generator`` startup, a trained+stacked scope, or a
    ``quantize_generator_weights``'d one; draft weights under
    ``draft.*`` when ``draft_cfg`` — see models/llama.py
    copy_weights_as_draft). The engine never initializes weights."""

    def __init__(self, cfg, scope=None, place=None, config=None,
                 draft_cfg=None, auto_start=True, optimize=True,
                 compile_store=None):
        from ..models.llama import build_llama_paged_programs
        self.cfg = cfg
        self.draft_cfg = draft_cfg
        self.config = config or DecodeConfig()
        c = self.config
        self.scope = scope or global_scope()
        if c.chunk_size is not None and draft_cfg is not None:
            raise NotImplementedError(
                "chunked prefill is a target-model path (the draft "
                "would need its own chunk program); drop chunk_size "
                "or draft_cfg")
        # worst-case positions a slot can touch: a full longest bucket,
        # max_new generated, plus the block/speculation overshoot of
        # the final dispatch before retirement is noticed
        slack = c.decode_block + (c.gamma + 1 if draft_cfg else 0)
        seq_need = c.prompt_buckets[-1] + c.max_new_tokens + slack
        self.pages_per_seq = -(-seq_need // c.page_size)
        n_pages = (c.max_batch * self.pages_per_seq + 1
                   if c.n_pages is None else int(c.n_pages))
        self.allocator = PageAllocator(n_pages, c.page_size)
        self.sched = get_scheduler(c.scheduler)
        # brownout ladder (overload.py): pressure = max(normalized
        # queue delay, breaker-open, page occupancy beyond 90%). The
        # controller decides the level; this engine applies/reverts
        # the effects and counts them.
        self.brownout = None
        self._bo_queue_target_s = 0.5
        self._bo_max_new_cap = max(1, c.max_new_tokens // 4)
        if c.brownout:
            bo_kw = dict(c.brownout) if isinstance(c.brownout, dict) \
                else {}
            self._bo_queue_target_s = float(
                bo_kw.pop("queue_target_s", 0.5))
            self._bo_max_new_cap = int(
                bo_kw.pop("max_new_cap", self._bo_max_new_cap))
            self.brownout = BrownoutController(**bo_kw)
        self.programs = build_llama_paged_programs(
            cfg, max_batch=c.max_batch, page_size=c.page_size,
            n_pages=n_pages, pages_per_seq=self.pages_per_seq,
            prompt_buckets=c.prompt_buckets,
            decode_block=c.decode_block,
            prefill_batch=c.prefill_batch, quantize=c.quantize,
            draft_cfg=draft_cfg, gamma=c.gamma,
            chunk_size=c.chunk_size)
        # graph rewrites on every step program (analysis/optimize.py,
        # proven bit-exact by optcheck): the bundles are private
        # clones, so optimizing in place is safe, and each program's
        # version bump lands BEFORE warmup so the no-recompile pin
        # covers the optimized executables. Failure degrades to the
        # unoptimized bundle.
        self.optimize_reports = {}
        if optimize:
            self._optimize_programs()
        import jax.numpy as jnp
        self._kp = jnp.zeros(tuple(self.programs.kv_shape), cfg.dtype)
        self._vp = jnp.zeros(tuple(self.programs.kv_shape), cfg.dtype)
        self._dkp = self._dvp = None
        if draft_cfg is not None:
            self._dkp = jnp.zeros(tuple(self.programs.draft_kv_shape),
                                  draft_cfg.dtype)
            self._dvp = jnp.zeros(tuple(self.programs.draft_kv_shape),
                                  draft_cfg.dtype)
        # all retries surface at the serving layer (counted); the inner
        # executor must not also retry. donate_state=False: pool
        # replicas share one weight scope (see ServingEngine).
        # compile_store: persistent compiled-artifact store — a second
        # decode replica (or a rolling-restart rebuild) loads every
        # step executable the first one compiled instead of paying XLA
        # again (io/artifact_store.py; None defers to
        # PADDLE_TPU_ARTIFACT_DIR)
        self.exe = Executor(place or CPUPlace(),
                            retry_policy=RetryPolicy(max_attempts=1),
                            donate_state=False,
                            compile_store=compile_store)
        self.metrics = ServingMetrics(extra_counters=_DECODE_COUNTERS)
        self.health = HealthMonitor()
        self.breaker = CircuitBreaker(
            failure_threshold=c.breaker_threshold,
            cooldown_s=c.breaker_cooldown_s)
        self.slots = [None] * c.max_batch
        # slot idx -> _ChunkJob: chunked prefills in flight (the slot
        # itself stays None until the final chunk installs it)
        self._chunk_jobs = {}
        # guards slots + chunk jobs + allocator against the
        # close()/watchdog vs worker race (drain-timeout expiry,
        # worker death)
        self._slots_lock = threading.RLock()
        self._queue = []
        self._qlock = threading.Lock()
        self._cv = threading.Condition(self._qlock)
        self._closed = False          # no new admissions (drain)
        self._warmed = None
        self._worker = None
        self._watchdog = None
        self._worker_death_seen = False
        self._stop = threading.Event()
        self._watchdog_stop = threading.Event()
        # chaos hook: per-engine ungraceful worker kill (cluster chaos
        # targets one replica; the global fault point cannot)
        self._crash = threading.Event()
        if auto_start:
            self.start()

    # -- lifecycle -------------------------------------------------------
    def start(self):
        """Start (or restart after a watchdog-declared death) the
        worker + watchdog threads."""
        if self._worker is not None and self._worker.is_alive():
            return self
        self._stop.clear()
        self._crash.clear()
        self._worker_death_seen = False
        self.health.beat()
        self._worker = threading.Thread(
            target=self._worker_loop, name="paddle-tpu-decode-worker",
            daemon=True)
        self._worker.start()
        if self._watchdog is None or not self._watchdog.is_alive():
            self._watchdog_stop.clear()
            self._watchdog = threading.Thread(
                target=self._watchdog_loop,
                name="paddle-tpu-decode-watchdog", daemon=True)
            self._watchdog.start()
        self.health.to(HealthState.READY)
        return self

    def close(self, timeout=5.0, drain=False, drain_timeout=None):
        """``drain=False``: stop admitting, refuse everything pending
        with ServerClosedError, join. ``drain=True``: stop admitting,
        let the worker FINISH every admitted request (bounded by
        ``drain_timeout``, default config.drain_timeout_s); per-request
        deadlines stay live during the drain."""
        worker = self._worker
        if drain and worker is not None and worker.is_alive() \
                and not self._stop.is_set():
            self.health.to(HealthState.DRAINING)
            with self._cv:
                self._closed = True
                self._cv.notify_all()
            budget = (self.config.drain_timeout_s
                      if drain_timeout is None else float(drain_timeout))
            worker.join(max(budget, 0.0))
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._stop.set()
        for req in self._take_pending():
            req.set_error(ServerClosedError("engine closed"))
        if self._worker is not None:
            self._worker.join(timeout)
        self._watchdog_stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout)
            self._watchdog = None
        self.health.to(HealthState.STOPPED)
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- warmup ----------------------------------------------------------
    def warmup(self):
        """Pre-compile every step executable (each prefill bucket, the
        decode step, the spec step) with null-page dummy dispatches,
        then snapshot compile counts for assert_no_recompiles(). The
        steady state after this never compiles, no matter how requests
        churn."""
        n = 0
        pb = self.config.prefill_batch
        for bucket in sorted(self.programs.prefill):
            self._run_prefill_program(
                bucket, np.zeros((pb, bucket), np.int64),
                np.ones((pb,), np.int32),
                np.zeros((pb, self.pages_per_seq), np.int32))
            n += 1
            if self.draft_cfg is not None:
                self._run_draft_prefill_program(
                    bucket, np.zeros((pb, bucket), np.int64),
                    np.ones((pb,), np.int32),
                    np.zeros((pb, self.pages_per_seq), np.int32))
                n += 1
        if self.programs.chunk is not None:
            cs = self.programs.chunk_size
            self._run_chunk_program(
                np.zeros((1, cs), np.int64), np.ones((1,), np.int32),
                np.zeros((1,), np.int32),
                np.zeros((1, self.pages_per_seq), np.int32))
            n += 1
        # the PLAIN decode program warms even for speculative engines:
        # brownout level 2 (spec_off) switches a live engine to it,
        # and the no-recompile pin must survive that switch
        self._run_decode_program(
            np.zeros((self.config.max_batch,), np.int64),
            np.ones((self.config.max_batch,), np.int32),
            np.zeros((self.config.max_batch, self.pages_per_seq),
                     np.int32))
        n += 1
        if self.draft_cfg is not None:
            self._run_spec_program(
                np.zeros((self.config.max_batch,), np.int64),
                np.zeros((self.config.max_batch,), np.int64),
                np.ones((self.config.max_batch,), np.int32),
                np.zeros((self.config.max_batch, self.pages_per_seq),
                         np.int32))
            n += 1
        self._warmed = self.exe.compile_counts()
        compiles = self.exe.total_compiles()
        self.metrics.incr("warmup_compiles", compiles)
        return {"programs": n, "compiles": compiles}

    def assert_no_recompiles(self):
        """AssertionError if any XLA compile happened after warmup —
        the churn-proof contract. No-op before warmup."""
        if self._warmed is None:
            return
        now = self.exe.compile_counts()
        if now != self._warmed:
            raise AssertionError(
                f"decode executables changed after warmup: "
                f"{self._warmed} -> {now} — a traced shape escaped the "
                "paged-buffer discipline")

    # -- request path ----------------------------------------------------
    def submit(self, prompt, max_new=None, timeout=None, slo=None,
               prefill_only=False, queued_for_s=0.0):
        """Enqueue one prompt; returns a DecodeRequest immediately.
        Rejections (all before any queueing): BucketError (prompt
        outside every declared bucket), PagesExhaustedError (the
        request can NEVER fit the page pool), QueueFullError (shed),
        ServiceUnavailableError (breaker open), ServerClosedError.

        ``slo``: an SLOClass — the scheduler orders admission by its
        TTFT deadline and the attainment counters score against it
        (no SLO = best-effort, FIFO among best-effort peers). The
        SLO's ``priority`` tier also drives overload behavior: a full
        queue EVICTS the lowest-priority queued request (counted in
        ``evictions_total`` + its class's ``shed_*_total``) when the
        newcomer outranks it, instead of flat-shedding the newcomer.
        ``prefill_only=True``: the request resolves with a KV handoff
        blob (page contents + generated-so-far) instead of generated
        tokens — the disaggregated prefill replica's verb; feed the
        blob to a decode replica's :meth:`import_handoff`.
        ``queued_for_s``: seconds this request ALREADY waited upstream
        (a router redrive, a cross-process hop) — backdates
        ``enqueued_at`` so TTFT and the EDF deadline measure from the
        original arrival, never from the latest hop (an age, not an
        absolute timestamp, so it is clock-skew-free on the wire)."""
        if slo is not None and (
                not hasattr(slo, "ttft_target_s")
                or not hasattr(slo, "tpot_target_s")):
            raise TypeError(
                f"slo must be an SLOClass (serving.sched), got "
                f"{type(slo).__name__}")
        prompt = np.asarray(prompt, dtype=np.int64).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        if prompt.size > self.config.prompt_buckets[-1]:
            self.metrics.incr("shed_total")
            raise BucketError(
                f"prompt of {prompt.size} tokens exceeds the largest "
                f"declared bucket {self.config.prompt_buckets[-1]}")
        max_new = (self.config.max_new_tokens if max_new is None
                   else int(max_new))
        if not 1 <= max_new <= self.config.max_new_tokens:
            raise ValueError(
                f"max_new must be in [1, {self.config.max_new_tokens}]"
                f", got {max_new}")
        rank = priority_rank(slo) if slo is not None \
            else PRIORITIES["standard"]
        if self.brownout is not None and rank == PRIORITIES["batch"] \
                and self.brownout.active("cap_batch_max_new") \
                and max_new > self._bo_max_new_cap:
            # brownout level >= 1: batch-tier generation is capped —
            # fewer tokens, identical numerics for every token served
            max_new = self._bo_max_new_cap
            self.metrics.incr("brownout_cap_max_new_total")
        if self._pages_needed(prompt.size, max_new) \
                > self.allocator.usable_pages:
            self.metrics.incr("shed_total")
            raise PagesExhaustedError(
                f"request needs {self._pages_needed(prompt.size, max_new)}"
                f" pages but the pool only has "
                f"{self.allocator.usable_pages} — grow n_pages or "
                "shorten the request")
        if not self.breaker.admits():
            self.metrics.incr("breaker_shed_total")
            raise ServiceUnavailableError(
                "circuit breaker open — the engine is failing; back "
                f"off at least {self.config.breaker_cooldown_s}s")
        if timeout is None:
            timeout = self.config.default_timeout_s
        now = time.monotonic()
        req = DecodeRequest(
            prompt=prompt, max_new=max_new,
            deadline=None if timeout is None else now + float(timeout),
            enqueued_at=now - max(0.0, float(queued_for_s)),
            slo=slo, prefill_only=prefill_only)
        victim = None
        with self._cv:
            if self._closed:
                raise ServerClosedError("decode engine is closed")
            if len(self._queue) >= self.config.max_queue:
                # priority eviction: displace the WORST queued request
                # iff the newcomer strictly outranks it — under
                # pressure batch leaves the queue first, interactive
                # never yields to anything
                worst_i = max(range(len(self._queue)),
                              key=lambda i: (
                                  priority_rank(self._queue[i]),
                                  self._queue[i].enqueued_at))
                if priority_rank(self._queue[worst_i]) > rank:
                    victim = self._queue.pop(worst_i)
                else:
                    self.metrics.incr("shed_total")
                    self.metrics.incr(
                        _SHED_BY_RANK.get(rank, "shed_standard_total"))
                    raise QueueFullError(
                        f"admission queue full "
                        f"({self.config.max_queue} requests) — load "
                        "shed, retry with backoff")
            self._queue.append(req)
            self._cv.notify_all()
        if victim is not None:
            self.metrics.incr("shed_total")
            self.metrics.incr("evictions_total")
            self.metrics.incr(
                _SHED_BY_RANK.get(priority_rank(victim),
                                  "shed_standard_total"))
            victim.set_error(QueueFullError(
                "evicted from a full admission queue by a "
                "higher-priority request — load shed, retry with "
                "backoff"))
        # progress mark for deterministic chaos barriers: "crash N loop
        # iterations after the K-th admission" (faultinject.arm after=)
        _faultinject.event("decode_submit")
        self.metrics.incr("requests_total")
        self.metrics.set_queue_depth(len(self._queue))
        return req

    def generate(self, prompt, max_new=None, timeout=None):
        """Synchronous convenience: submit + liveness-aware wait.
        Returns the generated tokens (1-D int64)."""
        req = self.submit(prompt, max_new=max_new, timeout=timeout)
        end = None if req.deadline is None else req.deadline + 10.0
        while True:
            if req.wait(0.05):
                return req.result(0)
            worker = self._worker
            if worker is None or not worker.is_alive():
                if req.wait(0.2):
                    return req.result(0)
                raise WorkerDiedError(
                    "decode worker died while this request waited "
                    "(restart the engine with start())")
            if end is not None and time.monotonic() >= end:
                return req.result(0)

    def import_handoff(self, state, timeout=None, slo=None):
        """Adopt a prefill replica's exported KV state: allocate local
        pages, copy the page contents in (an exact value copy — the
        paged cache is location-independent, so fresh page ids cost
        nothing), install a decode slot, and continue generating.
        Returns a DecodeRequest whose result is the FULL generated
        token sequence (handed-off tokens included). This is the
        decode half of the ``handoff`` replica verb.

        Typed rejections mirror submit(): ServingError on a malformed
        or geometry-mismatched blob, PagesExhaustedError when the
        state can never fit, QueueFullError / ServiceUnavailableError
        / ServerClosedError under load/failure."""
        if not isinstance(state, dict) \
                or state.get("kind") != "kv_handoff" \
                or not all(key in state for key in
                           ("prompt", "max_new", "pos", "cur", "prev",
                            "emitted", "pages", "page_size", "k", "v")):
            raise ServingError(
                "import_handoff needs the blob a prefill_only request "
                "resolved with (dict with kind='kv_handoff')")
        if int(state["page_size"]) != self.config.page_size:
            raise ServingError(
                f"handoff page_size {state['page_size']} != this "
                f"engine's {self.config.page_size} — prefill and "
                "decode replicas must share the page geometry")
        prompt = np.asarray(state["prompt"], np.int64).reshape(-1)
        max_new = int(state["max_new"])
        emitted = [int(t) for t in state["emitted"]]
        if timeout is None:
            timeout = self.config.default_timeout_s
        now = time.monotonic()
        req = DecodeRequest(
            prompt=prompt, max_new=max_new,
            deadline=None if timeout is None else now + float(timeout),
            enqueued_at=now, slo=slo, handoff_state=state)
        req.ttft_s = state.get("ttft_s")
        self.metrics.incr("requests_total")
        if state.get("done"):
            # the prefill side already finished the sequence (eos on
            # the first token / max_new == 1): settle without touching
            # the pool
            self.metrics.incr("handoff_import_total")
            self.metrics.incr("responses_total")
            self.metrics.incr("retired_total")
            req.set_result(np.asarray(emitted, dtype=np.int64))
            return req
        k = np.asarray(state["k"])
        if self.allocator.pages_for(prompt.size + max_new) \
                > self.allocator.usable_pages \
                or k.shape[1] > self.allocator.usable_pages:
            self.metrics.incr("shed_total")
            raise PagesExhaustedError(
                f"handoff state needs {k.shape[1]} pages but the pool "
                f"only has {self.allocator.usable_pages}")
        if not self.breaker.admits():
            self.metrics.incr("breaker_shed_total")
            raise ServiceUnavailableError(
                "circuit breaker open — handoff shed; back off at "
                f"least {self.config.breaker_cooldown_s}s")
        with self._cv:
            if self._closed:
                raise ServerClosedError("decode engine is closed")
            if len(self._queue) >= self.config.max_queue:
                self.metrics.incr("shed_total")
                raise QueueFullError(
                    f"admission queue full ({self.config.max_queue} "
                    "requests) — load shed, retry with backoff")
            self._queue.append(req)
            self._cv.notify_all()
        _faultinject.event("decode_submit")
        self.metrics.set_queue_depth(len(self._queue))
        return req

    def outstanding(self):
        """Admitted-but-unfinished requests: queued prompts plus
        active decode slots plus in-flight chunked prefills — the
        cluster router's balancing signal (cheap reads, not a
        stats() snapshot)."""
        with self._qlock:
            queued = len(self._queue)
        return (queued + sum(s is not None for s in self.slots)
                + len(self._chunk_jobs))

    def _simulate_worker_crash(self):
        """Kill THIS engine's worker ungracefully on its next loop
        iteration (per-engine SIGKILL model for cluster chaos).
        start() revives."""
        self._crash.set()

    def worker_alive(self):
        """True iff the worker thread exists and is running."""
        w = self._worker
        return w is not None and w.is_alive()

    def stats(self):
        snap = self.metrics.stats()
        snap["compiles_now"] = self.exe.total_compiles()
        with self._qlock:
            snap["queue_depth"] = len(self._queue)
        snap["active_slots"] = sum(s is not None for s in self.slots)
        snap["active_chunk_jobs"] = len(self._chunk_jobs)
        snap["scheduler"] = getattr(self.sched, "name",
                                    type(self.sched).__name__)
        snap["max_batch"] = self.config.max_batch
        snap["pages_in_use"] = self.allocator.in_use
        snap["pages_available"] = self.allocator.available
        snap["health_state"] = self.health.state
        snap["breaker"] = self.breaker.snapshot()
        snap["brownout"] = (None if self.brownout is None
                            else self.brownout.snapshot())
        snap["optimize"] = self.optimize_reports or None
        snap["artifact_store"] = self.exe.store_stats()
        return snap

    # -- internal: program rewrites --------------------------------------
    def _optimize_programs(self):
        """Runs the rewrite pipeline (Program.optimize) over every
        step-program bundle, keyed like the dispatch methods name
        them. All bundles are private clones built by
        build_llama_paged_programs, so in-place mutation leaks
        nowhere; fetch Variables are resolved by NAME because they
        belong to the pre-clone builder program."""
        import warnings
        bundles = {}
        for bucket, b in self.programs.prefill.items():
            bundles[f"prefill_{bucket}"] = b
        if self.programs.draft_prefill:
            for bucket, b in self.programs.draft_prefill.items():
                bundles[f"draft_prefill_{bucket}"] = b
        bundles["decode"] = self.programs.decode
        if self.programs.chunk is not None:
            bundles["chunk"] = self.programs.chunk
        if self.programs.spec is not None:
            bundles["spec"] = self.programs.spec
        for label, b in bundles.items():
            try:
                report = b["program"].optimize(
                    fetch_list=[v.name if hasattr(v, "name") else v
                                for v in b["fetch"]])
                if report:
                    self.optimize_reports[label] = report.to_dict()
            except Exception as e:  # pragma: no cover - safety net
                warnings.warn(
                    f"decode optimize rewrite failed on {label} "
                    f"({e!r}); serving it unoptimized", stacklevel=2)

    # -- internal: program dispatch --------------------------------------
    @staticmethod
    def _maybe_inject_fault():
        """serving_device_error fault point, raised INSIDE the retried
        dispatch so armed fault counts interact with the retry policy
        exactly as in ServingEngine."""
        if _faultinject.fires("serving_device_error"):
            from ..resilience.retry import TransientDeviceError
            raise TransientDeviceError(
                "injected serving-layer transient device error "
                "(UNAVAILABLE)")

    def _bundle_feed(self, bundle, arrays):
        return dict(zip(bundle["feeds"], arrays))

    # scope is passed explicitly to every run — scope_guard swaps a
    # process-global, which would race other live engines' threads
    def _run_prefill_program(self, bucket, tokens, lens, table):
        b = self.programs.prefill[bucket]
        nxt, self._kp, self._vp = self.exe.run(
            b["program"],
            feed=self._bundle_feed(
                b, (tokens, lens, table, self._kp, self._vp)),
            fetch_list=b["fetch"], mode="test", return_numpy=False,
            scope=self.scope)
        return np.asarray(nxt)

    def _run_draft_prefill_program(self, bucket, tokens, lens, table):
        b = self.programs.draft_prefill[bucket]
        _, self._dkp, self._dvp = self.exe.run(
            b["program"],
            feed=self._bundle_feed(
                b, (tokens, lens, table, self._dkp, self._dvp)),
            fetch_list=b["fetch"], mode="test", return_numpy=False,
            scope=self.scope)

    def _run_chunk_program(self, tokens, lens, offsets, table):
        b = self.programs.chunk
        nxt, self._kp, self._vp = self.exe.run(
            b["program"],
            feed=self._bundle_feed(
                b, (tokens, lens, offsets, table, self._kp, self._vp)),
            fetch_list=b["fetch"], mode="test", return_numpy=False,
            scope=self.scope)
        return np.asarray(nxt)

    def _run_decode_program(self, tokens, positions, table):
        b = self.programs.decode
        out, self._kp, self._vp = self.exe.run(
            b["program"],
            feed=self._bundle_feed(
                b, (tokens, positions, table, self._kp, self._vp)),
            fetch_list=b["fetch"], mode="test", return_numpy=False,
            scope=self.scope)
        return np.asarray(out)

    def _run_spec_program(self, tokens, prev, positions, table):
        b = self.programs.spec
        (emitted, accepted, self._kp, self._vp, self._dkp,
         self._dvp) = self.exe.run(
            b["program"],
            feed=self._bundle_feed(
                b, (tokens, prev, positions, table, self._kp,
                    self._vp, self._dkp, self._dvp)),
            fetch_list=b["fetch"], mode="test", return_numpy=False,
            scope=self.scope)
        return np.asarray(emitted), np.asarray(accepted)

    # -- internal: scheduler ---------------------------------------------
    def _pages_needed(self, prompt_len, max_new):
        c = self.config
        bucket = self._bucket_for(prompt_len)
        slack = c.decode_block + (c.gamma + 1 if self.draft_cfg else 0)
        return self.allocator.pages_for(
            max(bucket, prompt_len + max_new + slack))

    def _bucket_for(self, prompt_len):
        for b in self.config.prompt_buckets:
            if b >= prompt_len:
                return b
        raise BucketError(
            f"prompt length {prompt_len} exceeds the largest bucket")

    def _has_work(self):
        with self._qlock:
            queued = len(self._queue)
        return queued > 0 or any(s is not None for s in self.slots) \
            or bool(self._chunk_jobs)

    def _pressure(self):
        """The overload pressure signal in [0, 1]: max of (a) oldest
        queued wait normalized by the queue-delay target, (b) breaker
        open, (c) page-pool occupancy beyond 90% (full residency at
        steady state is normal; the last 10% means admission is about
        to wait on pages)."""
        now = time.monotonic()
        with self._qlock:
            oldest = min((r.enqueued_at for r in self._queue),
                         default=None)
        q = 0.0 if oldest is None else min(
            1.0, max(0.0, now - oldest) / self._bo_queue_target_s)
        b = 0.0 if self.breaker.admits() else 1.0
        in_use = self.allocator.in_use
        total = in_use + self.allocator.available
        occ = in_use / total if total else 0.0
        return max(q, b, max(0.0, (occ - 0.9) / 0.1))

    def _update_brownout(self):
        """One controller tick per worker iteration: feed the pressure
        signal, count level transitions. Returns True when the level
        moved (the loop treats that as progress so a braking engine
        keeps ticking)."""
        if self.brownout is None:
            return False
        old, new = self.brownout.update(self._pressure())
        if new > old:
            self.metrics.incr("brownout_engage_total")
        elif new < old:
            self.metrics.incr("brownout_revert_total")
        return new != old

    def _take_pending(self):
        """Remove and return every queued request plus every active
        slot's / chunk job's request, freeing their pages
        (shutdown/death path)."""
        with self._qlock:
            q, self._queue = self._queue, []
        pending = list(q)
        with self._slots_lock:
            for i, slot in enumerate(self.slots):
                if slot is not None:
                    pending.append(slot.req)
                    self.allocator.free(slot.pages)
                    self.slots[i] = None
            jobs, self._chunk_jobs = dict(self._chunk_jobs), {}
            for job in jobs.values():
                pending.append(job.req)
                self.allocator.free(job.pages)
        return pending

    def _sweep_expired(self):
        """Fail deadline-blown queued requests before any compute is
        spent on peers (the batching.py discipline)."""
        now = time.monotonic()
        expired = []
        with self._qlock:
            keep = []
            for r in self._queue:
                if r.deadline is not None and now >= r.deadline:
                    expired.append(r)
                else:
                    keep.append(r)
            self._queue = keep
        for r in expired:
            self.metrics.incr("timeouts_total")
            r.set_error(RequestTimeoutError(
                "request deadline expired before it was served "
                "(queue saturated or timeout too tight)"))
        return bool(expired)

    def _retire(self, idx, error=None, draining=False):
        with self._slots_lock:
            slot = self.slots[idx]
            if slot is None:      # already failed by close()/watchdog
                return
            self.slots[idx] = None
            self.allocator.free(slot.pages)
        now = time.monotonic()
        if error is not None:
            slot.req.set_error(error)
        else:
            n = len(slot.emitted)
            if n > 1 and slot.first_token_at is not None:
                tpot = (now - slot.first_token_at) / (n - 1)
                self.metrics.observe_window("tpot_s", tpot)
                slo = slot.req.slo
                if slo is not None:
                    if slo.tpot_target_s is not None:
                        self.metrics.incr(
                            "slo_tpot_met"
                            if tpot <= slo.tpot_target_s
                            else "slo_tpot_violated")
                    self.metrics.observe_window(
                        f"{slo.name}.tpot_s", tpot)
            self.metrics.observe_latency(now - slot.req.enqueued_at)
            self.metrics.incr("responses_total")
            self.metrics.incr("retired_total")
            if draining:
                self.metrics.incr("drained_total")
            slot.req.set_result(
                np.asarray(slot.emitted, dtype=np.int64))
        with self._cv:
            self._cv.notify_all()

    def _is_chunk_path(self, r):
        """Long prompts go through the chunked-prefill path when the
        chunk program exists; handoff imports and short prompts never
        do."""
        return (r.handoff_state is None
                and self.programs.chunk is not None
                and r.prompt.size > self.programs.chunk_size)

    def _admit(self, policy):
        """Move queued prompts into free slots — in SCHEDULER order
        (serving/sched.py): each pass re-sorts the queue (EDF over
        TTFT deadlines for the SLO scheduler, arrival order for FIFO)
        and asks the scheduler whether prefill work may run this
        iteration at all (the TPOT budget guard defers admission to
        the decode batch when a running stream is about to blow its
        per-token budget). The head of the order then picks its path:
        handoff import (pages + an eager KV copy, no dispatch),
        chunked prefill (reserve a slot + pages now; the slices run in
        _step_chunks), or whole-prompt prefill — up to
        ``prefill_batch`` same-bucket requests per DISPATCH (one
        dispatch per request would make admission cost rival the fused
        baseline). Rows are independent inside the prefill program, so
        grouping never couples request numerics (same contract as the
        decode step). Transient page exhaustion leaves requests queued
        (retirement frees pages and wakes admission); a terminal
        prefill failure fails only that dispatch's requests."""
        admitted = False
        while True:
            with self._slots_lock:
                free = [i for i, sl in enumerate(self.slots)
                        if sl is None and i not in self._chunk_jobs]
            if not free:
                break
            now = time.monotonic()
            with self._qlock:
                if not self._queue:
                    break
                self._queue = self.sched.order(self._queue, now)
                if not self.sched.admit_now(self._queue, self.slots,
                                            now):
                    break
                head = self._queue[0]
                if head.handoff_state is not None:
                    self._queue.pop(0)
                    plan = ("handoff", head)
                elif self._is_chunk_path(head):
                    self._queue.pop(0)
                    plan = ("chunk", head)
                else:
                    limit = min(len(free), self.config.prefill_batch)
                    bucket = self._bucket_for(head.prompt.size)
                    group, rest = [], []
                    for r in self._queue:
                        if (len(group) < limit
                                and r.handoff_state is None
                                and not self._is_chunk_path(r)
                                and self._bucket_for(r.prompt.size)
                                == bucket):
                            group.append(r)
                        else:
                            rest.append(r)
                    self._queue = rest
                    plan = ("prefill", bucket, group)
            if plan[0] == "handoff":
                if not self._admit_handoff(plan[1], free[0]):
                    break
                admitted = True
                continue
            if plan[0] == "chunk":
                if not self._start_chunk_job(plan[1], free[0]):
                    break
                admitted = True
                continue
            bucket, group = plan[1], plan[2]
            granted = []       # (req, pages) actually prefilling now
            starved = []
            for j, r in enumerate(group):
                if starved:
                    starved.append(r)
                    continue
                try:
                    with self._slots_lock:
                        pages = self.allocator.alloc(
                            self._pages_needed(r.prompt.size,
                                               r.max_new))
                except PagesExhaustedError:
                    self.metrics.incr("page_wait_total")
                    starved.append(r)
                    continue
                granted.append((r, pages))
            if starved:        # put them back at the front, in order
                with self._qlock:
                    self._queue[0:0] = starved
            if not granted:
                break
            self.metrics.set_queue_depth(len(self._queue))
            if not self.breaker.allow():
                with self._slots_lock:
                    for _, pages in granted:
                        self.allocator.free(pages)
                self.metrics.incr("breaker_shed_total", len(granted))
                for r, _ in granted:
                    r.set_error(ServiceUnavailableError(
                        "circuit breaker open — prefill shed; back "
                        f"off {self.config.breaker_cooldown_s}s"))
                continue
            pb = self.config.prefill_batch
            tokens = np.zeros((pb, bucket), np.int64)
            lens = np.ones((pb,), np.int32)
            tables = np.zeros((pb, self.pages_per_seq), np.int32)
            for j, (r, pages) in enumerate(granted):
                tokens[j, :r.prompt.size] = r.prompt
                lens[j] = r.prompt.size
                tables[j, :len(pages)] = pages
            deadlines = [r.deadline for r, _ in granted
                         if r.deadline is not None]

            def _prefill_dispatch():
                self._maybe_inject_fault()
                nxt = self._run_prefill_program(bucket, tokens, lens,
                                                tables)
                if self.draft_cfg is not None:
                    self._run_draft_prefill_program(bucket, tokens,
                                                    lens, tables)
                return nxt

            try:
                nxt = with_retries(
                    _prefill_dispatch, policy=policy,
                    deadline=min(deadlines) if deadlines else None,
                    on_retry=lambda exc, n, delay:
                        self.metrics.incr("retries_total"))
            except BaseException as exc:     # noqa: BLE001 — forwarded
                with self._slots_lock:
                    for _, pages in granted:
                        self.allocator.free(pages)
                if self.breaker.record_failure():
                    self.metrics.incr("breaker_open_total")
                    self.health.to(HealthState.DEGRADED)
                self.metrics.incr("errors_total", len(granted))
                for r, _ in granted:
                    r.set_error(exc)
                continue
            self.breaker.record_success()
            for j, (r, pages) in enumerate(granted):
                self._install_first_token(r, pages, tables[j],
                                          int(nxt[j]), free[j])
            admitted = True
        return admitted

    def _score_ttft(self, r):
        """SLO attainment bookkeeping for a freshly prefilled request:
        met/violated counter (only when the class has a TTFT half) and
        the per-class latency window."""
        slo = r.slo
        if slo is None or r.ttft_s is None:
            return
        if slo.ttft_target_s is not None:
            self.metrics.incr("slo_ttft_met"
                              if r.ttft_s <= slo.ttft_target_s
                              else "slo_ttft_violated")
        self.metrics.observe_window(f"{slo.name}.ttft_s", r.ttft_s)

    def _install_first_token(self, r, pages, table, first, idx):
        """Post-prefill bookkeeping shared by whole-prompt admission
        and the final chunk of a chunked prefill: TTFT accounting,
        then either a decode slot install or — for ``prefill_only``
        requests — a KV handoff export (the request resolves with the
        handoff blob instead of occupying a slot)."""
        now = time.monotonic()
        r.ttft_s = now - r.enqueued_at
        self.metrics.observe_window("ttft_s", r.ttft_s)
        self._score_ttft(r)
        self.metrics.incr("prefill_total")
        self.metrics.incr("generated_tokens_total")
        if r.prefill_only:
            self._export_handoff(r, pages, first)
            return
        with self._slots_lock:
            self.slots[idx] = _Slot(
                r, pages, table, pos=r.prompt.size, cur=first,
                prev=int(r.prompt[-1]), emitted=[first],
                first_token_at=now)
        eos = self.config.eos_id
        if (eos is not None and first == eos) or r.max_new == 1:
            self._retire(idx, draining=self._closed
                         and not self._stop.is_set())

    def _export_handoff(self, r, pages, first):
        """Resolve a ``prefill_only`` request with the KV handoff
        blob: the filled page CONTENTS in table order (sequence
        position p lives at blob page ``p // page_size``), the prompt,
        and the tokens generated so far. Pages are freed here — the
        blob owns the KV state now; import allocates fresh pages on
        the destination, so the handoff is location-independent."""
        with self._slots_lock:
            alloc_state = self.allocator.export_state(pages)
        idxs = np.asarray(pages, np.int64)
        k = np.asarray(self._kp)[:, idxs]
        v = np.asarray(self._vp)[:, idxs]
        with self._slots_lock:
            self.allocator.free(pages)
        eos = self.config.eos_id
        done = (eos is not None and first == eos) or r.max_new == 1
        if done:
            # a finished request needs no KV — the importer resolves it
            # without a decode slot, so don't ship dead pages
            k = k[:, :0]
            v = v[:, :0]
            alloc_state = {"pages": [], "page_size":
                           alloc_state["page_size"]}
        state = {"kind": "kv_handoff",
                 "prompt": np.asarray(r.prompt, np.int64),
                 "max_new": int(r.max_new),
                 "pos": int(r.prompt.size),
                 "cur": int(first),
                 "prev": int(r.prompt[-1]),
                 "emitted": [int(first)],
                 "pages": alloc_state["pages"],
                 "page_size": alloc_state["page_size"],
                 "k": k, "v": v,
                 "done": bool(done),
                 "ttft_s": r.ttft_s}
        self.metrics.incr("handoff_export_total")
        self.metrics.observe_latency(time.monotonic() - r.enqueued_at)
        self.metrics.incr("responses_total")
        self.metrics.incr("retired_total")
        r.set_result(state)
        with self._cv:
            self._cv.notify_all()

    def _admit_handoff(self, r, idx):
        """Install an imported handoff blob into slot ``idx``: fresh
        pages, an exact value copy of the exported page contents into
        the local pools (an EAGER array update — no program dispatch,
        no new executable, so the no-recompile pin is untouched), and
        a decode slot resuming at the handed-off position. Returns
        False (request requeued at the front) on page exhaustion."""
        state = r.handoff_state
        k = np.asarray(state["k"])
        v = np.asarray(state["v"])
        n_src = int(k.shape[1])
        try:
            with self._slots_lock:
                pages = self.allocator.import_alloc(
                    state,
                    total=self._pages_needed(r.prompt.size, r.max_new))
        except PagesExhaustedError:
            self.metrics.incr("page_wait_total")
            with self._qlock:
                self._queue.insert(0, r)
            return False
        import jax.numpy as jnp
        rows = np.asarray(pages[:n_src], np.int64)
        self._kp = self._kp.at[:, rows].set(
            jnp.asarray(k, self._kp.dtype))
        self._vp = self._vp.at[:, rows].set(
            jnp.asarray(v, self._vp.dtype))
        table = np.zeros((self.pages_per_seq,), np.int32)
        table[:len(pages)] = pages
        emitted = [int(t) for t in state["emitted"]]
        with self._slots_lock:
            self.slots[idx] = _Slot(
                r, pages, table, pos=int(state["pos"]),
                cur=int(state["cur"]), prev=int(state["prev"]),
                emitted=emitted,
                first_token_at=time.monotonic())
        self.metrics.incr("handoff_import_total")
        eos = self.config.eos_id
        if (eos is not None and emitted and emitted[-1] == eos) \
                or len(emitted) >= r.max_new:
            self._retire(idx, draining=self._closed
                         and not self._stop.is_set())
        return True

    def _start_chunk_job(self, r, idx):
        """Reserve slot ``idx`` and the request's full page budget for
        a chunked prefill. No dispatch happens here — the slices run
        one per engine iteration in _step_chunks, interleaved with the
        decode batch. Returns False (request requeued at the front) on
        page exhaustion."""
        try:
            with self._slots_lock:
                pages = self.allocator.alloc(
                    self._pages_needed(r.prompt.size, r.max_new))
        except PagesExhaustedError:
            self.metrics.incr("page_wait_total")
            with self._qlock:
                self._queue.insert(0, r)
            return False
        table = np.zeros((self.pages_per_seq,), np.int32)
        table[:len(pages)] = pages
        with self._slots_lock:
            self._chunk_jobs[idx] = _ChunkJob(r, pages, table)
        return True

    def _fail_chunk_job(self, idx, exc):
        with self._slots_lock:
            job = self._chunk_jobs.pop(idx, None)
            if job is None:
                return
            self.allocator.free(job.pages)
        job.req.set_error(exc)
        with self._cv:
            self._cv.notify_all()

    def _step_chunks(self, policy):
        """One chunk dispatch per in-flight chunked prefill — chunk
        work is per-step work, interleaved with the decode batch so a
        long prompt never monopolizes the worker between decode steps.
        The final chunk's NextTok is the request's first token (TTFT
        lands there, via _install_first_token). A terminal dispatch
        failure fails only that job's request."""
        with self._slots_lock:
            jobs = sorted(self._chunk_jobs)
        if not jobs:
            return False
        if len(jobs) > 1 and self.brownout is not None \
                and self.brownout.active("chunk_shrink"):
            # brownout level 3: one chunk slice per iteration — decode
            # steps for running streams outrank prefill progress for
            # queued long prompts while the crowd passes
            self.metrics.incr("brownout_chunk_defer_total",
                              len(jobs) - 1)
            jobs = jobs[:1]
        cs = self.programs.chunk_size
        progressed = False
        for idx in jobs:
            with self._slots_lock:
                job = self._chunk_jobs.get(idx)
            if job is None:
                continue
            r = job.req
            if r.deadline is not None \
                    and time.monotonic() >= r.deadline:
                self.metrics.incr("timeouts_total")
                self._fail_chunk_job(idx, RequestTimeoutError(
                    "request deadline expired mid-chunked-prefill"))
                progressed = True
                continue
            sl = r.prompt[job.off:job.off + cs]
            tokens = np.zeros((1, cs), np.int64)
            tokens[0, :sl.size] = sl
            lens = np.asarray([sl.size], np.int32)
            offs = np.asarray([job.off], np.int32)
            table = job.table.reshape(1, -1)

            def _chunk_dispatch():
                self._maybe_inject_fault()
                return self._run_chunk_program(tokens, lens, offs,
                                               table)

            try:
                nxt = with_retries(
                    _chunk_dispatch, policy=policy,
                    deadline=r.deadline,
                    on_retry=lambda exc, n, delay:
                        self.metrics.incr("retries_total"))
            except BaseException as exc:  # noqa: BLE001 — forwarded
                if self.breaker.record_failure():
                    self.metrics.incr("breaker_open_total")
                    self.health.to(HealthState.DEGRADED)
                self.metrics.incr("errors_total")
                self._fail_chunk_job(idx, exc)
                progressed = True
                continue
            self.breaker.record_success()
            self.metrics.incr("chunk_prefill_total")
            job.off += int(sl.size)
            progressed = True
            if job.off >= r.prompt.size:
                with self._slots_lock:
                    self._chunk_jobs.pop(idx, None)
                self._install_first_token(r, job.pages, job.table,
                                          int(nxt[0]), idx)
        return progressed

    def _active(self):
        return [(i, s) for i, s in enumerate(self.slots)
                if s is not None]

    def _step(self, policy):
        """One decode (or speculative) dispatch over the full slot
        array; per-row bookkeeping afterwards. A terminal dispatch
        failure fails every active request (and trips the breaker),
        never the worker."""
        active = self._active()
        if not active:
            return False
        now = time.monotonic()
        for i, slot in list(active):
            if slot.req.deadline is not None \
                    and now >= slot.req.deadline:
                self.metrics.incr("timeouts_total")
                self._retire(i, error=RequestTimeoutError(
                    "request deadline expired mid-generation"))
        active = self._active()
        if not active:
            return True
        c = self.config
        B = c.max_batch
        toks = np.zeros((B,), np.int64)
        prev = np.zeros((B,), np.int64)
        pos = np.ones((B,), np.int32)
        table = np.zeros((B, self.pages_per_seq), np.int32)
        for i, slot in active:
            toks[i] = slot.cur
            prev[i] = slot.prev
            pos[i] = slot.pos
            table[i] = slot.table
        deadlines = [s.req.deadline for _, s in active
                     if s.req.deadline is not None]
        batch_deadline = min(deadlines) if deadlines else None
        # brownout level >= 2 runs the (warmed) plain decode program
        # instead of the spec step: exact greedy output either way —
        # verification pins spec to target-greedy parity — so the
        # switch trades draft speedup for target-model load, never
        # numerics. Stale draft KV across the gap only lowers
        # acceptance after revert; it cannot change tokens.
        use_spec = self.draft_cfg is not None
        if use_spec and self.brownout is not None \
                and self.brownout.active("spec_off"):
            use_spec = False
            self.metrics.incr("brownout_spec_off_total")

        def _step_dispatch():
            self._maybe_inject_fault()
            if not use_spec:
                return self._run_decode_program(toks, pos, table)
            return self._run_spec_program(toks, prev, pos, table)

        try:
            result = with_retries(
                _step_dispatch, policy=policy, deadline=batch_deadline,
                on_retry=lambda exc, n, delay:
                    self.metrics.incr("retries_total"))
            if not use_spec:
                out = result
            else:
                emitted, accepted = result
        except BaseException as exc:     # noqa: BLE001 — forwarded
            if self.breaker.record_failure():
                self.metrics.incr("breaker_open_total")
                self.health.to(HealthState.DEGRADED)
            self.metrics.incr("errors_total", len(active))
            for i, _ in active:
                self._retire(i, error=exc)
            return True
        self.breaker.record_success()
        if self.health.state == HealthState.DEGRADED:
            self.health.to(HealthState.READY)
        self.metrics.incr("decode_batches_total")
        draining = self._closed and not self._stop.is_set()
        eos = c.eos_id
        n_new = 0
        if not use_spec:
            for i, slot in active:
                row = out[i]
                taken, done = self._truncate(slot, row)
                slot.emitted.extend(taken)
                n_new += len(taken)
                slot.pos += len(row)
                slot.cur = int(row[-1])
                slot.prev = int(row[-2]) if len(row) >= 2 \
                    else int(toks[i])
                if done:
                    self._retire(i, draining=draining)
        else:
            self.metrics.incr("spec_rounds_total", len(active))
            for i, slot in active:
                a = int(accepted[i])
                row = emitted[i]
                self.metrics.incr("spec_tokens_accepted_total", a)
                taken, done = self._truncate(slot, row[:a])
                slot.emitted.extend(taken)
                n_new += len(taken)
                old_cur = slot.cur
                slot.pos += a
                slot.cur = int(row[a - 1])
                slot.prev = int(row[a - 2]) if a >= 2 else old_cur
                if done:
                    self._retire(i, draining=draining)
        self.metrics.incr("generated_tokens_total", n_new)
        return True

    def _truncate(self, slot, row):
        """The slice of freshly generated ``row`` this slot actually
        keeps: cut at eos_id (inclusive) and at the request's max_new.
        Returns (tokens, done)."""
        eos = self.config.eos_id
        row = [int(t) for t in row]
        if eos is not None and eos in row:
            row = row[:row.index(eos) + 1]
        room = slot.req.max_new - len(slot.emitted)
        done = (len(row) >= room
                or (eos is not None and row and row[-1] == eos))
        return row[:room], done

    # -- worker / watchdog -----------------------------------------------
    def _worker_loop(self):
        policy = self.config.retry_policy or default_policy()
        while not self._stop.is_set():
            # the crash point is consumed only while this engine has
            # work: fires() advances a process-global clock, so an IDLE
            # engine polling the point (a drained fixture, a spare pool
            # replica) would otherwise steal a fire armed against the
            # loaded engine under test
            if self._crash.is_set() or (
                    self._has_work()
                    and _faultinject.fires("serving_worker_crash")):
                return   # models SIGKILL — the watchdog's job
            self.health.beat()
            moved = self._update_brownout()
            swept = self._sweep_expired()
            admitted = self._admit(policy)
            chunked = self._step_chunks(policy)
            stepped = self._step(policy)
            if self._closed and not self._has_work():
                break    # drain complete
            if not (admitted or chunked or stepped or swept or moved):
                with self._cv:
                    if not self._queue and not self._closed:
                        self._cv.wait(0.02)
        for req in self._take_pending():
            req.set_error(ServerClosedError("engine closed"))

    def _watchdog_loop(self):
        while not self._watchdog_stop.wait(
                self.config.watchdog_interval_s):
            if self._stop.is_set() or self._closed:
                continue
            worker = self._worker
            if worker is None:
                continue
            if not worker.is_alive():
                self._on_worker_dead("decode worker thread died")
                continue
            age = self.health.heartbeat_age()
            hang = self.config.hang_timeout_s
            if hang and age is not None and age > hang:
                self._on_worker_dead(
                    f"decode worker heartbeat stalled {age:.1f}s "
                    f"(hang timeout {hang:g}s) — worker is stuck")

    def _on_worker_dead(self, reason):
        if not self._worker_death_seen:
            self._worker_death_seen = True
            self.metrics.incr("worker_died_total")
            self.health.to(HealthState.DEGRADED)
        for req in self._take_pending():
            req.set_error(WorkerDiedError(reason))
