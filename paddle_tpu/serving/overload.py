"""Overload control: adaptive admission, brownout ladder, retry budget.

Every overload path used to be binary — a fixed ``max_cluster_queue``
and a flat ``QueueFullError`` treated a batch scrape and a user-facing
decode stream identically, and nothing stopped failover/redrive
traffic from amplifying the very overload that triggered it. This
module makes degradation deliberate (the production-dataflow move of
arXiv:1605.08695): three small, clock-injectable controllers that the
router and the decode engine wire in, each unit-testable on a fake
clock with no threads and no XLA (tests/test_overload.py).

- :class:`AdmissionController` — AIMD on observed request sojourn vs.
  a delay target. The admitted-outstanding limit grows additively
  while sojourn is under target and cuts multiplicatively when it is
  over, so the admitted rate tracks actual capacity instead of a
  hand-tuned constant. Priority tiers see DIFFERENT effective limits
  (batch a fraction of the limit, standard a larger one, interactive
  the hard ceiling itself), which is what makes shed ordering strict:
  as load rises past capacity, batch hits its ceiling first, then
  standard, and interactive sheds only where the old fixed bound
  would have shed it. The configured hard ceiling always binds.

- :class:`BrownoutController` — a pressure signal in [0, 1] (max of
  normalized queue delay, breaker state, page-pool occupancy) drives
  an explicit degradation ladder with hysteresis: level 1 caps
  batch-tier ``max_new``, level 2 disables speculative decoding,
  level 3 shrinks chunked-prefill admission. Each engage/revert is
  counted, and every step fully reverts on recovery — brownout trades
  work for admission, never numerics.

- :class:`RetryBudget` — a token bucket bounding cluster-wide retry /
  redrive / hedge amplification. Each retry takes a token; each
  success refills a configured fraction of one; an empty bucket makes
  retries fail fast with :class:`RetryBudgetExhaustedError` instead
  of storming a pool that is already down. Hedged requests draw from
  the same bucket, so tail-cutting duplicates can never become the
  storm themselves.

See docs/RELIABILITY.md "Operating at the overload knee".
"""
import threading
import time

from .health import ServiceUnavailableError
from .sched import PRIORITIES

__all__ = ["AdmissionController", "BrownoutController", "RetryBudget",
           "RetryBudgetExhaustedError", "BROWNOUT_STEPS",
           "shed_counter"]


class RetryBudgetExhaustedError(ServiceUnavailableError):
    """The cluster-wide retry budget is spent: this retry/redrive/
    hedge would amplify an overload, so it fails fast instead. Typed
    as unavailability (back off, don't resubmit immediately) — the
    ORIGINAL attempt's error is chained as ``__cause__``."""


# Per-tier admission fractions: the effective outstanding limit each
# priority admits against, as a fraction of the AIMD limit. Batch
# saturates first (sheds first), then standard; INTERACTIVE bypasses
# the adaptive limit entirely and admits up to the hard ceiling — the
# AIMD loop protects latency by throttling the lower tiers, and
# interactive traffic sheds only where the old fixed bound would have
# shed it. That is the strict ordering the overload drill asserts on.
_TIER_FRACTION = {0: 1.0, 1: 0.85, 2: 0.6}


class AdmissionController:
    """AIMD admission over observed request sojourn.

    ``admit(rank, outstanding)`` answers "may a request of this
    priority enter with this many already outstanding?" against
    ``limit * fraction(rank)``. ``observe(sojourn_s)`` feeds completed
    requests' wall time (submit → settle) into an EWMA; once per
    ``interval_s`` the limit adapts: additive increase (+``add_step``)
    while the EWMA is under ``target_delay_s``, multiplicative
    decrease (×``decrease``) when it is over. The limit lives in
    [``min_limit``, ``hard_ceiling``]; the ceiling is the old fixed
    bound and always binds.

    Thread-safe; ``clock`` is injectable for fake-clock units."""

    def __init__(self, hard_ceiling, target_delay_s=0.5,
                 min_limit=4, start_limit=None, add_step=1.0,
                 decrease=0.7, interval_s=0.25, ewma_alpha=0.3,
                 clock=None):
        if hard_ceiling is None or int(hard_ceiling) < 1:
            raise ValueError("hard_ceiling must be a positive int "
                             "(the fixed bound stays as the ceiling)")
        self.hard_ceiling = int(hard_ceiling)
        self.target_delay_s = float(target_delay_s)
        self.min_limit = max(1, int(min_limit))
        self.add_step = float(add_step)
        self.decrease = float(decrease)
        if not (0.0 < self.decrease < 1.0):
            raise ValueError("decrease must be in (0, 1)")
        self.interval_s = float(interval_s)
        self.ewma_alpha = float(ewma_alpha)
        self.clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._limit = float(min(self.hard_ceiling,
                                self.hard_ceiling
                                if start_limit is None
                                else max(self.min_limit,
                                         int(start_limit))))
        self._ewma = None               # observed sojourn EWMA, s
        self._last_adapt = self.clock()
        self._admitted_total = 0
        self._refused_total = 0

    def observe(self, sojourn_s):
        """Feed one completed request's sojourn (seconds, submit →
        settle) and adapt the limit if an interval elapsed."""
        s = float(sojourn_s)
        if not (s == s) or s < 0:       # NaN / negative: drop
            return
        now = self.clock()
        with self._lock:
            self._ewma = (s if self._ewma is None
                          else self.ewma_alpha * s
                          + (1.0 - self.ewma_alpha) * self._ewma)
            if now - self._last_adapt < self.interval_s:
                return
            self._last_adapt = now
            if self._ewma > self.target_delay_s:
                self._limit = max(float(self.min_limit),
                                  self._limit * self.decrease)
            else:
                self._limit = min(float(self.hard_ceiling),
                                  self._limit + self.add_step)

    def limit(self):
        with self._lock:
            return self._limit

    def admit(self, rank, outstanding):
        """True if a request of priority ``rank`` may enter with
        ``outstanding`` requests already in flight pool-wide.
        Interactive (rank 0) admits against the hard ceiling itself;
        lower tiers admit against their fraction of the AIMD limit."""
        rank = int(rank)
        frac = _TIER_FRACTION.get(rank, _TIER_FRACTION[2])
        with self._lock:
            if rank <= PRIORITIES["interactive"]:
                eff = float(self.hard_ceiling)
            else:
                eff = min(self._limit * frac, float(self.hard_ceiling))
            ok = outstanding < max(1.0, eff)
            if ok:
                self._admitted_total += 1
            else:
                self._refused_total += 1
            return ok

    def snapshot(self):
        with self._lock:
            return {"limit": self._limit,
                    "hard_ceiling": self.hard_ceiling,
                    "target_delay_s": self.target_delay_s,
                    "sojourn_ewma_s": self._ewma,
                    "admitted_total": self._admitted_total,
                    "refused_total": self._refused_total,
                    "tier_fractions": dict(_TIER_FRACTION)}


# The brownout ladder, mildest first. Step N engages when pressure
# holds above engage_at; everything reverts (in reverse order) as
# pressure falls below revert_at. Names key the brownout_* counters.
BROWNOUT_STEPS = ("cap_batch_max_new", "spec_off", "chunk_shrink")


class BrownoutController:
    """Pressure-driven degradation ladder with hysteresis.

    ``update(pressure)`` takes the current pressure signal in [0, 1]
    (the engine computes it as the max of normalized queue delay,
    breaker-open, and page-pool occupancy) and moves the level at most
    ONE step per call: up when pressure >= ``engage_at`` and the level
    has dwelled ``dwell_s``, down when pressure <= ``revert_at`` (the
    gap between the two thresholds is the hysteresis band that stops
    flapping). Levels mean: 0 = off, 1 = cap batch-tier ``max_new``,
    2 = +speculative decoding off, 3 = +chunked-prefill admission
    shrunk to one slice per iteration. ``active(step)`` answers
    whether a named step currently applies.

    The controller only decides the level; the ENGINE applies and
    reverts the effects and counts them (``brownout_engage_total`` /
    ``brownout_revert_total`` / per-step counters). Clock-injectable,
    thread-safe."""

    max_level = len(BROWNOUT_STEPS)

    def __init__(self, engage_at=0.85, revert_at=0.5, dwell_s=0.1,
                 clock=None):
        if not (0.0 <= revert_at < engage_at <= 1.0):
            raise ValueError("need 0 <= revert_at < engage_at <= 1 "
                             "(the hysteresis band)")
        self.engage_at = float(engage_at)
        self.revert_at = float(revert_at)
        self.dwell_s = float(dwell_s)
        self.clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._level = 0
        self._since = self.clock()
        self._pressure = 0.0

    def update(self, pressure):
        """Feed the current pressure; returns (old_level, new_level).
        Moves at most one rung per call."""
        p = min(1.0, max(0.0, float(pressure)))
        now = self.clock()
        with self._lock:
            self._pressure = p
            old = self._level
            dwelled = (now - self._since) >= self.dwell_s
            if p >= self.engage_at and dwelled \
                    and self._level < self.max_level:
                self._level += 1
                self._since = now
            elif p <= self.revert_at and dwelled and self._level > 0:
                self._level -= 1
                self._since = now
            return old, self._level

    def level(self):
        with self._lock:
            return self._level

    def pressure(self):
        with self._lock:
            return self._pressure

    def active(self, step):
        """Whether the named ladder step currently applies."""
        try:
            rung = BROWNOUT_STEPS.index(step) + 1
        except ValueError:
            raise ValueError(f"unknown brownout step {step!r}; one "
                             f"of {BROWNOUT_STEPS}") from None
        with self._lock:
            return self._level >= rung

    def snapshot(self):
        with self._lock:
            return {"level": self._level,
                    "pressure": self._pressure,
                    "engage_at": self.engage_at,
                    "revert_at": self.revert_at,
                    "steps": list(BROWNOUT_STEPS)}


class RetryBudget:
    """Cluster-wide retry token bucket.

    Starts full at ``capacity`` tokens. Every retry/redrive/hedge
    calls :meth:`acquire` — True consumes one token, False means the
    budget is spent and the caller must fail fast (the router raises
    :class:`RetryBudgetExhaustedError`). Every SUCCESS (first try or
    retried) calls :meth:`note_success`, refilling ``refill_ratio``
    of a token — so sustained retry traffic is bounded at roughly
    ``refill_ratio`` of goodput, the classic retry-budget contract:
    a healthy pool earns its retries back, a down pool cannot storm
    itself. Thread-safe."""

    def __init__(self, capacity=16, refill_ratio=0.1):
        self.capacity = float(capacity)
        if self.capacity <= 0:
            raise ValueError("capacity must be positive")
        self.refill_ratio = float(refill_ratio)
        if not (0.0 <= self.refill_ratio <= 1.0):
            raise ValueError("refill_ratio must be in [0, 1]")
        self._lock = threading.Lock()
        self._tokens = self.capacity
        self._acquired_total = 0
        self._exhausted_total = 0

    def acquire(self):
        """Take one retry token; False = budget spent, fail fast."""
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self._acquired_total += 1
                return True
            self._exhausted_total += 1
            return False

    def note_success(self):
        """A request succeeded: earn back a fraction of a token."""
        with self._lock:
            self._tokens = min(self.capacity,
                               self._tokens + self.refill_ratio)

    def tokens(self):
        with self._lock:
            return self._tokens

    def snapshot(self):
        with self._lock:
            return {"tokens": self._tokens,
                    "capacity": self.capacity,
                    "refill_ratio": self.refill_ratio,
                    "acquired_total": self._acquired_total,
                    "exhausted_total": self._exhausted_total}


def shed_counter(rank):
    """The per-class shed counter name for a priority rank — one
    vocabulary across engine, pool, and metrics merge."""
    for name, r in PRIORITIES.items():
        if r == int(rank):
            return f"shed_{name}_total"
    return "shed_standard_total"
