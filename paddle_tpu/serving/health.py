"""Serving health machinery: liveness states, circuit breaker, typed
failure errors.

Large-scale serving treats failure as the steady state (the TF design
axis — Abadi et al., 2016): a server is not "up or down" but somewhere
on STARTING → READY → DEGRADED → DRAINING → STOPPED, and every failure
mode must map to a *defined* behavior a client can program against.
This module is the pure-policy half of that story (no threads, no
executor — deterministic under an injectable clock, like batching.py):

- :class:`HealthState` / :class:`HealthMonitor` — the engine's
  liveness state machine plus the worker heartbeat the watchdog reads.
  The worker beats once per loop iteration; a stalled heartbeat or a
  dead thread is the watchdog's signal to fail pending requests with
  :class:`WorkerDiedError` instead of letting callers sit on their
  grace bound.
- :class:`CircuitBreaker` — the classic closed → open → half-open
  cycle over *consecutive* batch failures. While open, work is shed
  immediately with :class:`ServiceUnavailableError` (fail fast beats
  queueing into a known-bad device); after ``cooldown_s`` one probe
  batch is let through, and its outcome closes or re-opens the
  breaker. The engine keeps one breaker for itself and one per bucket
  signature, so a single poisoned shape cannot black-hole the whole
  server.

Thread-safety: every method takes the instance lock; the engine calls
in from the submit path, the worker, and the watchdog concurrently.
"""
import threading
import time

from .batching import ServingError

__all__ = ["HealthState", "HealthMonitor", "CircuitBreaker",
           "WorkerDiedError", "ServiceUnavailableError",
           "SERVING_STATE_RANK", "serving_rank"]


class WorkerDiedError(ServingError):
    """The serving worker thread is dead or stalled; this request will
    never be served by it. Distinct from RequestTimeoutError (the
    request was viable, the clock ran out) — a dead worker means the
    whole engine needs a restart, not the request a retry."""


class ServiceUnavailableError(ServingError):
    """Shed by an open circuit breaker: the engine (or this request's
    bucket) is in a known-failing state and refuses work instead of
    burning compute on it. Back off at least the breaker cooldown
    before retrying."""


class HealthState:
    """The serving lifecycle, ordered. String constants (not enum) so
    ``stats()`` snapshots stay plain-JSON."""

    STARTING = "STARTING"    # constructed, worker not yet taking work
    READY = "READY"          # worker up, admission open
    DEGRADED = "DEGRADED"    # serving impaired: breaker open or worker dead
    DRAINING = "DRAINING"    # admission closed, finishing queued work
    STOPPED = "STOPPED"      # worker joined, engine finished

    ALL = (STARTING, READY, DEGRADED, DRAINING, STOPPED)


# serving states ranked best-first for traffic placement; states absent
# from the map are NOT candidates. One vocabulary shared by the cluster
# router's health-aware balancing and the membership view, so "which
# tier is this replica in" has exactly one answer — local engine,
# pipe-backed process, or socket-backed remote host alike.
SERVING_STATE_RANK = {HealthState.READY: 0, HealthState.DEGRADED: 1}


def serving_rank(state):
    """Best-first placement rank for a health state, or None when the
    state must not take traffic (STARTING/DRAINING/STOPPED)."""
    return SERVING_STATE_RANK.get(state)


class HealthMonitor:
    """State holder + worker heartbeat for one engine.

    ``beat()`` is called by the worker once per loop iteration (cheap:
    one lock + one float store). ``heartbeat_age()`` is what the
    watchdog compares against the hang timeout — None before the first
    beat, so a never-started worker reads as "no heartbeat" rather
    than "infinitely stale"."""

    def __init__(self, clock=None):
        self.clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._state = HealthState.STARTING
        self._last_beat = None

    @property
    def state(self):
        with self._lock:
            return self._state

    def to(self, state):
        if state not in HealthState.ALL:
            raise ValueError(f"unknown health state {state!r}; one of "
                             f"{HealthState.ALL}")
        with self._lock:
            prev, self._state = self._state, state
            return prev

    def beat(self):
        with self._lock:
            self._last_beat = self.clock()

    def heartbeat_age(self):
        with self._lock:
            if self._last_beat is None:
                return None
            return self.clock() - self._last_beat


class CircuitBreaker:
    """Consecutive-failure breaker: closed → open → half-open → closed.

    ``failure_threshold`` consecutive failures open the breaker (one
    success resets the count — a flapping device never accumulates to
    open). While open, :meth:`admits` is False until ``cooldown_s`` has
    elapsed; the first :meth:`allow` after the cooldown transitions to
    half-open and lets exactly that caller's batch through as the
    probe. :meth:`record_success` closes, :meth:`record_failure`
    re-opens with a fresh cooldown.

    Two read points by design: ``admits()`` is the *read-only* check
    the submit path uses to shed early (it never changes state — state
    transitions belong to the worker, the single dispatcher), while
    ``allow()`` is the dispatch-side check that performs the
    open → half-open transition."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(self, failure_threshold=5, cooldown_s=1.0, clock=None):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._opened_at = None
        self._opens_total = 0

    @property
    def state(self):
        with self._lock:
            return self._state

    @property
    def opens_total(self):
        with self._lock:
            return self._opens_total

    def _cooled_down(self, now):
        return (self._opened_at is not None
                and now - self._opened_at >= self.cooldown_s)

    def admits(self, now=None):
        """Read-only: would a new request be accepted right now? False
        only while open with the cooldown still running."""
        with self._lock:
            if self._state != self.OPEN:
                return True
            return self._cooled_down(self.clock() if now is None else now)

    def allow(self):
        """Dispatch-side gate. Closed/half-open pass; open passes only
        once the cooldown elapsed, transitioning to half-open — the
        caller's batch is the probe and MUST report its outcome via
        record_success/record_failure."""
        with self._lock:
            if self._state != self.OPEN:
                return True
            if self._cooled_down(self.clock()):
                self._state = self.HALF_OPEN
                return True
            return False

    def record_success(self):
        with self._lock:
            self._consecutive_failures = 0
            self._opened_at = None
            self._state = self.CLOSED

    def record_failure(self):
        """Count one terminal batch failure (post-retry). Returns True
        iff this failure OPENED the breaker (edge, not level — the
        caller counts opens and flips health on the edge)."""
        with self._lock:
            self._consecutive_failures += 1
            if self._state == self.HALF_OPEN or (
                    self._state == self.CLOSED
                    and self._consecutive_failures
                    >= self.failure_threshold):
                self._state = self.OPEN
                self._opened_at = self.clock()
                self._opens_total += 1
                return True
            return False

    def snapshot(self):
        """Plain-dict state for ``stats()``."""
        with self._lock:
            return {"state": self._state,
                    "consecutive_failures": self._consecutive_failures,
                    "opens_total": self._opens_total,
                    "cooldown_s": self.cooldown_s,
                    "failure_threshold": self.failure_threshold}
