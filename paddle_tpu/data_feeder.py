"""DataFeeder — converts python minibatch data into feed dicts.

Parity with python/paddle/fluid/data_feeder.py: takes a list of feed
Variables; ``feed(batch_of_rows)`` transposes row-major reader output
into per-variable arrays. Variables with ``lod_level > 0`` become
SequenceBatch (padded + lengths) instead of LoDTensor.
"""
import numpy as np

from .core import framework
from .core.sequence import to_sequence_batch

__all__ = ["DataFeeder"]


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_vars = []
        program = program or framework.default_main_program()
        for v in feed_list:
            if isinstance(v, str):
                v = program.global_block().var(v)
            self.feed_vars.append(v)
        self.place = place

    def feed(self, iterable):
        rows = list(iterable)
        feed = {}
        for i, var in enumerate(self.feed_vars):
            col = [r[i] for r in rows]
            if var.lod_level == 2:
                # rows carry lists of subsequences (2-level LoD)
                from .core.sequence import to_nested_sequence_batch
                feed[var.name] = to_nested_sequence_batch(
                    col, dtype=np.dtype(var.dtype))
            elif var.lod_level > 0:
                feed[var.name] = to_sequence_batch(
                    col, dtype=np.dtype(var.dtype))
            else:
                arr = np.asarray(col, dtype=np.dtype(var.dtype))
                want = [s for s in var.shape if s != -1]
                if list(arr.shape[1:]) != want and want:
                    arr = arr.reshape([arr.shape[0]] + want)
                feed[var.name] = arr
        return feed
