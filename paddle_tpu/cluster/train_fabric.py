"""Elastic fault-tolerant data-parallel training over the serving
fabric — the training half of ROADMAP item 1.

PR 11 gave *serving* a cross-host socket fabric (CRC frames, typed
transport errors, breakers, membership). This module lifts *training*
onto the same wire: a :class:`TrainCoordinator` (one process, the
parameter-server role of Paddle's distribute transpiler — PAPER.md §1)
drives N :class:`~paddle_tpu.cluster.train_worker.TrainWorkerServer`
hosts through a step-synchronized loop, and every failure mode is a
*typed, recoverable* event instead of a lost run:

- **heartbeat-missed / straggler-deadline** → the worker is evicted
  and the step is retried at reduced world size (elastic down);
- **rejoin / replacement** → a host cold-provisions its compiled
  ``__artifacts__`` over the wire from any live peer (PR 11
  ``provision_from_remote`` — zero XLA compiles), catches up from the
  last committed state, and is folded back into the shard assignment
  (elastic up);
- **coordinator crash** → workers park at the barrier under a
  deadline; a new coordinator resumes from the last committed
  checkpoint serial and the run continues.

Determinism is the load-bearing design decision: the global batch of
every step is cut into a FIXED number of logical shards
(``n_shards``), workers return per-shard gradient *sums*, and the
coordinator reduces them in shard-index order before applying the
update. The math of step S is therefore a pure function of
(committed state at S-1, S, the data) — independent of world size,
shard→worker assignment, evictions, or which host died — so crash
resume is bit-deterministic: same params sha at step S as an
uninterrupted run (``tools/trainbench.py --chaos`` gates exactly
this).

Commit discipline: every ``commit_interval`` steps the coordinator
writes the state through the crash-safe store
(``resilience/checkpoint.py`` — temp → fsync → rename, per-array
sha256 manifest, leader-only pruning under ``PADDLE_TPU_CKPT_KEEP``)
and broadcasts ``(step, state, sha)`` to every live worker, which
re-hashes and VERIFIES the sha (leader-writes / followers-verify). A
kill -9 of any worker — or the coordinator — mid-step never loses a
committed step; at worst the uncommitted tail is recomputed, to the
same bits.

Wire verbs (cluster/net.py frames, after the hello/welcome
handshake)::

    {"type": "train_configure", "id": n, "task": {...spec...}}
        -> {"type": "train_configured", "id": n, "name": ...,
            "total_compiles": c}
    {"type": "train_step", "id": n, "step": s, "state": {...},
     "shards": [ids], "n_shards": k}
        -> {"type": "train_grads", "id": n, "step": s,
            "shards": {id: {"loss_sum": f, "n_rows": r,
                            "grads": {name: array}}}}
    {"type": "train_commit", "id": n, "step": s, "serial": s,
     "state": {...}, "sha": hex}
        -> {"type": "train_committed", "id": n, "ok": bool,
            "sha": worker_sha}
    {"type": "stats"/"ping"/"fetch_manifest"/"fetch_artifact"/"bye"}
        — identical to the serving fabric (provisioning included).

Fault points (``resilience/faultinject.py``): the worker-side step
handler checks ``trainer_crash_at_step`` (hard death) and
``trainer_straggle`` (stall past the straggler deadline) and marks
``train_step`` progress events; the coordinator's RPC path checks
``train_net_partition`` and its step loop ``coordinator_crash`` —
all four ride the PR 16 event-barrier discipline so chaos drills are
deterministic on any host.
"""
import os
import threading
import time

import numpy as np

from ..resilience import checkpoint as _ckpt
from ..resilience import faultinject as _faultinject
from ..serving.health import (HealthState, ServiceUnavailableError)
from ..serving.metrics import ServingMetrics
from . import net
from .membership import Membership

__all__ = ["TrainTaskError", "NoTrainWorkersError", "CommitMismatch",
           "LinRegTask", "ProgramGradTask", "task_from_spec",
           "WorkerClient", "TrainCoordinator"]

_STRAGGLE_ENV = "PADDLE_TPU_FAULT_STRAGGLE_S"


class TrainTaskError(ValueError):
    """A task spec is malformed or names an unknown task kind."""


class NoTrainWorkersError(ServiceUnavailableError):
    """Every worker is evicted/unreachable and the admit deadline
    expired — the step cannot run at ANY world size. IS-A
    ServiceUnavailableError so fleet tooling treats it like an
    unservable cluster, not a crash."""


class CommitMismatch(_ckpt.CheckpointError):
    """A follower's re-hash of the committed state disagreed with the
    leader's manifest sha — bitwise divergence, the one thing the
    fabric must never paper over."""


# TrainTaskError is raised worker-side (task-spec validation) and
# forwarded as a wire pair; without registration it would re-raise on
# the coordinator as a bare ServingError and the typed-refusal tests
# would pass only in-process
net.register_wire_error(TrainTaskError)
net.register_wire_error(NoTrainWorkersError)


# ---------------------------------------------------------------------------
# tasks — the unit of work the fleet agrees on
# ---------------------------------------------------------------------------
#
# A task is the deterministic triple the coordinator and every worker
# rebuild from one wire-safe spec dict (plain containers only — it
# travels inside a restricted-unpickle frame):
#
#   init_state()                          -> {name: np.ndarray}
#   grad_sums(state, step, shard, n)      -> (loss_sum, {name: gsum}, rows)
#   apply(state, gsums, n_rows, step)     -> new state      (coordinator)
#
# grad_sums returns per-shard SUMS (not means): the coordinator adds
# shards in shard-index order and divides once, so the reduction is
# bit-identical however shards are assigned to workers.


class LinRegTask:
    """Analytic linear regression on deterministic synthetic data —
    pure numpy, zero compiles, sub-millisecond steps. The unit-test
    and faultsmoke task: every fabric behavior (barrier, eviction,
    commit, resume) is exercised without jax in the loop."""

    kind = "linreg"

    def __init__(self, dim=8, rows_per_shard=4, lr=0.1, seed=0):
        self.dim = int(dim)
        self.rows_per_shard = int(rows_per_shard)
        self.lr = float(lr)
        self.seed = int(seed)
        rng = np.random.RandomState(self.seed)
        self._w_true = rng.standard_normal(self.dim).astype(np.float32)

    def spec(self):
        return {"kind": self.kind, "dim": self.dim,
                "rows_per_shard": self.rows_per_shard,
                "lr": self.lr, "seed": self.seed}

    @classmethod
    def from_spec(cls, spec):
        return cls(dim=spec.get("dim", 8),
                   rows_per_shard=spec.get("rows_per_shard", 4),
                   lr=spec.get("lr", 0.1), seed=spec.get("seed", 0))

    def init_state(self):
        return {"w": np.zeros(self.dim, np.float32)}

    def _shard_data(self, step, shard):
        rng = np.random.RandomState(
            self.seed + 100003 * (step + 1) + shard)
        x = rng.standard_normal(
            (self.rows_per_shard, self.dim)).astype(np.float32)
        y = (x @ self._w_true).astype(np.float32)
        return x, y

    def grad_sums(self, state, step, shard, n_shards):
        x, y = self._shard_data(step, shard)
        err = (x @ state["w"] - y).astype(np.float32)
        loss_sum = float(np.sum(err.astype(np.float64) ** 2))
        g = (2.0 * x.T @ err).astype(np.float32)
        return loss_sum, {"w": g}, self.rows_per_shard

    def apply(self, state, gsums, n_rows, step):
        w = state["w"] - np.float32(self.lr) * (
            gsums["w"] / np.float32(n_rows))
        return {"w": w.astype(np.float32)}

    def total_compiles(self):
        return 0


class ProgramGradTask:
    """A real fluid train program split pserver-style: the worker runs
    forward + ``append_backward`` and fetches per-shard gradient sums
    through the Executor (artifact store attached, so a provisioned
    host replays the compiled step with ZERO XLA compiles); the
    coordinator applies the SGD update in deterministic host numpy.

    The program — data → fc(tanh) → fc → square_error_cost → mean —
    is rebuilt from the spec on every host; the PR 9 canonical
    program hash makes the artifact keys match across processes, which
    is what cold wire-provisioning relies on."""

    kind = "program"

    def __init__(self, dim=8, hidden=8, rows_per_shard=4, lr=0.05,
                 seed=0, artifact_dir=None):
        self.dim = int(dim)
        self.hidden = int(hidden)
        self.rows_per_shard = int(rows_per_shard)
        self.lr = float(lr)
        self.seed = int(seed)
        self.artifact_dir = artifact_dir
        self._built = None      # lazy: the coordinator never compiles

    def spec(self):
        # artifact_dir is deliberately host-local (CLI/ctor), never
        # part of the wire spec — the math is shared, the cache is not
        return {"kind": self.kind, "dim": self.dim,
                "hidden": self.hidden,
                "rows_per_shard": self.rows_per_shard,
                "lr": self.lr, "seed": self.seed}

    @classmethod
    def from_spec(cls, spec, artifact_dir=None):
        return cls(dim=spec.get("dim", 8), hidden=spec.get("hidden", 8),
                   rows_per_shard=spec.get("rows_per_shard", 4),
                   lr=spec.get("lr", 0.05), seed=spec.get("seed", 0),
                   artifact_dir=artifact_dir)

    def _build(self):
        if self._built is not None:
            return self._built
        from ..core import framework
        from ..core.backward import append_backward
        from ..core.executor import Executor, Scope, TPUPlace
        from .. import layers
        main, startup = framework.Program(), framework.Program()
        with framework.program_guard(main, startup), \
                framework.unique_name.guard():
            x = layers.data(name="x", shape=[self.dim],
                            dtype="float32")
            y = layers.data(name="y", shape=[1], dtype="float32")
            h = layers.fc(input=x, size=self.hidden, act="tanh")
            pred = layers.fc(input=h, size=1)
            loss = layers.mean(layers.square_error_cost(
                input=pred, label=y))
            params_grads = append_backward(loss)
        exe = Executor(TPUPlace(), donate_state=False,
                       compile_store=self.artifact_dir)
        self._built = {
            "main": main, "loss": loss,
            "params_grads": [(p.name, g) for p, g in params_grads],
            "exe": exe, "scope": Scope(),
        }
        return self._built

    def param_shapes(self):
        b = self._build()
        gb = b["main"].global_block()
        return {name: tuple(int(d) for d in gb.var(name).shape)
                for name, _g in b["params_grads"]}

    def init_state(self):
        shapes = self.param_shapes()
        rng = np.random.RandomState(self.seed)
        return {name: (rng.standard_normal(shapes[name]) * 0.1
                       ).astype(np.float32)
                for name in sorted(shapes)}

    def _shard_data(self, step, shard):
        rng = np.random.RandomState(
            self.seed + 100003 * (step + 1) + shard)
        x = rng.standard_normal(
            (self.rows_per_shard, self.dim)).astype(np.float32)
        y = np.tanh(x.sum(axis=1, keepdims=True)).astype(np.float32)
        return x, y

    def grad_sums(self, state, step, shard, n_shards):
        b = self._build()
        for name, value in state.items():
            b["scope"].set(name, np.asarray(value))
        x, y = self._shard_data(step, shard)
        fetch = [b["loss"]] + [g for _n, g in b["params_grads"]]
        outs = b["exe"].run(b["main"], feed={"x": x, "y": y},
                            fetch_list=fetch, scope=b["scope"])
        rows = self.rows_per_shard
        loss_sum = float(np.asarray(outs[0])) * rows
        gsums = {name: np.asarray(g, np.float32) * np.float32(rows)
                 for (name, _gv), g in zip(b["params_grads"],
                                           outs[1:])}
        return loss_sum, gsums, rows

    def apply(self, state, gsums, n_rows, step):
        inv = np.float32(1.0 / n_rows)
        lr = np.float32(self.lr)
        return {name: (np.asarray(state[name], np.float32)
                       - lr * gsums[name] * inv).astype(np.float32)
                for name in sorted(state)}

    def total_compiles(self):
        if self._built is None:
            return 0
        return self._built["exe"].total_compiles()


_TASK_KINDS = {"linreg": LinRegTask, "program": ProgramGradTask}


def task_from_spec(spec, artifact_dir=None):
    """Rebuild a task from its wire spec (the worker side of
    ``train_configure``). Raises :class:`TrainTaskError` on anything
    malformed — a typed refusal, never an import or KeyError."""
    if not isinstance(spec, dict) or "kind" not in spec:
        raise TrainTaskError(f"malformed task spec: {spec!r}")
    cls = _TASK_KINDS.get(spec["kind"])
    if cls is None:
        raise TrainTaskError(
            f"unknown task kind {spec['kind']!r}; "
            f"known: {sorted(_TASK_KINDS)}")
    if cls is ProgramGradTask:
        return cls.from_spec(spec, artifact_dir=artifact_dir)
    return cls.from_spec(spec)


# ---------------------------------------------------------------------------
# WorkerClient — the coordinator's handle to one worker host
# ---------------------------------------------------------------------------


class WorkerClient:
    """Synchronous deadline-bounded RPC to one TrainWorkerServer.

    Training is step-synchronized, so the client is deliberately
    simpler than RemoteReplica: one socket, one RPC in flight,
    serialized by a connection lock (the membership refresher and the
    step dispatcher share it). ANY failed RPC — timeout, partition,
    typed transport error — closes the connection, so a straggler's
    late reply can never desynchronize the frame stream; the next RPC
    reconnects fresh. Exposes the membership-view surface
    (``refresh``/``alive``/``health_state``/``outstanding``) so
    :class:`~paddle_tpu.cluster.membership.Membership` drives
    heartbeats and staleness unchanged."""

    def __init__(self, addr, name=None, token=None,
                 connect_timeout_s=5.0, rpc_timeout_s=10.0,
                 stale_after_s=None, connect=None):
        self.addr = addr
        self.name = name or (addr if isinstance(addr, str)
                             else f"{addr[0]}:{addr[1]}")
        self._token = token
        self.connect_timeout_s = float(connect_timeout_s)
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.stale_after_s = stale_after_s
        self._connect = connect or net.open_conn
        self._io_lock = threading.Lock()
        self._sock = None
        self._next_id = 0
        self._closed = False
        self._last_seen = None
        self._last_stats = {}
        # coordinator bookkeeping (mutated only under the coordinator's
        # own lock — see TrainCoordinator)
        self.admitted = False
        self.evicted_at = None
        self.last_step = None
        self.evictions = 0
        self.rejoins = 0
        self.metrics = ServingMetrics(extra_counters=(
            "train_steps_total", "train_rpc_failures_total",
            "train_evictions_total", "train_rejoins_total",
            "train_commits_total"))

    # -- transport ------------------------------------------------------
    def _drop_locked(self):
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def rpc(self, frame, timeout=None):
        """One request → one reply, bounded by ``timeout`` seconds.
        Typed wire errors re-raise as their original class; transport
        failures surface as RemoteUnavailableError /
        RequestTimeoutError and tear the connection down."""
        deadline = time.monotonic() + (self.rpc_timeout_s
                                       if timeout is None
                                       else float(timeout))
        with self._io_lock:
            if self._closed:
                raise net.RemoteUnavailableError(
                    f"worker client {self.name} is closed")
            if _faultinject.fires("train_net_partition"):
                self._drop_locked()
                raise net.RemoteUnavailableError(
                    f"injected train-net partition to {self.name}")
            if self._sock is None:
                # racecheck: ok(blocking-under-lock) — deadline-bounded
                # connect under the connection's serialization lock;
                # only the step dispatcher and the heartbeat share it
                sock, _welcome = self._connect(
                    self.addr, token=self._token, deadline=deadline,
                    connect_timeout=self.connect_timeout_s)
                self._sock = sock
                self._last_seen = time.monotonic()
            self._next_id += 1
            frame = dict(frame, id=self._next_id)
            try:
                # racecheck: ok(blocking-under-lock) — deadline-bounded
                # frame RPC under the write-serialization lock: one
                # request in flight per connection is the protocol, so
                # send+recv must be atomic w.r.t. concurrent callers
                net.send_frame(self._sock, frame, deadline=deadline)
                reply = net.recv_frame(self._sock, deadline=deadline)
            except Exception:
                self._drop_locked()
                raise
            if reply is None:
                self._drop_locked()
                raise net.RemoteUnavailableError(
                    f"worker {self.name} closed the connection "
                    "mid-RPC")
            self._last_seen = time.monotonic()
            if reply.get("type") == "stats":
                self._last_stats = reply.get("value") or {}
        if reply.get("type") in ("error", "protocol_error"):
            net.raise_wire_error(reply["error"])
        return reply

    # -- train verbs ----------------------------------------------------
    def configure(self, spec, timeout=None):
        reply = self.rpc({"type": "train_configure", "task": spec},
                         timeout=timeout)
        return reply

    def train_step(self, step, state, shards, n_shards, timeout=None):
        return self.rpc({"type": "train_step", "step": int(step),
                         "state": state, "shards": list(shards),
                         "n_shards": int(n_shards)}, timeout=timeout)

    def commit(self, step, state, sha, timeout=None):
        return self.rpc({"type": "train_commit", "step": int(step),
                         "serial": int(step), "state": state,
                         "sha": sha}, timeout=timeout)

    # -- membership-view surface ---------------------------------------
    def refresh(self, timeout=2.0):
        """One heartbeat: stats RPC (reconnecting if needed). Returns
        True when the worker answered."""
        if self._closed:
            return False
        try:
            self.rpc({"type": "stats"}, timeout=timeout)
            return True
        except (net.ServingError, OSError):
            return False

    def alive(self):
        return self._sock is not None and not self._closed

    def health_state(self):
        if self._closed:
            return HealthState.STOPPED
        if not self.alive() or self._stale():
            return HealthState.DEGRADED
        return HealthState.READY

    def _stale(self):
        if self.stale_after_s is None or self._last_seen is None:
            return False
        return time.monotonic() - self._last_seen \
            > float(self.stale_after_s)

    def outstanding(self):
        return 0        # step-synchronized: nothing queues client-side

    def last_seen_age_s(self):
        return (None if self._last_seen is None
                else round(time.monotonic() - self._last_seen, 3))

    def stats(self):
        return dict(self._last_stats)

    def close(self):
        with self._io_lock:
            self._closed = True
            self._drop_locked()
        return self

    def drop_connection(self):
        """Sever the link (eviction hygiene: a stale reply must never
        be read as a fresh one — the next RPC reconnects)."""
        with self._io_lock:
            self._drop_locked()


# ---------------------------------------------------------------------------
# TrainCoordinator
# ---------------------------------------------------------------------------


class TrainCoordinator:
    """Owns the state, the membership view, the step barrier, and the
    commit discipline for a fleet of train workers.

    Construction RESUMES: if ``checkpoint_dir`` holds a committed
    serial, the newest checksum-valid one is loaded (quarantine and
    fall back on damage, exactly the resilience-store read protocol)
    and training continues from the step after it — the coordinator
    crash-recovery path is the constructor, there is no separate
    recover() to get wrong.

    ``elastic=False`` disables eviction/retry (a worker failure
    raises) — the teeth-check mode that proves the chaos drill
    detects lost steps.
    """

    def __init__(self, task, workers, checkpoint_dir,
                 commit_interval=5, n_shards=None,
                 step_deadline_s=30.0, admit_deadline_s=10.0,
                 readmit_interval_s=0.2, token=None,
                 refresh_interval_s=0.0, stale_after_s=None,
                 keep_checkpoints=None, elastic=True):
        self.task = task
        self.checkpoint_dir = checkpoint_dir
        self.commit_interval = max(1, int(commit_interval))
        self.step_deadline_s = float(step_deadline_s)
        self.admit_deadline_s = float(admit_deadline_s)
        self.readmit_interval_s = float(readmit_interval_s)
        self.keep_checkpoints = keep_checkpoints
        self.elastic = bool(elastic)
        self._token = token
        self._lock = threading.Lock()
        self._clients = []
        self._events = []           # (kind, worker, step, reason)
        self._losses = []           # per-step global mean loss
        self._commits = []          # (step, sha)
        self.retries_total = 0
        self.evictions_total = 0
        self.rejoins_total = 0
        self.last_recover_s = None          # eviction → rejoin wall
        self._readmit_at = {}               # name -> next attempt time
        for w in workers:
            self.admit(w, _initial=True)
        self.n_shards = int(n_shards) if n_shards \
            else max(1, len(self._clients))
        # resume from the newest committed serial, or start fresh
        self.state = None
        self.step = 0
        self._committed_state = None    # catch-up payload for rejoins
        try:
            state, manifest, serial, _path = _ckpt.load_latest_valid(
                checkpoint_dir)
            self.state = state
            self.step = int(serial)
            self._committed_state = state
            meta = manifest.get("meta", {})
            with self._lock:
                self._commits.append(
                    (self.step, meta.get("params_sha")
                     or _ckpt.state_sha(state)))
        except FileNotFoundError:
            self.state = task.init_state()
        if stale_after_s is None:
            # refresh_interval_s=0 is the hand-driven test mode;
            # Membership's 3×interval default would degenerate to 0s
            # staleness and mark every worker DEGRADED on sight
            stale_after_s = max(3.0 * refresh_interval_s, 30.0)
        self.membership = Membership(
            list(self._clients), refresh_interval_s=refresh_interval_s,
            stale_after_s=stale_after_s)

    # -- membership / elasticity ---------------------------------------
    def admit(self, worker, _initial=False):
        """Add a worker (an address or a ready WorkerClient). The
        handshake + task configure + catch-up from the last committed
        state happen on the next admit sweep — a dead seed address
        never blocks construction."""
        client = worker if isinstance(worker, WorkerClient) \
            else WorkerClient(worker, token=self._token)
        with self._lock:
            self._clients.append(client)
            self._readmit_at[client.name] = 0.0
        membership = getattr(self, "membership", None)
        if not _initial and membership is not None:
            # fold the newcomer into the heartbeat view
            with membership._lock:
                membership._replicas.append(client)
                membership._alive_view.setdefault(client.name, None)
        return client

    def _record_event(self, kind, client, step, reason):
        with self._lock:
            self._events.append({
                "kind": kind, "worker": client.name, "step": step,
                "reason": reason, "t": time.monotonic()})

    def _evict(self, client, step, reason):
        with self._lock:
            if not client.admitted:
                return
            client.admitted = False
            client.evicted_at = time.monotonic()
            client.evictions += 1
            self.evictions_total += 1
            self._readmit_at[client.name] = (
                time.monotonic() + self.readmit_interval_s)
        client.metrics.incr("train_evictions_total")
        client.drop_connection()
        self._record_event("evicted", client, step, reason)

    def _try_admit(self, client):
        """One admit attempt: configure + catch up from the last
        committed state. Returns True when the worker is in."""
        try:
            client.configure(self.task.spec(),
                             timeout=self.step_deadline_s)
            step, sha = self.last_commit()
            if sha is not None and self._committed_state is not None:
                # catch up from the COMMITTED snapshot — the live
                # self.state may be steps past the barrier and would
                # never re-hash to the committed sha
                reply = client.commit(step, self._committed_state,
                                      sha,
                                      timeout=self.step_deadline_s)
                if not reply.get("ok"):
                    # bitwise divergence at the door: refuse, record,
                    # and keep the coordinator alive — the readmit
                    # sweep will retry after the worker re-syncs
                    self._record_event(
                        "admit_refused", client, self.step,
                        f"CommitMismatch: worker sha "
                        f"{reply.get('sha')} != leader sha {sha}")
                    return False
        except (net.ServingError, OSError):
            return False
        now = time.monotonic()
        with self._lock:
            was_evicted = client.evicted_at is not None
            client.admitted = True
            if was_evicted:
                client.rejoins += 1
                self.rejoins_total += 1
                self.last_recover_s = now - client.evicted_at
                client.evicted_at = None
        client.metrics.incr("train_rejoins_total")
        if was_evicted:
            self._record_event("rejoined", client, self.step,
                              f"recover_s={self.last_recover_s:.3f}")
        return True

    def _admit_sweep(self, block=False):
        """Try to (re)admit every non-admitted worker; with ``block``,
        keep trying until at least one worker is in or the admit
        deadline expires."""
        end = time.monotonic() + self.admit_deadline_s
        while True:
            now = time.monotonic()
            for client in list(self._clients):
                if client.admitted:
                    continue
                if now < self._readmit_at.get(client.name, 0.0):
                    continue
                with self._lock:
                    self._readmit_at[client.name] = (
                        now + self.readmit_interval_s)
                self._try_admit(client)
            live = [c for c in self._clients if c.admitted]
            if live or not block or time.monotonic() >= end:
                return live
            time.sleep(min(0.05, self.readmit_interval_s))

    def live_workers(self):
        return [c for c in self._clients if c.admitted]

    # -- the step loop --------------------------------------------------
    def _assignment(self, live):
        """Round-robin logical shards over the live workers, in
        deterministic (name-sorted) order. The ASSIGNMENT may change
        every step; the reduction order never does."""
        live = sorted(live, key=lambda c: c.name)
        out = {c: [] for c in live}
        for shard in range(self.n_shards):
            out[live[shard % len(live)]].append(shard)
        return out

    def _dispatch(self, assignment, step):
        """The barrier: every live worker computes its shards in
        parallel, bounded by the straggler deadline. Returns
        (per-shard results, failures)."""
        results = {}
        failures = {}
        res_lock = threading.Lock()

        def one(client, shards):
            t0 = time.monotonic()
            try:
                reply = client.train_step(
                    step, self.state, shards, self.n_shards,
                    timeout=self.step_deadline_s)
                got = reply.get("shards") or {}
                missing = [s for s in shards if s not in got
                           and str(s) not in got]
                if missing:
                    raise net.ServingError(
                        f"worker {client.name} answered step {step} "
                        f"without shards {missing}")
                with res_lock:
                    for s in shards:
                        results[s] = got.get(s, got.get(str(s)))
                client.metrics.incr("train_steps_total")
                client.metrics.observe_window(
                    "step_time_s", time.monotonic() - t0)
                with self._lock:
                    client.last_step = step
            except Exception as exc:    # noqa: BLE001 — typed below
                client.metrics.incr("train_rpc_failures_total")
                with res_lock:
                    failures[client] = exc

        threads = [threading.Thread(
            target=one, args=(c, s), daemon=True,
            name=f"train-dispatch-{c.name}")
            for c, s in assignment.items()]
        for t in threads:
            t.start()
        end = time.monotonic() + self.step_deadline_s + 1.0
        for t in threads:
            t.join(max(0.0, end - time.monotonic()))
        # a thread still alive past the deadline is a straggler whose
        # RPC will fail typed on its own recv deadline; its client is
        # treated as failed NOW
        for client in assignment:
            with res_lock:
                done = (client in failures
                        or all(s in results
                               for s in assignment[client]))
            if not done:
                failures.setdefault(client, net.RequestTimeoutError(
                    f"worker {client.name} missed the straggler "
                    f"deadline ({self.step_deadline_s}s) at step "
                    f"{step}"))
                client.drop_connection()
        return results, failures

    def step_once(self):
        """One committed-or-retried global step. Elastic: worker
        failures evict + retry at reduced world size; zero live
        workers parks up to the admit deadline then raises typed."""
        if _faultinject.fires("coordinator_crash"):
            raise _faultinject.SimulatedCrash(
                f"injected coordinator crash before step "
                f"{self.step + 1}")
        step = self.step + 1
        attempts = 0
        while True:
            live = self._admit_sweep(block=attempts > 0)
            if not live:
                raise NoTrainWorkersError(
                    f"no admitted train workers for step {step} "
                    f"within the {self.admit_deadline_s}s admit "
                    "deadline")
            assignment = self._assignment(live)
            results, failures = self._dispatch(assignment, step)
            if not failures:
                break
            for client, exc in failures.items():
                if not self.elastic:
                    raise exc
                self._evict(client, step,
                            f"{type(exc).__name__}: {exc}")
            with self._lock:
                self.retries_total += 1
            attempts += 1
        # deterministic reduction: shard-index order, sums first
        total_rows = 0
        total_loss = 0.0
        gsums = None
        for shard in range(self.n_shards):
            r = results[shard]
            total_rows += int(r["n_rows"])
            total_loss += float(r["loss_sum"])
            grads = r["grads"]
            if gsums is None:
                gsums = {k: np.asarray(v, np.float32).copy()
                         for k, v in grads.items()}
            else:
                for k in gsums:
                    gsums[k] += np.asarray(grads[k], np.float32)
        self.state = self.task.apply(self.state, gsums, total_rows,
                                     step)
        self.step = step
        with self._lock:
            self._losses.append(total_loss / max(1, total_rows))
        _faultinject.event("coordinator_step")
        if step % self.commit_interval == 0:
            self.commit()
        return self.step

    def run(self, num_steps):
        """Drive ``num_steps`` committed-or-retried steps."""
        for _ in range(int(num_steps)):
            self.step_once()
        return self.step

    # -- commit discipline ---------------------------------------------
    def commit(self):
        """The checkpoint barrier: leader writes the committed state
        through the crash-safe store (sha in the manifest meta,
        leader-only pruning), then every live worker re-hashes the
        broadcast state and verifies — a mismatch is bitwise
        divergence and evicts the worker typed."""
        sha = _ckpt.state_sha(self.state)
        _ckpt.save_state(
            self.checkpoint_dir, self.state, serial=self.step,
            meta={"step": self.step, "params_sha": sha,
                  "world_size": len(self.live_workers()),
                  "n_shards": self.n_shards},
            max_num_checkpoints=self.keep_checkpoints, leader=True)
        self._committed_state = self.state      # apply() never mutates
        with self._lock:
            self._commits.append((self.step, sha))
        for client in self.live_workers():
            try:
                reply = client.commit(self.step, self.state, sha,
                                      timeout=self.step_deadline_s)
            except (net.ServingError, OSError) as exc:
                self._evict(client, self.step,
                            f"commit barrier: {type(exc).__name__}: "
                            f"{exc}")
                continue
            client.metrics.incr("train_commits_total")
            if not reply.get("ok"):
                self._evict(client, self.step, CommitMismatch(
                    f"worker sha {reply.get('sha')} != leader sha "
                    f"{sha} at step {self.step}").args[0])
        _faultinject.event("train_commit")
        return sha

    def last_commit(self):
        with self._lock:
            return self._commits[-1] if self._commits else (0, None)

    def losses(self):
        with self._lock:
            return list(self._losses)

    def commits(self):
        with self._lock:
            return list(self._commits)

    def events(self):
        with self._lock:
            return list(self._events)

    # -- ops plane ------------------------------------------------------
    def stats(self):
        """The operator view: fleet position, per-worker rows
        (last_step, step-time percentiles, heartbeat age,
        evictions/rejoins), and one merged metrics registry with every
        worker's counters under its own ``<name>/`` namespace
        (ServingMetrics.merge label discipline — rows never
        collide)."""
        step, sha = self.last_commit()
        rows = []
        per_worker = []
        for c in list(self._clients):
            win = c.metrics.stats().get("step_time_s") or {}
            rows.append({
                "name": c.name,
                "addr": c.addr,
                "admitted": c.admitted,
                "alive": c.alive(),
                "health_state": c.health_state(),
                "last_step": c.last_step,
                "step_time_p50_ms": win.get("p50_ms"),
                "step_time_p99_ms": win.get("p99_ms"),
                "heartbeat_age_s": c.last_seen_age_s(),
                "evictions": c.evictions,
                "rejoins": c.rejoins,
                "remote": c.stats(),
            })
            per_worker.append(
                ServingMetrics.merge(c.metrics, label=c.name))
        merged = ServingMetrics.merge(*per_worker) if per_worker \
            else ServingMetrics()
        with self._lock:
            snap = {
                "step": self.step,
                "committed_step": step,
                "committed_sha": sha,
                "commits_total": len(self._commits),
                "world_size": sum(1 for c in self._clients
                                  if c.admitted),
                "n_shards": self.n_shards,
                "evictions_total": self.evictions_total,
                "rejoins_total": self.rejoins_total,
                "retries_total": self.retries_total,
                "last_recover_s": self.last_recover_s,
                "events": list(self._events[-32:]),
            }
        snap["workers"] = rows
        snap["membership"] = self.membership.stats()
        snap["metrics"] = merged.stats()
        return snap

    def close(self, goodbye=True):
        """Shut the coordinator down; the worker SERVERS keep running
        (they belong to their hosts, and they will park for the next
        coordinator)."""
        self.membership.close()
        for c in list(self._clients):
            c.close()
        return self
