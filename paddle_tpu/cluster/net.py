"""Network transport for the serving fabric — the robust frame layer.

Everything that crosses a machine boundary in paddle_tpu goes through
this module: the versioned frame codec (shared by the stdio pipe
protocol of ``proc_worker`` and the TCP sockets of ``net_worker`` /
``RemoteReplica``), the connection handshake, and the deadline-aware
socket send/recv primitives. The design stance is the TF-paper one
(arXiv:1605.08695): the network is a *fault domain*, so every failure
mode must map to a typed error a client can program against — never
pickle garbage, never an indefinite hang.

Frame format (``PTN`` + version byte, then two big-endian u32s)::

    +------+----+----------+----------+----------------+
    | PTN  | v1 | len(u32) | crc32    | pickle payload |
    +------+----+----------+----------+----------------+

- an **alien** frame (wrong magic — a stray print, an HTTP probe, a
  port scanner) raises :class:`FrameError` at the first 4 bytes;
- a **version-skew** frame (magic right, version byte wrong) is typed
  too, so a rolling fleet upgrade fails loudly instead of misparsing;
- a **truncated** frame (EOF mid-header or mid-payload — the peer died
  or a partial write landed) is distinguished from a clean EOF at a
  frame boundary (``None``: the peer closed politely);
- a **corrupt** frame (CRC32 mismatch) never reaches the unpickler.

Unpickling is restricted on BOTH transports: only plain containers,
scalars, and numpy array reconstructors are allowed — a frame whose
payload references any other global (``os.system``, ``builtins.eval``,
a framework class) raises :class:`FrameError` instead of importing it.
Feeds, fetches, stats dicts, and error tuples all fit comfortably
inside that vocabulary; arbitrary code does not.

The handshake (one frame each way, before any RPC) carries a shared
auth token (``PADDLE_TPU_NET_TOKEN``) compared constant-time, plus a
schema fingerprint (frame protocol version + jax version) so two hosts
that would disagree about executables or wire semantics refuse each
other with a typed :class:`HandshakeError` up front.

Fault points (``resilience/faultinject.py``) are compiled into the
socket paths on both sides: ``net_conn_refused`` (connect),
``net_frame_drop`` / ``net_frame_delay`` / ``net_partial_write``
(send), and ``net_partition`` (send AND recv fail as if the route
vanished) — the chaos drills in ``tests/test_net_cluster.py`` and
``servebench --remote --chaos`` arm them mid-load.
"""
import hashlib
import hmac
import io
import os
import pickle
import socket
import struct
import time
import zlib

from ..resilience import faultinject as _faultinject
from ..serving.batching import (QueueFullError, RequestTimeoutError,
                                ServerClosedError, ServingError)
from ..serving.buckets import BucketError
from ..serving.health import ServiceUnavailableError, WorkerDiedError
from ..serving.kv_pages import PagesExhaustedError
from ..serving.overload import RetryBudgetExhaustedError

__all__ = ["FrameError", "HandshakeError", "RemoteUnavailableError",
           "PROTO_VERSION", "MAGIC", "HEADER_LEN", "MAX_FRAME_BYTES",
           "encode_frame", "decode_payload", "write_frame",
           "read_frame", "send_frame", "recv_frame",
           "schema_fingerprint", "default_token", "client_hello",
           "check_hello", "open_conn", "WIRE_ERRORS", "wire_error",
           "raise_wire_error"]

MAGIC = b"PTN"               # paddle_tpu net frame
PROTO_VERSION = 1
_HEADER = struct.Struct(">II")          # payload length, crc32
HEADER_LEN = len(MAGIC) + 1 + _HEADER.size
# length sanity bound: an alien frame that happens to start with the
# magic must not make us allocate gigabytes on a garbage length field
MAX_FRAME_BYTES = 256 * 2 ** 20

_FAULT_DELAY_ENV = "PADDLE_TPU_FAULT_NET_DELAY_S"


class FrameError(ServingError):
    """Protocol-level damage on a frame stream: alien magic, version
    skew, truncation mid-frame, CRC mismatch, an oversize length, or a
    payload outside the restricted-unpickle vocabulary. The connection
    that produced it is unusable — close it; the *stream position* is
    unknowable after garbage."""

    def __init__(self, reason, detail=""):
        self.reason = reason
        super().__init__(f"[{reason}] {detail}" if detail else reason)


class HandshakeError(ServingError):
    """The peer refused the connection at handshake time: bad auth
    token, schema/jax fingerprint mismatch, or a malformed hello.
    Deliberately NOT retriable-looking — reconnecting with the same
    credentials will refuse identically."""


class RemoteUnavailableError(ServiceUnavailableError):
    """The remote endpoint cannot be reached right now: connection
    refused/reset, a partition, a send into a dead socket. IS-A
    ServiceUnavailableError, so the Router's reroute ladder treats it
    exactly like an open breaker — try the next replica."""


# typed serving errors forwarded over the wire by class name; both the
# pipe worker and the socket server send ``(type_name, message)`` and
# the client re-raises the same type so retry/reroute classification is
# identical however the replica is backed
WIRE_ERRORS = {cls.__name__: cls for cls in (
    QueueFullError, RequestTimeoutError, ServerClosedError,
    ServingError, BucketError, ServiceUnavailableError,
    WorkerDiedError, PagesExhaustedError, FrameError, HandshakeError,
    RemoteUnavailableError, RetryBudgetExhaustedError, ValueError,
    TimeoutError)}


def register_wire_error(cls):
    """Register a typed error defined ABOVE net in the import graph
    (router, train_fabric) for by-name re-raise on the client side.
    Modules call this right after the class definition, so any
    process that can raise the class can also map it — protocheck's
    wire-error rule audits that every raised ServingError-family
    class is registered one way or the other."""
    WIRE_ERRORS[cls.__name__] = cls
    return cls


def wire_error(exc):
    """The ``(type_name, message)`` pair a server forwards."""
    return (type(exc).__name__, str(exc))


def raise_wire_error(pair):
    """Re-raise a forwarded error as its original type (ServingError
    when the name is unknown — a newer server never crashes an older
    client with an unmappable name)."""
    name, text = pair
    raise WIRE_ERRORS.get(name, ServingError)(text)


# ---------------------------------------------------------------------------
# restricted unpickling
# ---------------------------------------------------------------------------

_SAFE_BUILTINS = frozenset((
    "bool", "bytearray", "bytes", "complex", "dict", "float",
    "frozenset", "int", "list", "range", "set", "slice", "str",
    "tuple"))

# exactly the globals numpy's array/scalar pickles reference, across
# the numpy 1.x (numpy.core) and 2.x (numpy._core) module layouts
_SAFE_NUMPY = {
    "numpy": frozenset(("dtype", "ndarray")),
    "numpy.core.multiarray": frozenset(("_reconstruct", "scalar")),
    "numpy._core.multiarray": frozenset(("_reconstruct", "scalar")),
    "numpy.core.numeric": frozenset(("_frombuffer",)),
    "numpy._core.numeric": frozenset(("_frombuffer",)),
}


class _RestrictedUnpickler(pickle.Unpickler):
    """Allow containers, scalars, and numpy arrays — nothing else. A
    frame is DATA; a payload that wants to import anything beyond this
    vocabulary is an attack or a bug, and both deserve FrameError."""

    def find_class(self, module, name):
        if module == "builtins" and name in _SAFE_BUILTINS:
            return super().find_class(module, name)
        allowed = _SAFE_NUMPY.get(module)
        if allowed is not None and name in allowed:
            return super().find_class(module, name)
        raise FrameError(
            "unpickle",
            f"payload references disallowed global {module}.{name}")


def decode_payload(payload):
    """Restricted-unpickle one frame payload; any failure (including a
    disallowed global) is FrameError."""
    try:
        return _RestrictedUnpickler(io.BytesIO(payload)).load()
    except FrameError:
        raise
    except Exception as exc:            # noqa: BLE001 — typed rewrap
        raise FrameError("unpickle",
                         f"payload would not deserialize: {exc}") \
            from exc


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------


def encode_frame(obj):
    """One complete frame (header + payload) as bytes."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    return (MAGIC + bytes((PROTO_VERSION,))
            + _HEADER.pack(len(payload), zlib.crc32(payload))
            + payload)


def _check_header(header):
    """Validate a 12-byte header; returns the payload length."""
    if header[:len(MAGIC)] != MAGIC:
        raise FrameError(
            "alien-magic",
            f"stream carries non-protocol bytes {header[:4]!r} — a "
            "stray write reached the frame channel")
    version = header[len(MAGIC)]
    if version != PROTO_VERSION:
        raise FrameError(
            "version-skew",
            f"peer speaks frame protocol v{version}, this process "
            f"speaks v{PROTO_VERSION}")
    length, crc = _HEADER.unpack_from(header, len(MAGIC) + 1)
    if length > MAX_FRAME_BYTES:
        raise FrameError(
            "oversize", f"declared payload of {length} bytes exceeds "
            f"the {MAX_FRAME_BYTES}-byte frame bound")
    return length, crc


def _finish_frame(payload, length, crc):
    if len(payload) < length:
        raise FrameError(
            "truncated",
            f"payload ended at {len(payload)}/{length} bytes — peer "
            "died or a partial write landed")
    if zlib.crc32(payload) != crc:
        raise FrameError(
            "crc-mismatch",
            "payload checksum mismatch — corruption in transit")
    return decode_payload(payload)


# -- file-like streams (the stdio pipe transport) ----------------------


def _read_exact(stream, n):
    """Read exactly ``n`` bytes; short data returns what arrived."""
    chunks = []
    got = 0
    while got < n:
        chunk = stream.read(n - got)
        if not chunk:
            break
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def write_frame(stream, obj):
    """One frame onto a file-like stream (the proc_worker pipe)."""
    stream.write(encode_frame(obj))
    stream.flush()


def read_frame(stream):
    """One frame from a file-like stream. ``None`` on clean EOF at a
    frame boundary; FrameError on anything else."""
    header = _read_exact(stream, HEADER_LEN)
    if not header:
        return None
    if len(header) < HEADER_LEN:
        raise FrameError(
            "truncated",
            f"header ended at {len(header)}/{HEADER_LEN} bytes")
    length, crc = _check_header(header)
    return _finish_frame(_read_exact(stream, length), length, crc)


# -- sockets (the cross-host transport) --------------------------------


def _remaining(deadline, clock=time.monotonic):
    """Seconds left before ``deadline`` (monotonic), or None."""
    if deadline is None:
        return None
    left = deadline - clock()
    if left <= 0:
        raise RequestTimeoutError(
            "deadline expired before the network operation started")
    return left


def send_frame(sock, obj, deadline=None):
    """One frame onto a socket, bounded by ``deadline`` (monotonic
    seconds). Transport failures surface as RemoteUnavailableError;
    an expired deadline as RequestTimeoutError. Fault points:
    net_partition / net_frame_delay / net_frame_drop /
    net_partial_write."""
    if _faultinject.fires("net_partition"):
        raise RemoteUnavailableError(
            "injected network partition (send side)")
    if _faultinject.fires("net_frame_delay"):
        time.sleep(float(os.environ.get(_FAULT_DELAY_ENV, 0.05)))
    data = encode_frame(obj)
    if _faultinject.fires("net_frame_drop"):
        return                      # the network ate it; caller's
    try:                            # deadline is the safety net
        sock.settimeout(_remaining(deadline))
        if _faultinject.fires("net_partial_write"):
            sock.sendall(data[:max(1, len(data) // 2)])
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            raise ConnectionResetError(
                "injected partial write — connection torn mid-frame")
        sock.sendall(data)
    except socket.timeout as exc:
        raise RequestTimeoutError(
            "deadline expired while sending a frame") from exc
    except OSError as exc:
        raise RemoteUnavailableError(
            f"send failed: {exc}") from exc


def _recv_exact(sock, n, deadline):
    chunks = []
    got = 0
    while got < n:
        sock.settimeout(_remaining(deadline))
        try:
            chunk = sock.recv(min(n - got, 1 << 20))
        except socket.timeout as exc:
            raise RequestTimeoutError(
                "deadline expired while receiving a frame") from exc
        if not chunk:
            break
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock, deadline=None):
    """One frame from a socket, bounded by ``deadline``. ``None`` on
    clean EOF at a frame boundary; FrameError / RequestTimeoutError /
    RemoteUnavailableError otherwise."""
    if _faultinject.fires("net_partition"):
        raise RemoteUnavailableError(
            "injected network partition (recv side)")
    try:
        header = _recv_exact(sock, HEADER_LEN, deadline)
    except RequestTimeoutError:
        raise
    except OSError as exc:
        raise RemoteUnavailableError(f"recv failed: {exc}") from exc
    if not header:
        return None
    if len(header) < HEADER_LEN:
        raise FrameError(
            "truncated",
            f"header ended at {len(header)}/{HEADER_LEN} bytes")
    length, crc = _check_header(header)
    try:
        payload = _recv_exact(sock, length, deadline)
    except OSError as exc:
        raise RemoteUnavailableError(f"recv failed: {exc}") from exc
    return _finish_frame(payload, length, crc)


# ---------------------------------------------------------------------------
# handshake
# ---------------------------------------------------------------------------


def default_token():
    """The shared fabric auth token (``PADDLE_TPU_NET_TOKEN``, default
    empty — fine on a loopback dev box, set a real secret on a
    fleet)."""
    return os.environ.get("PADDLE_TPU_NET_TOKEN", "")


def schema_fingerprint():
    """What both ends must agree on before exchanging work: the frame
    protocol version and the jax version (a replica whose jax differs
    would disagree about executables and numerics — refuse at
    handshake, not at the first weird answer)."""
    try:
        import jax
        jax_version = jax.__version__
    except Exception:               # noqa: BLE001 — handshake-only
        jax_version = "unknown"
    return {"proto": PROTO_VERSION, "jax": jax_version}


def client_hello(token=None, fingerprint=None):
    return {"type": "hello",
            "token": default_token() if token is None else str(token),
            "fingerprint": fingerprint or schema_fingerprint()}


def check_hello(msg, token=None, fingerprint=None):
    """Server-side hello validation; returns None when acceptable,
    else the refusal reason string."""
    if not isinstance(msg, dict) or msg.get("type") != "hello":
        return "malformed hello"
    want = default_token() if token is None else str(token)
    got = msg.get("token")
    if not isinstance(got, str) or not hmac.compare_digest(got, want):
        return "bad auth token"
    want_fp = fingerprint or schema_fingerprint()
    if msg.get("fingerprint") != want_fp:
        return (f"fingerprint mismatch: client "
                f"{msg.get('fingerprint')} vs server {want_fp}")
    return None


def open_conn(addr, token=None, deadline=None, connect_timeout=5.0):
    """Connect + handshake; returns ``(socket, welcome_frame)``.

    ``addr`` is ``(host, port)`` or ``"host:port"``. Raises
    RemoteUnavailableError (unreachable / refused — including the
    ``net_conn_refused`` fault point), HandshakeError (peer refused
    us), FrameError (peer is not speaking the protocol), or
    RequestTimeoutError (deadline)."""
    if isinstance(addr, str):
        host, _, port = addr.rpartition(":")
        addr = (host or "127.0.0.1", int(port))
    if _faultinject.fires("net_conn_refused"):
        raise RemoteUnavailableError(
            f"injected connection refusal to {addr[0]}:{addr[1]}")
    left = _remaining(deadline)
    timeout = connect_timeout if left is None \
        else min(connect_timeout, left)
    try:
        sock = socket.create_connection(addr, timeout=timeout)
    except socket.timeout as exc:
        raise RequestTimeoutError(
            f"connect to {addr[0]}:{addr[1]} timed out") from exc
    except OSError as exc:
        raise RemoteUnavailableError(
            f"cannot connect to {addr[0]}:{addr[1]}: {exc}") from exc
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        hello_deadline = (time.monotonic() + connect_timeout
                          if deadline is None else deadline)
        send_frame(sock, client_hello(token=token),
                   deadline=hello_deadline)
        welcome = recv_frame(sock, deadline=hello_deadline)
    except BaseException:
        sock.close()
        raise
    if welcome is None:
        sock.close()
        raise RemoteUnavailableError(
            f"{addr[0]}:{addr[1]} closed the connection during "
            "handshake")
    if welcome.get("type") == "reject":
        sock.close()
        raise HandshakeError(
            f"{addr[0]}:{addr[1]} refused the handshake: "
            f"{welcome.get('reason')}")
    if welcome.get("type") != "welcome":
        sock.close()
        raise FrameError(
            "alien-magic",
            f"peer answered the hello with {welcome.get('type')!r}")
    return sock, welcome


def hash_blob(blob):
    """sha256 hex of a wire blob (provisioning integrity checks)."""
    return hashlib.sha256(blob).hexdigest()
