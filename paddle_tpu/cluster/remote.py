"""RemoteReplica — a socket-backed replica with the robustness layer.

The third backing of the :class:`~paddle_tpu.cluster.replica.Replica`
interface (after in-process engines and pipe-driven OS processes): the
engine lives on another host behind a :class:`ReplicaServer`, and this
wrapper makes the network's failure modes *defined behaviors* the
Router's reroute/failover ladder already knows how to absorb:

- **deadline-aware RPC** — every submit propagates the tightest of the
  caller's deadline and the replica's default request timeout into the
  frame (the server enforces it engine-side) AND arms a local sweeper,
  so a request on a partitioned connection resolves as a typed
  RequestTimeoutError at its deadline, never a hang;
- **per-connection circuit breaker** — PR 4 semantics over transport
  failures: consecutive connect/send/reader failures open it, open
  sheds submits instantly with ServiceUnavailableError (the router
  reroutes), a cooled-down breaker lets one submit through half-open
  as the probe whose outcome closes or re-opens it;
- **reconnect with jittered exponential backoff** — ``start()`` (the
  pool revival monitor's verb, and the membership refresher's) retries
  the connect through ``resilience.retry.with_retries`` with a
  0.5–1.5× jitter on each delay so a rack of replicas does not
  reconnect in lockstep after a partition heals;
- **typed error re-raise** — server-side serving errors arrive as
  ``(type_name, message)`` and re-raise as the same class, so
  QueueFullError still reroutes, BucketError still doesn't, and
  WorkerDiedError still triggers infer() failover — the Router cannot
  tell a remote replica from a local one.
"""
import random
import threading
import time

from ..resilience.retry import RetryPolicy, with_retries
from ..serving.batching import (PendingResult, RequestTimeoutError,
                                ServerClosedError)
from ..serving.health import (CircuitBreaker, HealthState,
                              ServiceUnavailableError,
                              WorkerDiedError)
from . import net
from .replica import Replica

__all__ = ["RemoteReplica"]


class RemoteReplica(Replica):
    """One remote serving engine at ``addr`` (``"host:port"`` or a
    ``(host, port)`` pair) behind the standard Replica interface.

    ``connect=`` is injectable (tests drive scriptable fake sockets
    through it); the default is :func:`net.open_conn`. ``lazy=True``
    skips the construction-time connect — the pool monitor or the
    membership refresher will establish it (a seed list may name hosts
    that are still provisioning)."""

    def __init__(self, addr, name=None, token=None,
                 request_timeout_s=30.0, connect_timeout_s=5.0,
                 breaker_threshold=3, breaker_cooldown_s=1.0,
                 reconnect_attempts=3, reconnect_backoff_s=0.05,
                 stale_after_s=None, deadline_grace_s=0.5,
                 connect=None, sleep=None, rng=None, lazy=False,
                 role=None):
        super().__init__(name or (addr if isinstance(addr, str)
                                  else f"{addr[0]}:{addr[1]}"))
        self.addr = addr
        self.role = role
        self._token = token
        self.request_timeout_s = request_timeout_s
        self.connect_timeout_s = float(connect_timeout_s)
        self.reconnect_attempts = int(reconnect_attempts)
        self.reconnect_backoff_s = float(reconnect_backoff_s)
        self.stale_after_s = stale_after_s
        self.deadline_grace_s = float(deadline_grace_s)
        self._connect = connect or net.open_conn
        self._base_sleep = sleep or time.sleep
        self._rng = rng or random.Random()
        self._breaker_threshold = int(breaker_threshold)
        self._breaker_cooldown_s = float(breaker_cooldown_s)
        self._lock = threading.Lock()       # write side + pending map
        self._pending = {}                  # id -> PendingResult
        self._waiters = {}                  # id -> [event, payload]
        self._next_id = 0
        self._sock = None
        self._reader = None
        self._closed = False
        self._last_stats = {}
        self._last_seen = None              # monotonic, last reply
        self._warmup_report = None
        self.remote_name = None
        self.reconnects_total = 0
        self.reconnect_failures_total = 0
        # breaker opens survive connection turnover: each established
        # connection gets a FRESH breaker (per-connection semantics),
        # so the opens seen across the replica's lifetime accumulate
        # here — the chaos gate's "breaker opened and re-closed" read
        self._breaker_opens_accum = 0
        # per-connection breaker: replaced on every established
        # connection, so "consecutive failures" counts against the
        # CURRENT link, per the PR 4 contract
        self.breaker = self._fresh_breaker()
        self._sweeper = None
        if not lazy:
            self._establish()

    def _fresh_breaker(self):
        return CircuitBreaker(
            failure_threshold=self._breaker_threshold,
            cooldown_s=self._breaker_cooldown_s)

    # -- connection lifecycle --------------------------------------------
    def _jittered_sleep(self, delay):
        """0.5–1.5x jitter so a fleet never reconnects in lockstep."""
        self._base_sleep(delay * (0.5 + self._rng.random()))

    def _establish(self, deadline=None):
        """One connect + handshake; raises typed on failure."""
        sock, welcome = self._connect(
            self.addr, token=self._token, deadline=deadline,
            connect_timeout=self.connect_timeout_s)
        with self._lock:
            old = self._sock
            self._sock = sock
            self.remote_name = welcome.get("name")
            self._warmup_report = welcome.get("warmup")
            self._last_stats = welcome.get("stats") or {}
            self._last_seen = time.monotonic()
            self._breaker_opens_accum += self.breaker.opens_total
            self.breaker = self._fresh_breaker()
        if old is not None:
            try:
                old.close()
            except OSError:
                pass
        self._reader = threading.Thread(
            target=self._reader_loop, args=(sock,),
            name=f"{self.name}-reader", daemon=True)
        self._reader.start()
        if self._sweeper is None or not self._sweeper.is_alive():
            self._sweeper = threading.Thread(
                target=self._sweep_loop, name=f"{self.name}-sweeper",
                daemon=True)
            self._sweeper.start()
        return self

    def _mark_dead(self, exc):
        """The connection is gone: fail everything pending with a
        typed error and count a breaker failure."""
        with self._lock:
            sock, self._sock = self._sock, None
            pending = list(self._pending.values())
            self._pending.clear()
            waiters = list(self._waiters.values())
            self._waiters.clear()
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        for req in pending:
            req.set_error(exc)
        for waiter in waiters:
            waiter[0].set()
        self.breaker.record_failure()

    def _reader_loop(self, sock):
        """Demux reply frames to pending requests. The try/finally is
        the lesson of the ProcessReplica audit: the reader MUST fail
        everything pending however it exits — EOF, protocol damage, a
        partition, or an unexpected bug — or callers strand past their
        deadlines."""
        exc = WorkerDiedError(
            f"remote replica {self.name} connection closed")
        try:
            while True:
                msg = net.recv_frame(sock)
                if msg is None:
                    break
                with self._lock:
                    self._last_seen = time.monotonic()
                kind = msg.get("type")
                if kind == "result":
                    req = self._pop_pending(msg["id"])
                    if req is not None:
                        req.set_result(msg["value"])
                    self.breaker.record_success()
                elif kind == "error":
                    req = self._pop_pending(msg["id"])
                    if req is not None:
                        name, text = msg["error"]
                        req.set_error(net.WIRE_ERRORS.get(
                            name, net.ServingError)(text))
                    else:
                        # an error answering a non-submit RPC
                        # (fetch_artifact on a bad path, …) settles
                        # that verb's waiter instead
                        with self._lock:
                            waiter = self._waiters.pop(
                                msg.get("id"), None)
                        if waiter is not None:
                            waiter[1] = msg
                            waiter[0].set()
                    # a typed SERVING error is a live, answering
                    # remote — the transport breaker stays closed
                    self.breaker.record_success()
                elif kind in ("stats", "pong", "manifest", "artifact"):
                    with self._lock:
                        waiter = self._waiters.pop(msg.get("id"), None)
                        if kind == "stats":
                            self._last_stats = msg.get("value") or {}
                    if waiter is not None:
                        waiter[1] = msg
                        waiter[0].set()
                elif kind == "protocol_error":
                    exc = net.WIRE_ERRORS.get(
                        msg["error"][0], net.FrameError)(
                            msg["error"][1])
                    break
        except net.FrameError as e:
            exc = e
        except (net.RemoteUnavailableError, OSError) as e:
            exc = net.RemoteUnavailableError(
                f"remote replica {self.name} unreachable: {e}")
        except RequestTimeoutError as e:
            exc = e
        finally:
            # only tear down if WE still own this socket (a newer
            # connection may already have replaced it)
            if self._sock is sock:
                self._mark_dead(exc if isinstance(exc, Exception)
                                else WorkerDiedError(str(exc)))

    def _sweep_loop(self):
        """Deadline sentinel: a request whose deadline (+grace) passed
        with no reply — partitioned link, dropped frame, stuck server
        — is failed typed HERE, so 'never a hang' holds even when TCP
        has not noticed the partition."""
        while not self._closed:
            time.sleep(min(0.05, self.deadline_grace_s))
            now = time.monotonic()
            overdue = []
            with self._lock:
                for req_id, req in list(self._pending.items()):
                    if req.deadline is not None and \
                            now >= req.deadline + self.deadline_grace_s:
                        overdue.append(self._pending.pop(req_id))
            for req in overdue:
                req.set_error(RequestTimeoutError(
                    f"request deadline expired with no reply from "
                    f"{self.name} (connection unresponsive — "
                    "partition or dropped frame)"))

    def _pop_pending(self, req_id):
        with self._lock:
            return self._pending.pop(req_id, None)

    # -- small RPC helper (stats/ping/fetch) -----------------------------
    def _rpc(self, frame, timeout=5.0):
        """Fire one non-submit verb and wait for its reply frame; None
        on any transport failure (callers degrade to cached state)."""
        waiter = [threading.Event(), None]
        deadline = time.monotonic() + float(timeout)
        with self._lock:
            if self._sock is None or self._closed:
                return None
            self._next_id += 1
            frame = dict(frame, id=self._next_id)
            self._waiters[frame["id"]] = waiter
            try:
                # racecheck: ok(blocking-under-lock) — the send is
                # deadline-bounded and the lock is what orders the
                # waiter-map insert with the socket write; moving the
                # send out would let the reply race its own waiter
                net.send_frame(self._sock, frame, deadline=deadline)
            except (net.ServingError, OSError):
                self._waiters.pop(frame["id"], None)
                return None
        waiter[0].wait(timeout)
        with self._lock:
            self._waiters.pop(frame["id"], None)
        return waiter[1]

    # -- replica interface -----------------------------------------------
    def submit(self, item, timeout=None, **kw):
        return self._submit_frame(
            {"type": "submit", "feed": item}, timeout, kw)

    def handoff(self, state, timeout=None, **kw):
        """Ship a KV handoff blob to a decode-role server (the
        ``handoff`` wire verb); same breaker/deadline/pending
        machinery as submit."""
        return self._submit_frame(
            {"type": "handoff", "state": state}, timeout, kw)

    def _submit_frame(self, frame, timeout, kw):
        if kw:
            # wire-safe kwargs only (prefill_only, max_new, an SLO as
            # a plain dict — the restricted unpickler refuses custom
            # classes; the server rebuilds the SLOClass)
            frame = dict(frame, kw=kw)
        if self._closed:
            raise ServerClosedError(f"replica {self.name} is closed")
        # breaker gate: open sheds instantly (the router reroutes); a
        # cooled-down open transitions half-open and THIS submit is
        # the probe
        if not self.breaker.allow():
            raise ServiceUnavailableError(
                f"circuit breaker open for {self.name} — the "
                f"connection is failing; back off "
                f"{self._breaker_cooldown_s}s")
        # tightest of the caller deadline and the replica default
        wire_timeout = self.request_timeout_s if timeout is None \
            else (timeout if self.request_timeout_s is None
                  else min(float(timeout), self.request_timeout_s))
        now = time.monotonic()
        deadline = None if wire_timeout is None \
            else now + float(wire_timeout)
        if self._sock is None:
            # one FAST reconnect attempt inline (the submit path must
            # not sit in a backoff loop — that is start()'s job); a
            # failure is typed and reroutable
            try:
                self._establish(deadline=deadline)
            except (net.ServingError, OSError) as exc:
                self.breaker.record_failure()
                self.reconnect_failures_total += 1
                raise net.RemoteUnavailableError(
                    f"replica {self.name} unreachable: {exc}") \
                    from exc
        req = PendingResult(
            feed=None, n_rows=1, signature=(), deadline=deadline,
            enqueued_at=now)
        with self._lock:
            if self._sock is None:
                raise net.RemoteUnavailableError(
                    f"replica {self.name} lost its connection")
            self._next_id += 1
            req_id = self._next_id
            self._pending[req_id] = req
            try:
                # racecheck: ok(blocking-under-lock) — deadline-bounded
                # send; the lock orders the pending-map insert with the
                # write so the reader can never see a reply for an id
                # it cannot find
                net.send_frame(
                    self._sock,
                    dict(frame, id=req_id, timeout=wire_timeout),
                    deadline=deadline)
            except (net.RemoteUnavailableError, OSError) as exc:
                self._pending.pop(req_id, None)
                sock, self._sock = self._sock, None
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                self.breaker.record_failure()
                raise net.RemoteUnavailableError(
                    f"replica {self.name} send failed: {exc}") \
                    from exc
            except RequestTimeoutError:
                self._pending.pop(req_id, None)
                raise
        return req

    def outstanding(self):
        with self._lock:
            return len(self._pending)

    def _stale(self):
        if self.stale_after_s is None or self._last_seen is None:
            return False
        return time.monotonic() - self._last_seen \
            > float(self.stale_after_s)

    def health_state(self):
        if self._closed:
            return HealthState.STOPPED
        if not self.alive():
            return HealthState.DEGRADED
        if self.breaker.state == CircuitBreaker.OPEN or self._stale():
            return HealthState.DEGRADED
        return self._last_stats.get("health_state", HealthState.READY)

    def admits(self):
        if not self.breaker.admits():
            return False
        remote = self._last_stats.get("breaker") or {}
        return remote.get("state", "closed") != "open"

    def alive(self):
        return self._sock is not None and not self._closed

    def start(self):
        """Revive a dead connection: jittered exponential backoff via
        resilience.retry, bounded attempts. Swallows the terminal
        failure (the replica simply stays dead/excluded and the next
        revival sweep or membership refresh tries again) — a
        partitioned peer must cost retries, never a crash or a hang."""
        if self._closed or self.alive():
            return self
        policy = RetryPolicy(
            max_attempts=max(1, self.reconnect_attempts),
            initial_backoff=self.reconnect_backoff_s,
            retryable=(net.RemoteUnavailableError, ConnectionError,
                       OSError, RequestTimeoutError),
            sleep=self._jittered_sleep)
        try:
            with_retries(self._establish, policy=policy)
            self.reconnects_total += 1
        except (net.HandshakeError, net.FrameError):
            raise           # a peer that REFUSES us won't heal by retry
        except (net.ServingError, OSError):
            self.reconnect_failures_total += 1
            # a whole reconnect cycle failing is one consecutive
            # failure against this link — enough of them open the
            # breaker even while the router is ignoring the corpse
            self.breaker.record_failure()
        return self

    def rebuild(self, warmup=True):
        """The rolling-restart verb: drop the link and reconnect fresh
        (the server engine itself is rebuilt server-side by ITS
        operator; client-side a rebuild is a clean re-handshake)."""
        self._mark_dead(ServerClosedError(
            f"replica {self.name} rebuilding its connection"))
        self._establish()
        self.last_rebuild_report = self._warmup_report
        return self

    def close(self, drain=False, drain_timeout=None):
        """Close the CLIENT side (the server keeps serving its other
        clients). ``drain=True`` waits for this client's outstanding
        requests to settle first, bounded by ``drain_timeout``."""
        if drain:
            budget = 10.0 if drain_timeout is None \
                else float(drain_timeout)
            end = time.monotonic() + budget
            while self.outstanding() and time.monotonic() < end:
                time.sleep(0.01)
        self._closed = True
        self._mark_dead(ServerClosedError(
            f"replica {self.name} closed"))
        return self

    def warmup(self):
        """The server warmed at ITS construction; this returns the
        report it handed over in the welcome frame."""
        return self._warmup_report

    def breaker_opens_total(self):
        """Breaker opens across every connection this replica has
        owned (per-connection breakers are replaced on reconnect)."""
        return self._breaker_opens_accum + self.breaker.opens_total

    def stats(self, timeout=5.0):
        reply = self._rpc({"type": "stats"}, timeout=timeout)
        snap = dict(self._last_stats)
        if reply is None:
            snap["health_state"] = self.health_state()
        snap["breaker_client"] = self.breaker.snapshot()
        snap["breaker_opens_lifetime"] = self.breaker_opens_total()
        snap["reconnects_total"] = self.reconnects_total
        snap["last_seen_age_s"] = (
            None if self._last_seen is None
            else round(time.monotonic() - self._last_seen, 3))
        return snap

    def refresh(self, timeout=2.0):
        """One membership heartbeat: reconnect if dead (the rejoin
        path), then refresh cached stats. Returns True when the remote
        answered."""
        if self._closed:
            return False
        if not self.alive():
            self.start()
            if not self.alive():
                return False
        return self._rpc({"type": "stats"},
                         timeout=timeout) is not None

    def fetch_artifact(self, relpath, timeout=30.0):
        """One model-dir file over the wire (verified against the
        server's sha256). Raises on transport failure or damage."""
        reply = self._rpc({"type": "fetch_artifact", "path": relpath},
                          timeout=timeout)
        if reply is None:
            raise net.RemoteUnavailableError(
                f"fetch_artifact({relpath!r}) from {self.name} got "
                "no reply")
        if reply.get("type") == "error":
            net.raise_wire_error(reply["error"])
        blob = reply["blob"]
        if net.hash_blob(blob) != reply.get("sha256"):
            raise net.FrameError(
                "crc-mismatch",
                f"{relpath} blob sha256 mismatch in transit")
        return blob

    def metrics_obj(self):
        return None     # metrics live server-side; stats() fetches

    def crash(self):
        """Chaos: sever the link abruptly (the network analogue of
        SIGKILL — the server never hears a goodbye)."""
        self._mark_dead(WorkerDiedError(
            f"replica {self.name} link severed (chaos)"))
