"""ReplicaServer — one serving replica behind a TCP socket.

The cross-host half of the replica story: where ``proc_worker`` serves
a ``save_inference_model`` directory to its parent over a stdio pipe,
:class:`ReplicaServer` serves the same engine to ANY number of
concurrent client connections over sockets (``cluster/net.py`` frames:
magic + version + CRC32, restricted unpickling, handshake auth). A
fresh host needs nothing but this module and a saved-model dir — and
with the ``fetch_manifest`` / ``fetch_artifact`` verbs it does not even
need the dir: a peer can provision itself over the wire
(:func:`provision_from_remote`), ``__artifacts__`` blobs included, so
the new replica warms with ZERO XLA compiles and no shared filesystem.

Wire verbs (after the hello/welcome handshake)::

    {"type": "submit", "id": n, "feed": {...}, "timeout": s | None}
        -> {"type": "result", "id": n, "value": [arrays]}
         | {"type": "error", "id": n, "error": (type_name, message)}
    {"type": "stats", "id": n}   -> {"type": "stats", "id": n, "value": {...}}
    {"type": "ping", "id": n}    -> {"type": "pong", "id": n}
    {"type": "fetch_manifest", "id": n}
        -> {"type": "manifest", "id": n,
            "value": {relpath: {"sha256": ..., "bytes": n}}}
    {"type": "fetch_artifact", "id": n, "path": relpath}
        -> {"type": "artifact", "id": n, "path": relpath,
            "blob": bytes, "sha256": ...}
    {"type": "bye"}              -> connection closed (server stays up)

A protocol error on one connection (alien bytes, CRC damage, a
disallowed pickle global) answers with a typed ``protocol_error`` frame
when the socket still works, then closes THAT connection — the server
and its other clients keep serving. Closing a client connection never
drains the engine; :meth:`ReplicaServer.close` is the deploy boundary.

Run in-process (tests, loopback benches) or as a host entrypoint::

    python -m paddle_tpu.cluster.net_worker --dir <saved_model_dir> \
        --port 7711 [--token-env PADDLE_TPU_NET_TOKEN]
"""
import argparse
import os
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from ..io.artifact_store import dir_manifest
from . import net

__all__ = ["ReplicaServer", "provision_from_remote"]

_HANDSHAKE_TIMEOUT_S = 10.0


class ReplicaServer:
    """Serve a ``save_inference_model`` directory over TCP.

    ``port=0`` picks a free port (read it back from ``.port``).
    ``token=None`` uses the shared-env default. ``engine_kw`` forwards
    ServingConfig knobs exactly like ProcessReplica does. The engine
    is built (and warmed, unless ``warmup=False``) at construction, so
    ``.warmup_report`` answers the zero-compile question before the
    first client connects.

    ``engine=`` serves a pre-built engine instead (a DecodeEngine for
    disaggregated decode serving: submit feeds are prompt arrays, the
    extra ``handoff`` wire verb adopts KV handoff blobs); model_dir
    may then be None — the artifact verbs refuse politely."""

    def __init__(self, model_dir, host="127.0.0.1", port=0,
                 token=None, name=None, warmup=True, max_workers=8,
                 backlog=16, engine=None, **engine_kw):
        from ..serving import ServingConfig, ServingEngine
        self.model_dir = (None if model_dir is None
                          else os.path.abspath(model_dir))
        self._token = token
        if engine is not None:
            if engine_kw:
                raise TypeError(
                    "pass engine_kw only when the server builds the "
                    f"engine itself, got both engine= and {engine_kw}")
            self.engine = engine
        else:
            self.engine = ServingEngine.from_saved_model(
                self.model_dir,
                config=ServingConfig(**engine_kw) if engine_kw
                else None)
        self.warmup_report = self.engine.warmup() if warmup else None
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers,
            thread_name_prefix="replica-net-serve")
        self._closed = threading.Event()
        self._conns = set()
        self._conns_lock = threading.Lock()
        self._counters = {"connections_total": 0,
                          "handshake_refused_total": 0,
                          "protocol_errors_total": 0,
                          "artifacts_served_total": 0}
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(backlog)
        self.host, self.port = self._listener.getsockname()[:2]
        self.name = name or f"net-replica@{self.host}:{self.port}"
        self._acceptor = threading.Thread(
            target=self._accept_loop, name=f"{self.name}-accept",
            daemon=True)
        self._acceptor.start()

    @property
    def addr(self):
        return f"{self.host}:{self.port}"

    def total_compiles(self):
        """XLA compiles this server's engine has performed — the
        remote-provisioning gate reads 0 here when the model dir
        carried a seeded ``__artifacts__`` store."""
        return self.engine.exe.total_compiles()

    def _incr(self, key, n=1):
        with self._conns_lock:
            self._counters[key] += n

    # -- accept / per-connection ----------------------------------------
    def _accept_loop(self):
        while not self._closed.is_set():
            try:
                sock, peer = self._listener.accept()
            except OSError:
                return              # listener closed: shutting down
            self._incr("connections_total")
            with self._conns_lock:
                self._conns.add(sock)
            threading.Thread(
                target=self._serve_conn, args=(sock, peer),
                name=f"{self.name}-conn", daemon=True).start()

    def _drop_conn(self, sock):
        with self._conns_lock:
            self._conns.discard(sock)
        try:
            sock.close()
        except OSError:
            pass

    def _serve_conn(self, sock, peer):
        write_lock = threading.Lock()

        def send(obj):
            with write_lock:
                # racecheck: ok(blocking-under-lock) — the lock exists
                # ONLY to serialize frame writes on this socket (pool
                # threads answer concurrently); nothing else ever
                # waits on it
                net.send_frame(sock, obj)

        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            deadline = time.monotonic() + _HANDSHAKE_TIMEOUT_S
            hello = net.recv_frame(sock, deadline=deadline)
            if hello is None:
                return
            refusal = net.check_hello(hello, token=self._token)
            if refusal is not None:
                self._incr("handshake_refused_total")
                send({"type": "reject", "reason": refusal})
                return
            send({"type": "welcome", "name": self.name,
                  "fingerprint": net.schema_fingerprint(),
                  "warmup": self.warmup_report,
                  "stats": self.engine.stats()})
            while not self._closed.is_set():
                msg = net.recv_frame(sock)
                # protocheck: ok(verb-asymmetric) — 'bye' is the
                # socket-only polite hangup; the pipe transport's
                # equivalent is simply closing the child's stdin (EOF)
                if msg is None or msg.get("type") == "bye":
                    return
                self._dispatch(msg, send)
        except net.FrameError as exc:
            # this CONNECTION is damaged; tell the peer (typed, best
            # effort) and drop it — the server keeps serving others
            self._incr("protocol_errors_total")
            try:
                send({"type": "protocol_error",
                      "error": net.wire_error(exc)})
            except Exception:       # noqa: BLE001 — socket is gone
                pass
        except (OSError, net.RemoteUnavailableError,
                net.RequestTimeoutError):
            pass                    # peer vanished mid-frame
        finally:
            self._drop_conn(sock)

    def _dispatch(self, msg, send):
        kind = msg.get("type")
        req_id = msg.get("id")
        if kind == "submit":
            self._pool.submit(self._serve_one, req_id, msg.get("feed"),
                              msg.get("timeout"), send,
                              msg.get("kw") or {})
        elif kind == "handoff":
            self._pool.submit(self._serve_handoff, req_id,
                              msg.get("state"), msg.get("timeout"),
                              send, msg.get("kw") or {})
        elif kind == "stats":
            send({"type": "stats", "id": req_id,
                  "value": self.stats()})
        # protocheck: ok(verb-dead) — liveness probe for operators and
        # external monitors (nc/ncat a frame, get a pong); in-tree
        # clients use 'stats' for health because it refreshes the
        # membership view's metrics at the same time
        elif kind == "ping":
            send({"type": "pong", "id": req_id})
        # protocheck: ok(verb-asymmetric) — artifact provisioning is
        # socket-only by design: a pipe replica is a child process on
        # the same host and shares the parent's filesystem, so it
        # never fetches artifacts over its own wire
        elif kind == "fetch_manifest":
            if self.model_dir is None:
                send({"type": "error", "id": req_id,
                      "error": ("ServingError",
                                "this server has no model dir to "
                                "serve artifacts from")})
                return
            send({"type": "manifest", "id": req_id,
                  "value": dir_manifest(self.model_dir)})
        # protocheck: ok(verb-asymmetric) — socket-only, same reason
        # as fetch_manifest: pipe replicas share the host filesystem
        elif kind == "fetch_artifact":
            self._send_artifact(req_id, msg.get("path"), send)
        else:
            send({"type": "error", "id": req_id,
                  "error": ("ServingError",
                            f"unknown verb {kind!r}")})

    @staticmethod
    def _wire_slo(kw):
        """An SLO crosses the wire as a plain dict (the restricted
        unpickler refuses custom classes — by design); rebuild the
        SLOClass server-side."""
        slo = kw.get("slo")
        if isinstance(slo, dict):
            from ..serving import SLOClass
            kw["slo"] = SLOClass(**slo)
        return kw

    def _serve_one(self, req_id, feed, timeout, send, kw=None):
        try:
            if hasattr(self.engine, "infer"):       # ServingEngine
                value = self.engine.infer(feed, timeout=timeout)
            else:                                   # DecodeEngine
                import numpy as np
                handle = self.engine.submit(
                    np.asarray(feed), timeout=timeout,
                    **self._wire_slo(dict(kw or {})))
                value = handle.result(
                    None if timeout is None else float(timeout) + 10.0)
            send({"type": "result", "id": req_id, "value": value})
        except Exception as exc:        # noqa: BLE001 — forwarded
            try:
                send({"type": "error", "id": req_id,
                      "error": net.wire_error(exc)})
            except Exception:           # noqa: BLE001 — conn gone; the
                pass                    # client's deadline covers it

    def _serve_handoff(self, req_id, state, timeout, send, kw=None):
        try:
            handle = self.engine.import_handoff(
                state, timeout=timeout,
                **self._wire_slo(dict(kw or {})))
            value = handle.result(
                None if timeout is None else float(timeout) + 10.0)
            send({"type": "result", "id": req_id, "value": value})
        except Exception as exc:        # noqa: BLE001 — forwarded
            try:
                send({"type": "error", "id": req_id,
                      "error": net.wire_error(exc)})
            except Exception:           # noqa: BLE001 — conn gone
                pass

    def _send_artifact(self, req_id, relpath, send):
        """One file of the model dir, path-confined and checksummed —
        the remote-provisioning primitive."""
        try:
            if not isinstance(relpath, str) or os.path.isabs(relpath):
                raise ValueError(f"artifact path must be relative, "
                                 f"got {relpath!r}")
            full = os.path.realpath(
                os.path.join(self.model_dir, relpath))
            if not (full + os.sep).startswith(
                    os.path.realpath(self.model_dir) + os.sep) \
                    and full != os.path.realpath(self.model_dir):
                raise ValueError(
                    f"artifact path {relpath!r} escapes the model dir")
            with open(full, "rb") as f:
                blob = f.read()
        except (OSError, ValueError) as exc:
            send({"type": "error", "id": req_id,
                  "error": net.wire_error(
                      exc if isinstance(exc, ValueError)
                      else ValueError(str(exc)))})
            return
        self._incr("artifacts_served_total")
        send({"type": "artifact", "id": req_id, "path": relpath,
              "blob": blob, "sha256": net.hash_blob(blob)})

    # -- introspection / lifecycle ---------------------------------------
    def stats(self):
        snap = self.engine.stats()
        with self._conns_lock:
            snap.update(self._counters)
            snap["open_connections"] = len(self._conns)
        snap["addr"] = self.addr
        snap["total_compiles"] = self.total_compiles()
        return snap

    def close(self, drain=False, drain_timeout=None):
        """Stop accepting, drop every connection, shut the engine down
        (``drain=True`` lets admitted work finish first)."""
        self._closed.set()
        # shutdown BEFORE close: merely closing the fd leaves a thread
        # blocked in accept() stuck (Linux); shutdown wakes it with a
        # typed OSError immediately, so close() returns fast instead
        # of eating the full acceptor join timeout
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        self.engine.close(drain=drain, drain_timeout=drain_timeout)
        self._pool.shutdown(wait=True)
        with self._conns_lock:
            conns = list(self._conns)
        for sock in conns:
            self._drop_conn(sock)
        self._acceptor.join(5.0)
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# remote provisioning
# ---------------------------------------------------------------------------


def provision_from_remote(addr, dest_dir, token=None, timeout=120.0):
    """Materialize a saved-model directory from a running
    :class:`ReplicaServer` — no shared filesystem: fetch the file
    manifest, then every file (``__artifacts__`` blobs and the warmup
    manifest included) over ``fetch_artifact``, each verified against
    its sha256 before it touches disk. Returns a report dict; a fresh
    ``ReplicaServer(dest_dir)`` afterwards warms the exporter's bucket
    set with zero XLA compiles."""
    t0 = time.monotonic()
    deadline = None if timeout is None else t0 + float(timeout)
    sock, _welcome = net.open_conn(addr, token=token,
                                   deadline=deadline)
    total = 0
    try:
        net.send_frame(sock, {"type": "fetch_manifest", "id": 0},
                       deadline=deadline)
        reply = net.recv_frame(sock, deadline=deadline)
        if reply is None or reply.get("type") != "manifest":
            if reply is not None and reply.get("type") == "error":
                net.raise_wire_error(reply["error"])
            raise net.FrameError(
                "alien-magic", f"expected a manifest frame, got "
                f"{None if reply is None else reply.get('type')!r}")
        manifest = reply["value"]
        os.makedirs(dest_dir, exist_ok=True)
        for i, (relpath, spec) in enumerate(sorted(manifest.items())):
            net.send_frame(sock, {"type": "fetch_artifact",
                                  "id": i + 1, "path": relpath},
                           deadline=deadline)
            got = net.recv_frame(sock, deadline=deadline)
            if got is None:
                raise net.RemoteUnavailableError(
                    f"{addr} closed the connection mid-provision")
            if got.get("type") == "error":
                net.raise_wire_error(got["error"])
            blob = got["blob"]
            if net.hash_blob(blob) != spec["sha256"]:
                raise net.FrameError(
                    "crc-mismatch",
                    f"{relpath} arrived with sha256 != manifest — "
                    "refusing to provision from damaged bytes")
            full = os.path.join(dest_dir, relpath)
            os.makedirs(os.path.dirname(full) or dest_dir,
                        exist_ok=True)
            with open(full, "wb") as f:
                f.write(blob)
            total += len(blob)
        try:
            net.send_frame(sock, {"type": "bye"})
        except Exception:           # noqa: BLE001 — best-effort bye
            pass
    finally:
        sock.close()
    return {"files": len(manifest), "bytes": total,
            "wall_s": round(time.monotonic() - t0, 3)}


# ---------------------------------------------------------------------------
# host entrypoint
# ---------------------------------------------------------------------------


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="serve a save_inference_model dir over TCP")
    ap.add_argument("--dir", required=True)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=7711)
    ap.add_argument("--no-warmup", action="store_true")
    ap.add_argument("--max-workers", type=int, default=8)
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--default-timeout-s", type=float, default=30.0)
    args = ap.parse_args(argv)
    # racecheck: ok(global-mutation) — this IS the process entrypoint:
    # it owns the whole process and runs before any thread or jax
    # backend exists
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import paddle_tpu as fluid
    # racecheck: ok(global-mutation) — ditto: entrypoint-owned process,
    # called once before the first device op
    fluid.force_cpu()
    server = ReplicaServer(
        args.dir, host=args.host, port=args.port,
        warmup=not args.no_warmup, max_workers=args.max_workers,
        max_wait_ms=args.max_wait_ms, max_queue=args.max_queue,
        default_timeout_s=args.default_timeout_s)
    print(f"replica server ready on {server.addr} "
          f"(compiles={server.total_compiles()})", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.close(drain=True)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
