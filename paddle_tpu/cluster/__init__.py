"""paddle_tpu.cluster — multi-replica serving: router, replica pool,
health-aware balancing, zero-downtime rolling restart.

One engine is one worker thread on one process — the ceiling of the
serving story no matter how good its batching gets. This package
lifts serving one level (the reference Paddle's trainer/pserver split
and the TF-Serving replica tier, arXiv:1605.08695): a
:class:`ReplicaPool` owns N identical engine replicas (in-process by
default; :class:`ProcessReplica` drives the same interface over a
separate OS process), and a :class:`Router` spreads traffic across
them with pluggable balancing (round-robin, least-outstanding, and
health-aware weighting that reads each replica's existing
HealthMonitor + circuit-breaker state), cluster-level admission
control, transparent failover, and merged pool-wide metrics. The pool
revives crashed replicas and rolls restarts one replica at a time —
zero lost requests under load, proven by the chaos suite and
``tools/servebench.py --cluster --rolling-restart``.

    from paddle_tpu import cluster, serving

    def factory():
        return serving.ServingEngine.from_saved_model("./model_dir")

    router = cluster.serve_cluster(factory, replicas=2, warmup=True)
    out = router.infer({"img": x})       # balanced, failover-protected
    router.pool.rolling_restart()        # zero-downtime deploy
    router.close(drain=True)

See docs/SERVING.md "Running a replica pool".
"""
from .pool import ReplicaPool                                    # noqa: F401
from .replica import InProcessReplica, ProcessReplica, Replica   # noqa: F401
from .router import (BalancePolicy, ClusterOverloadError,        # noqa: F401
                     HealthAwarePolicy, LeastOutstandingPolicy,
                     NoReadyReplicaError, POLICIES, RoundRobinPolicy,
                     Router, get_policy)

__all__ = ["BalancePolicy", "ClusterOverloadError",
           "HealthAwarePolicy", "InProcessReplica",
           "LeastOutstandingPolicy", "NoReadyReplicaError", "POLICIES",
           "ProcessReplica", "Replica", "ReplicaPool",
           "RoundRobinPolicy", "Router", "get_policy", "serve_cluster"]


def serve_cluster(factory, replicas=2, policy="health_aware",
                  warmup=False, max_cluster_queue=None,
                  revive_interval_s=0.25):
    """One call from engine factory to balanced, self-healing router:
    builds a :class:`ReplicaPool` of ``replicas`` engines and fronts
    it with a :class:`Router`. The router owns the pool (closing the
    router closes the pool)."""
    pool = ReplicaPool(factory, replicas=replicas, warmup=warmup,
                       revive_interval_s=revive_interval_s)
    return Router(pool, policy=policy,
                  max_cluster_queue=max_cluster_queue)
