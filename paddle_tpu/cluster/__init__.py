"""paddle_tpu.cluster — multi-replica serving: router, replica pool,
health-aware balancing, zero-downtime rolling restart.

One engine is one worker thread on one process — the ceiling of the
serving story no matter how good its batching gets. This package
lifts serving one level (the reference Paddle's trainer/pserver split
and the TF-Serving replica tier, arXiv:1605.08695): a
:class:`ReplicaPool` owns N identical engine replicas (in-process by
default; :class:`ProcessReplica` drives the same interface over a
separate OS process), and a :class:`Router` spreads traffic across
them with pluggable balancing (round-robin, least-outstanding, and
health-aware weighting that reads each replica's existing
HealthMonitor + circuit-breaker state), cluster-level admission
control, transparent failover, and merged pool-wide metrics. The pool
revives crashed replicas and rolls restarts one replica at a time —
zero lost requests under load, proven by the chaos suite and
``tools/servebench.py --cluster --rolling-restart``.

    from paddle_tpu import cluster, serving

    def factory():
        return serving.ServingEngine.from_saved_model("./model_dir")

    router = cluster.serve_cluster(factory, replicas=2, warmup=True)
    out = router.infer({"img": x})       # balanced, failover-protected
    router.pool.rolling_restart()        # zero-downtime deploy
    router.close(drain=True)

Across HOSTS, the same data plane rides the socket fabric
(``cluster/net.py`` CRC-framed transport, handshake auth, per-
connection circuit breakers, deadline-aware RPC, membership with
staleness eviction — docs/DISTRIBUTED.md "Serving across hosts")::

    # on each serving host:   python -m paddle_tpu.cluster.net_worker \
    #                             --dir ./model_dir --port 7711
    router = cluster.serve_remotes(["10.0.0.5:7711", "10.0.0.6:7711"])
    out = router.infer({"img": x})       # identical client contract

On top of the pool, ``cluster/deploy.py`` closes the deployment loop
(*ship, observe, revert*): a :class:`DeploymentManager` names
immutable model versions, dark-deploys a canary behind router version
weights (``Router.set_weights``), gates promotion on a pinned
golden-set numerics check plus error-rate/p99 guardrails, and
auto-rolls-back with zero lost requests and zero re-warm compiles —
docs/SERVING.md "Deploying a new version".

See docs/SERVING.md "Running a replica pool".
"""
from .deploy import (DeploymentError, DeploymentManager,         # noqa: F401
                     Guardrails, ModelVersion, check_numerics,
                     evaluate_guardrails)
from .membership import Membership, serve_remotes                # noqa: F401
from .net import (FrameError, HandshakeError,                    # noqa: F401
                  RemoteUnavailableError)
from .net_worker import ReplicaServer, provision_from_remote     # noqa: F401
from .pool import ReplicaPool                                    # noqa: F401
from .remote import RemoteReplica                                # noqa: F401
from .replica import InProcessReplica, ProcessReplica, Replica   # noqa: F401
from .router import (BalancePolicy, ClusterOverloadError,        # noqa: F401
                     HealthAwarePolicy, LeastOutstandingPolicy,
                     NoReadyReplicaError, POLICIES, RoundRobinPolicy,
                     Router, get_policy)
from .train_fabric import (CommitMismatch, LinRegTask,           # noqa: F401
                           NoTrainWorkersError, ProgramGradTask,
                           TrainCoordinator, TrainTaskError,
                           WorkerClient, task_from_spec)
from .train_worker import TrainWorkerServer                      # noqa: F401

__all__ = ["BalancePolicy", "ClusterOverloadError", "CommitMismatch",
           "DeploymentError",
           "DeploymentManager", "FrameError", "Guardrails",
           "HandshakeError", "HealthAwarePolicy", "InProcessReplica",
           "LeastOutstandingPolicy", "LinRegTask", "Membership",
           "ModelVersion",
           "NoReadyReplicaError", "NoTrainWorkersError", "POLICIES",
           "ProcessReplica", "ProgramGradTask",
           "RemoteReplica", "RemoteUnavailableError", "Replica",
           "ReplicaPool", "ReplicaServer", "RoundRobinPolicy",
           "Router", "TrainCoordinator", "TrainTaskError",
           "TrainWorkerServer", "WorkerClient", "check_numerics",
           "evaluate_guardrails",
           "get_policy", "provision_from_remote", "serve_cluster",
           "serve_remotes", "task_from_spec"]


def serve_cluster(factory, replicas=2, policy="health_aware",
                  warmup=False, max_cluster_queue=None,
                  revive_interval_s=0.25):
    """One call from engine factory to balanced, self-healing router:
    builds a :class:`ReplicaPool` of ``replicas`` engines and fronts
    it with a :class:`Router`. The router owns the pool (closing the
    router closes the pool)."""
    pool = ReplicaPool(factory, replicas=replicas, warmup=warmup,
                       revive_interval_s=revive_interval_s)
    return Router(pool, policy=policy,
                  max_cluster_queue=max_cluster_queue)
