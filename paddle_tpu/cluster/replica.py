"""Replica wrappers — the unit the router balances over.

A replica is one serving engine plus the lifecycle state the pool
needs around it (restarting flag, revival, rebuild). Two backings
share one interface, so the same Router drives either:

- :class:`InProcessReplica` — the tested default: the engine lives in
  this process (its own worker thread, its own Executor compile
  cache; parameters may share a read-only scope). Death is a dead
  worker thread; revival is ``engine.start()``; a rolling-restart
  rebuild constructs a FRESH engine from the factory (a closed
  engine's admission queue never reopens — by design, close is a
  deploy boundary).
- :class:`ProcessReplica` — the same engine behind a separate OS
  process (``cluster/proc_worker.py`` serves a ``save_inference_model``
  directory over CRC-framed, restricted-unpickle ``cluster/net.py``
  frames on stdin/stdout).
  Death is process exit (chaos ``crash()`` is a real SIGKILL);
  revival/rebuild respawn the process, which re-warms from the
  artifact's serving manifest — the process-level half of the
  scale-out story, and the template for host-level replicas.

Interface contract (everything the Router/Pool touch):
``submit(item, timeout=, **kw)`` returning a settled-once handle with
``wait``/``result``; ``outstanding()``; ``health_state()``;
``admits()`` (breaker read); ``alive()``; ``start()`` (revive in
place); ``rebuild()`` (fresh engine); ``close(drain=)``; ``warmup()``;
``stats()``; ``metrics_obj()`` (a ServingMetrics for pool merging, or
None); ``crash()`` (chaos).
"""
import os
import subprocess
import sys
import threading
import time

from ..serving.batching import (PendingResult, ServerClosedError,
                                ServingError)
from ..serving.health import HealthState, WorkerDiedError
# the pipe protocol speaks the SAME hardened frame format as the
# socket fabric (magic + version + CRC32, restricted unpickling): a
# stray write to the protocol fd is a typed FrameError on either
# transport, never pickle garbage
from .net import FrameError, WIRE_ERRORS, read_frame, write_frame

__all__ = ["Replica", "InProcessReplica", "ProcessReplica",
           "read_frame", "write_frame"]


class Replica:
    """Base: naming + the restarting flag the router honors."""

    def __init__(self, name):
        self.name = name
        self.restarting = False     # rolling restart steers traffic away
        self.last_rebuild_report = None   # warmup report of last rebuild
        self.version = None         # deployment label (cluster/deploy.py)
        # disaggregated serving role: None (any work), "prefill"
        # (prefill_only submits that resolve with KV handoff blobs), or
        # "decode" (accepts handoff() imports). The Router's
        # role-filtered candidate lists read this tag.
        self.role = None

    # every method below is backing-specific
    def submit(self, item, timeout=None, **kw):
        raise NotImplementedError

    def handoff(self, state, timeout=None, **kw):
        """Adopt a KV handoff blob (decode engines only) — the decode
        half of prefill/decode disaggregation. Returns a settled-once
        handle like submit()."""
        raise NotImplementedError(
            f"{type(self).__name__} does not accept KV handoffs")

    def outstanding(self):
        raise NotImplementedError

    def health_state(self):
        raise NotImplementedError

    def admits(self):
        raise NotImplementedError

    def alive(self):
        raise NotImplementedError

    def start(self):
        raise NotImplementedError

    def rebuild(self, warmup=True, factory=None):
        raise NotImplementedError

    def close(self, drain=False, drain_timeout=None):
        raise NotImplementedError

    def warmup(self):
        raise NotImplementedError

    def stats(self):
        raise NotImplementedError

    def metrics_obj(self):
        return None

    def crash(self):
        raise NotImplementedError

    def __repr__(self):
        return (f"{type(self).__name__}({self.name!r}, "
                f"state={self.health_state()}, "
                f"outstanding={self.outstanding()})")


class InProcessReplica(Replica):
    """One engine (ServingEngine or DecodeEngine) in this process.

    ``factory`` is a zero-arg callable returning a STARTED engine; the
    replica calls it at construction and again on ``rebuild()`` —
    engines built from one factory must share nothing mutable (a
    read-only parameter scope is fine; that is what
    ``Inferencer.serve(replicas=N)`` does)."""

    def __init__(self, factory, name="replica", warmup=False,
                 engine=None, role=None):
        super().__init__(name)
        self._factory = factory
        self._engine = engine if engine is not None else factory()
        self.role = role
        if warmup:
            self._engine.warmup()

    @property
    def engine(self):
        return self._engine

    def submit(self, item, timeout=None, **kw):
        return self._engine.submit(item, timeout=timeout, **kw)

    def handoff(self, state, timeout=None, **kw):
        return self._engine.import_handoff(state, timeout=timeout, **kw)

    def outstanding(self):
        return self._engine.outstanding()

    def health_state(self):
        return self._engine.health.state

    def admits(self):
        return self._engine.breaker.admits()

    def alive(self):
        return self._engine.worker_alive()

    def start(self):
        """Revive after a worker death — same engine, same compile
        cache, so revival is milliseconds, not a re-warm."""
        self._engine.start()
        return self

    def rebuild(self, warmup=True, factory=None):
        """Fresh engine from the factory (the rolling-restart /
        deploy-rollover path; the caller has already drained and
        closed the old one). Passing ``factory=`` swaps the replica
        onto a NEW factory first — that is how a canary deploy (and
        its rollback) converts a drained replica to another model
        version in place, keeping the pool's membership stable. The
        warmup report is stashed on ``last_rebuild_report`` — with a
        compiled-artifact store behind the factory's engines it shows
        ``compiles: 0``, the proof that restart cost is load-bound,
        not compile-bound."""
        if factory is not None:
            self._factory = factory
        self._engine = self._factory()
        self.last_rebuild_report = (self._engine.warmup() if warmup
                                    else None)
        return self

    def close(self, drain=False, drain_timeout=None):
        self._engine.close(drain=drain, drain_timeout=drain_timeout)
        return self

    def warmup(self):
        return self._engine.warmup()

    def stats(self):
        return self._engine.stats()

    def metrics_obj(self):
        return self._engine.metrics

    def crash(self):
        self._engine._simulate_worker_crash()


# ---------------------------------------------------------------------------
# process-backed replica
# ---------------------------------------------------------------------------

# typed serving errors the worker process forwards by class name (the
# shared wire vocabulary of cluster/net.py); the parent re-raises the
# same type so router/client retry classification is identical for
# every replica backing
_ERROR_TYPES = WIRE_ERRORS


class ProcessReplica(Replica):
    """A serving replica in its own OS process.

    The worker (``python -m paddle_tpu.cluster.proc_worker``) loads a
    ``save_inference_model`` directory, warms the buckets from its
    serving manifest, and serves pickle frames; this wrapper gives it
    the in-process replica interface so the Router cannot tell them
    apart. ``crash()`` is a real ``SIGKILL``; the pool's revival
    monitor then respawns the process.

    ``engine_kw`` forwards ServingConfig knobs (max_wait_ms,
    max_queue, default_timeout_s) to the worker's engine.

    ``decode=True`` serves a :func:`~paddle_tpu.models.llama.
    save_decode_model` directory with a DecodeEngine instead
    (engine_kw then forwards DecodeConfig knobs: max_batch, page_size,
    chunk_size, scheduler, ...); such a worker also answers the
    ``handoff`` verb, and ``role`` tags the replica for the router's
    disaggregated placement."""

    READY_TIMEOUT_S = 120.0    # process start + jax import + warmup

    def __init__(self, model_dir, name="proc-replica", warmup=True,
                 stderr=None, decode=False, role=None, **engine_kw):
        super().__init__(name)
        self.model_dir = os.path.abspath(model_dir)
        self.decode = bool(decode)
        self.role = role
        self.engine_kw = dict(engine_kw)
        self._do_warmup = bool(warmup)
        self._stderr = stderr
        self._lock = threading.Lock()       # write side + pending map
        self._pending = {}                  # id -> PendingResult
        self._stats_waiters = {}            # id -> [event, payload]
        self._next_id = 0
        self._proc = None
        self._reader = None
        self._ready = threading.Event()
        self._last_stats = {}
        self._warmup_report = None
        self._closed = False
        self._spawn()

    # -- process lifecycle ----------------------------------------------
    def _spawn(self):
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = repo_root + os.pathsep \
            + env.get("PYTHONPATH", "")
        cmd = [sys.executable, "-m", "paddle_tpu.cluster.proc_worker",
               "--dir", self.model_dir]
        if self.decode:
            cmd.append("--decode")
        if not self._do_warmup:
            cmd.append("--no-warmup")
        for k, v in self.engine_kw.items():
            cmd += [f"--{k.replace('_', '-')}", str(v)]
        self._ready.clear()
        self._closed = False
        self._proc = subprocess.Popen(
            cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=self._stderr if self._stderr is not None
            else subprocess.DEVNULL,
            env=env, cwd=repo_root)
        self._reader = threading.Thread(
            target=self._reader_loop, name=f"{self.name}-reader",
            daemon=True)
        self._reader.start()

    def wait_ready(self, timeout=None):
        """Block until the worker reported ready (engine loaded +
        warmed). Raises WorkerDiedError if it exited first."""
        if not self._ready.wait(self.READY_TIMEOUT_S
                                if timeout is None else timeout):
            raise WorkerDiedError(
                f"replica process {self.name} never became ready")
        if not self.alive() and not self._ready.is_set():
            raise WorkerDiedError(
                f"replica process {self.name} died during startup")
        return self

    def _reader_loop(self):
        proc = self._proc
        stream = proc.stdout
        # the try/finally is load-bearing: the reader thread is the
        # ONLY settler of pending requests, so it must fail them all
        # however it exits — clean EOF, protocol damage on the pipe, or
        # an unexpected bug in the dispatch below. Before this audit a
        # reader death during close(drain=True) (or any raising frame)
        # stranded pending requests past their deadlines.
        note = ""
        try:
            while True:
                msg = read_frame(stream)
                if msg is None:
                    break
                kind = msg.get("type")
                if kind == "ready":
                    self._last_stats = msg.get("stats") or {}
                    self._warmup_report = msg.get("warmup")
                    self._ready.set()
                elif kind == "result":
                    req = self._pop_pending(msg["id"])
                    if req is not None:
                        req.set_result(msg["value"])
                elif kind == "error":
                    req = self._pop_pending(msg["id"])
                    if req is not None:
                        name, text = msg["error"]
                        req.set_error(_ERROR_TYPES.get(
                            name, ServingError)(text))
                elif kind == "stats":
                    with self._lock:
                        waiter = self._stats_waiters.pop(
                            msg["id"], None)
                    self._last_stats = msg.get("value") or {}
                    if waiter is not None:
                        waiter[1] = self._last_stats
                        waiter[0].set()
        except FrameError as exc:
            note = f" (pipe protocol damage: {exc})"
        except (OSError, ValueError) as exc:
            note = f" (pipe read failed: {exc})"
        finally:
            # the process (or its protocol stream) is gone — nothing
            # it held will ever answer
            self._fail_all_pending(WorkerDiedError(
                f"replica process {self.name} exited "
                f"(rc={proc.poll()}){note}"))

    def _pop_pending(self, req_id):
        with self._lock:
            return self._pending.pop(req_id, None)

    def _fail_all_pending(self, exc):
        with self._lock:
            pending = list(self._pending.values())
            self._pending.clear()
            waiters = list(self._stats_waiters.values())
            self._stats_waiters.clear()
        for req in pending:
            req.set_error(exc)
        for waiter in waiters:
            waiter[0].set()

    # -- replica interface ----------------------------------------------
    def _send_pending(self, frame, timeout):
        """Register a pending handle and ship one request frame; the
        reader thread settles it (or fails it typed on worker death)."""
        if self._closed:
            raise ServerClosedError(f"replica {self.name} is closed")
        if not self.alive():
            raise WorkerDiedError(
                f"replica process {self.name} is dead")
        now = time.monotonic()
        req = PendingResult(
            feed=None, n_rows=1, signature=(),
            deadline=None if timeout is None else now + float(timeout),
            enqueued_at=now)
        with self._lock:
            self._next_id += 1
            req_id = self._next_id
            self._pending[req_id] = req
            frame["id"] = req_id
            try:
                # racecheck: ok(blocking-under-lock) — frames are far
                # smaller than the pipe buffer, so the write cannot
                # stall on an unread pipe; the lock orders the
                # pending-map insert with the write
                write_frame(self._proc.stdin, frame)
            except (OSError, ValueError) as exc:
                self._pending.pop(req_id, None)
                raise WorkerDiedError(
                    f"replica process {self.name} pipe broken: "
                    f"{exc}") from exc
        return req

    def submit(self, item, timeout=None, **kw):
        frame = {"type": "submit", "feed": item, "timeout": timeout}
        if kw:
            # wire-safe kwargs only (prefill_only, max_new, an SLO
            # passed as a plain dict); the decode worker rebuilds the
            # SLOClass on its side
            frame["kw"] = kw
        return self._send_pending(frame, timeout)

    def handoff(self, state, timeout=None, **kw):
        frame = {"type": "handoff", "state": state, "timeout": timeout}
        if kw:
            frame["kw"] = kw
        return self._send_pending(frame, timeout)

    def outstanding(self):
        with self._lock:
            return len(self._pending)

    def health_state(self):
        if self._closed:
            return HealthState.STOPPED
        if not self.alive():
            return HealthState.DEGRADED
        if not self._ready.is_set():
            return HealthState.STARTING
        return self._last_stats.get("health_state", HealthState.READY)

    def admits(self):
        breaker = self._last_stats.get("breaker") or {}
        return breaker.get("state", "closed") != "open"

    def alive(self):
        proc = self._proc
        return proc is not None and proc.poll() is None

    def start(self):
        """Revive a dead process (full respawn — the process's compile
        cache died with it; the serving manifest makes the re-warm
        deterministic)."""
        if self.alive():
            return self
        self._fail_all_pending(WorkerDiedError(
            f"replica process {self.name} died"))
        self._spawn()
        return self

    def rebuild(self, warmup=True, factory=None):
        """Respawn the worker process. For process replicas the
        "factory" is the saved-model directory itself, so a version
        deploy passes the new version's export dir here."""
        if factory is not None:
            if not isinstance(factory, (str, os.PathLike)):
                raise TypeError(
                    "ProcessReplica.rebuild(factory=) takes a "
                    "saved-model directory path, got "
                    f"{type(factory).__name__}")
            self.model_dir = os.path.abspath(os.fspath(factory))
        self._do_warmup = bool(warmup)
        self._spawn()
        return self

    def close(self, drain=False, drain_timeout=None):
        self._closed = True      # stop admitting here; the worker's
        proc = self._proc        # engine drains its own queue
        if proc is None or proc.poll() is not None:
            return self
        try:
            with self._lock:
                # racecheck: ok(blocking-under-lock) — one tiny close
                # frame, bounded by the pipe buffer; serialized against
                # concurrent submit writes on the same fd
                write_frame(proc.stdin,
                            {"type": "close", "drain": bool(drain),
                             "drain_timeout": drain_timeout})
        except (OSError, ValueError):
            pass
        budget = 10.0 if drain_timeout is None \
            else float(drain_timeout) + 5.0
        try:
            proc.wait(budget)
        except subprocess.TimeoutExpired:
            proc.kill()
        self._closed = True
        return self

    def warmup(self):
        """Warmup happens inside the worker at spawn; this just waits
        for (and returns) its report."""
        self.wait_ready()
        return self._warmup_report

    def stats(self, timeout=5.0):
        if not self.alive():
            snap = dict(self._last_stats)
            snap["health_state"] = self.health_state()
            return snap
        waiter = [threading.Event(), None]
        with self._lock:
            self._next_id += 1
            req_id = self._next_id
            self._stats_waiters[req_id] = waiter
            try:
                # racecheck: ok(blocking-under-lock) — tiny frame,
                # bounded by the pipe buffer; the lock orders the
                # waiter insert with the write
                write_frame(self._proc.stdin,
                            {"type": "stats", "id": req_id})
            except (OSError, ValueError):
                self._stats_waiters.pop(req_id, None)
                return dict(self._last_stats)
        waiter[0].wait(timeout)
        return dict(waiter[1] if waiter[1] is not None
                    else self._last_stats)

    def metrics_obj(self):
        return None     # metrics live in the worker; stats() fetches

    def crash(self):
        """A REAL SIGKILL — the strongest form of the replica-crash
        drill."""
        proc = self._proc
        if proc is not None and proc.poll() is None:
            proc.kill()
