"""ReplicaPool — owns N serving replicas and their lifecycle.

The pool is the control plane the Router (data plane) reads:

- **construction** — ``factory`` is a zero-arg callable returning a
  started engine (ServingEngine or DecodeEngine) or a ready
  :class:`~paddle_tpu.cluster.replica.Replica`; the pool builds
  ``replicas`` of them (warming each when ``warmup=True``) and names
  them ``replica-0..N-1``.
- **revival** — a monitor thread watches for dead replicas (worker
  thread died, process exited) and revives them in place
  (``replica.start()``: same compile cache for in-process replicas, a
  respawn for process replicas), counted in ``revives_total``. The
  engine-level watchdog already failed that replica's pending
  requests with WorkerDiedError; the router's failover resubmits
  them elsewhere meanwhile.
- **scaling** — ``scale_up()`` adds warmed replicas; ``scale_down()``
  drains and removes them (finish what they admitted, take nothing
  new) — traffic-spike response once artifact warmup is fast.
- **rolling restart** — ``rolling_restart()`` is the zero-downtime
  deploy: one replica at a time is flagged ``restarting`` (the router
  stops picking it), drained via the engine's own
  ``close(drain=True)``, rebuilt fresh from the factory, re-warmed,
  and put back. At most one replica is ever out of rotation, so the
  pool never reports fewer than N-1 READY replicas and — with the
  router steering — zero requests are lost (proven under load by
  ``tools/servebench.py --cluster --rolling-restart`` and the chaos
  suite).
- **stats** — per-replica snapshots plus a pool-wide merge:
  ``ServingMetrics.merge`` combines every in-process replica's
  registry into cluster p50/p95/p99 and counters under ``"cluster"``.
"""
import threading
import time

from ..serving.health import HealthState
from ..serving.metrics import ServingMetrics
from .replica import InProcessReplica, Replica

__all__ = ["ReplicaPool"]

_POOL_COUNTERS = ("revives_total", "restarts_total",
                  "cluster_shed_total", "reroutes_total",
                  "failovers_total", "handoffs_total",
                  "handoff_redrives_total",
                  # overload robustness (PR 19): cluster sheds broken
                  # out by priority tier (the shed-ordering proof),
                  # retry-budget exhaustions (a retry that failed fast
                  # instead of storming), and hedging (duplicates sent
                  # / duplicates that won)
                  "shed_interactive_total", "shed_standard_total",
                  "shed_batch_total", "retry_budget_exhausted_total",
                  "hedges_total", "hedge_wins_total")


class ReplicaPool:
    """N replicas from one factory + lifecycle orchestration.

    ``revive_interval_s`` is how often the monitor checks liveness
    (0 disables the monitor — tests drive ``revive_dead()`` by hand).
    """

    def __init__(self, factory, replicas=2, warmup=False,
                 revive_interval_s=0.25, name_prefix="replica"):
        if replicas < 1:
            raise ValueError("a pool needs at least one replica")
        self._factory = factory
        self._warmup = bool(warmup)
        self._prefix = name_prefix
        self._lock = threading.Lock()
        self._counters = {name: 0 for name in _POOL_COUNTERS}
        self._made = 0
        self._replicas = [self._make_replica() for _ in range(replicas)]
        self._closed = False
        self._closers = []       # companion shutdowns (membership, …)
        self._monitor = None
        self._monitor_stop = threading.Event()
        self.revive_interval_s = float(revive_interval_s)
        if self.revive_interval_s > 0:
            self._monitor = threading.Thread(
                target=self._monitor_loop,
                name="paddle-tpu-pool-monitor", daemon=True)
            self._monitor.start()

    def _make_replica(self):
        with self._lock:
            name = f"{self._prefix}-{self._made}"
            self._made += 1
        built = self._factory()
        if isinstance(built, Replica):
            built.name = name
            replica = built
            if self._warmup:
                replica.warmup()
        else:
            replica = InProcessReplica(self._factory, name=name,
                                       warmup=self._warmup,
                                       engine=built)
        return replica

    # -- views -----------------------------------------------------------
    def replicas(self):
        with self._lock:
            return list(self._replicas)

    def __len__(self):
        with self._lock:
            return len(self._replicas)

    def ready_count(self):
        return sum(r.alive() and not r.restarting
                   and r.health_state() == HealthState.READY
                   for r in self.replicas())

    def total_outstanding(self):
        return sum(r.outstanding() for r in self.replicas())

    def incr(self, name, n=1):
        with self._lock:
            self._counters[name] += n

    # -- lifecycle -------------------------------------------------------
    def warmup(self):
        """Warm every replica; returns the per-replica reports."""
        return {r.name: r.warmup() for r in self.replicas()}

    def scale_up(self, n=1):
        """Add ``n`` fresh (warmed, if the pool warms) replicas."""
        added = [self._make_replica() for _ in range(int(n))]
        with self._lock:
            self._replicas.extend(added)
        return added

    def scale_down(self, n=1, drain=True, drain_timeout=None):
        """Remove the ``n`` newest replicas; each finishes what it
        admitted (``drain=True``) before closing."""
        with self._lock:
            n = min(int(n), len(self._replicas) - 1)
            if n <= 0:
                return []
            removed = self._replicas[len(self._replicas) - n:]
            del self._replicas[len(self._replicas) - n:]
        for r in removed:
            r.close(drain=drain, drain_timeout=drain_timeout)
        return removed

    def revive_dead(self):
        """One revival sweep; returns the replicas revived. Called by
        the monitor thread (and directly by deterministic tests)."""
        revived = []
        if self._closed:
            return revived
        for r in self.replicas():
            if r.restarting or r.alive():
                continue
            if r.health_state() == HealthState.STOPPED:
                continue     # deliberately closed, not a death
            r.start()
            self.incr("revives_total")
            revived.append(r)
        return revived

    def _monitor_loop(self):
        while not self._monitor_stop.wait(self.revive_interval_s):
            if self._closed:
                return
            try:
                self.revive_dead()
            except Exception:                 # noqa: BLE001
                # a failed revival must not kill the monitor; the next
                # sweep retries (the replica stays ineligible while
                # dead, so traffic keeps flowing around it)
                pass

    def rolling_restart(self, drain_timeout=None, warmup=None):
        """Zero-downtime deploy: restart every replica, one at a time.

        Per replica: flag ``restarting`` (the router stops picking
        it) → ``close(drain=True)`` (every admitted request finishes,
        bounded by ``drain_timeout``) → rebuild fresh from the factory
        → warm up → back in rotation. Returns a report including
        ``min_ready_observed`` — with one-at-a-time rotation it is
        N-1 unless something ELSE failed mid-restart."""
        return self.restart_replicas(None, drain_timeout=drain_timeout,
                                     warmup=warmup)

    def restart_replicas(self, replicas=None, factory=None,
                         version=None, drain_timeout=None, warmup=None):
        """The generalized rolling restart: restart a SUBSET of
        replicas, optionally swapping them onto a different
        ``factory`` and stamping a ``version`` label — the primitive
        ``cluster/deploy.py`` uses both to convert k replicas to a
        canary version and to roll them back to the incumbent. Same
        zero-loss choreography as :meth:`rolling_restart` (flag →
        drain → rebuild → re-warm, one at a time), same report shape.
        ``replicas=None`` restarts every replica; a whole-pool restart
        onto a new ``factory`` also makes it the pool's factory for
        future ``scale_up()`` builds (the version won), while a SUBSET
        conversion leaves the pool's factory alone — ``scale_up()``
        during a canary must add incumbent capacity, never more
        unproven canaries."""
        warmup = self._warmup if warmup is None else bool(warmup)
        whole_pool = replicas is None
        targets = self.replicas() if whole_pool else list(replicas)
        if factory is not None and whole_pool:
            with self._lock:
                self._factory = factory
        t0 = time.monotonic()
        restarted = []
        rewarm = {}
        min_ready = None
        for r in targets:
            if self._closed:
                break
            r.restarting = True
            try:
                r.close(drain=True, drain_timeout=drain_timeout)
                # the moment of minimum capacity: old engine gone, new
                # one not yet built
                ready_now = self.ready_count()
                min_ready = (ready_now if min_ready is None
                             else min(min_ready, ready_now))
                if factory is None:
                    r.rebuild(warmup=warmup)
                else:
                    r.rebuild(warmup=warmup, factory=factory)
                if version is not None:
                    r.version = version
            finally:
                r.restarting = False
            self.incr("restarts_total")
            restarted.append(r.name)
            rewarm[r.name] = r.last_rebuild_report
        return {"restarted": restarted,
                "min_ready_observed": min_ready,
                "ready_after": self.ready_count(),
                # per-replica rewarm reports: with a compiled-artifact
                # store behind the factory these show compiles: 0 —
                # restart cost is loading, not XLA
                "rewarm": rewarm,
                "wall_s": round(time.monotonic() - t0, 3)}

    def register_closer(self, fn):
        """Register a zero-arg callable run at ``close()`` — the hook
        companion subsystems (the remote-fabric membership refresher)
        use to share the pool's lifecycle."""
        self._closers.append(fn)
        return self

    def close(self, drain=False, drain_timeout=None):
        self._closed = True
        for fn in self._closers:
            try:
                fn()
            except Exception:                 # noqa: BLE001
                pass         # a companion's failure must not block the
        self._monitor_stop.set()              # pool's own shutdown
        if self._monitor is not None:
            self._monitor.join(5.0)
            self._monitor = None
        for r in self.replicas():
            r.close(drain=drain, drain_timeout=drain_timeout)
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- stats -----------------------------------------------------------
    def stats(self):
        """Pool snapshot: lifecycle counters, per-replica summaries,
        and the merged cluster-wide metrics (pool p50/p95/p99 over
        every in-process replica's registry; process replicas report
        per-replica only — their registries live across the pipe)."""
        replicas = self.replicas()
        per = []
        metric_objs = []
        by_version = {}
        for r in replicas:
            per.append({"name": r.name,
                        "alive": r.alive(),
                        "health_state": r.health_state(),
                        "outstanding": r.outstanding(),
                        "admits": r.admits(),
                        "restarting": r.restarting,
                        "version": r.version})
            m = r.metrics_obj()
            if m is not None:
                metric_objs.append(m)
                if r.version is not None:
                    by_version.setdefault(r.version, []).append(m)
        with self._lock:
            snap = dict(self._counters)
        snap["n_replicas"] = len(replicas)
        snap["ready_replicas"] = sum(
            p["alive"] and not p["restarting"]
            and p["health_state"] == HealthState.READY for p in per)
        snap["total_outstanding"] = sum(p["outstanding"] for p in per)
        snap["replicas"] = per
        snap["cluster"] = (ServingMetrics.merge(*metric_objs).stats()
                           if metric_objs else None)
        # per-version merged views (a pool serving a canary beside its
        # incumbent): each version's replicas merge into their own
        # registry so the canary's error-rate/p99 is directly
        # comparable to the incumbent's — the numbers the promotion
        # guardrails read (cluster/deploy.py)
        snap["versions"] = ({str(v): ServingMetrics.merge(*ms).stats()
                             for v, ms in by_version.items()}
                            if by_version else None)
        return snap
