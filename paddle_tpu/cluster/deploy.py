"""Versioned deployments — canary traffic shifting, numerics-gated
promotion, instant zero-compile rollback.

The serving tier can cold-start any replica with zero XLA compiles
from the artifact store (io/artifact_store.py) and restart replicas
under load without losing a request (pool.rolling_restart), but those
are mechanisms; this module is the POLICY that closes the deployment
loop: *ship, observe, revert*.

A **version** is an immutable, nameable deployment unit — a
``save_inference_model`` directory plus everything embedded in it:
the ``__artifacts__`` compiled-executable snapshot, the params
manifest sha256, and the monotonically stamped ``model_version`` from
``__meta__.json``. :class:`DeploymentManager` lets one
:class:`~paddle_tpu.cluster.pool.ReplicaPool` serve two versions side
by side and walks a candidate through the production gauntlet:

1. **dark deploy** — k replicas are drained and converted to the
   canary's factory (the PR-7 zero-loss restart choreography, so no
   request is dropped by the conversion itself) while the router's
   version weights keep the canary at exactly zero traffic;
2. **numerics gate** — the canary replays a recorded golden-request
   set and its outputs are tolerance-compared against the incumbent's
   recorded references (optcheck-style ``|a-b| <= atol + rtol*|b|``,
   the TPU-MLIR verify-before-deploy discipline, arXiv:2210.15016)
   BEFORE any traffic touches it, and re-sampled at every ramp stage;
3. **staged ramp** — ``promote()`` walks the weight schedule
   (1% → 50% → 100% by default) and at each stage compares the
   canary's error rate and p99 against the incumbent's through the
   pool's per-version merged metrics, with configured guardrail
   margins;
4. **auto-reject + instant rollback** — any gate failure repoints the
   router weights to the incumbent (instant: the very next request
   draw cannot pick the canary) and rolls the canary replicas back to
   the incumbent's factory; the artifact store guarantees the re-warm
   performs ZERO compiles, and the drain-based restart guarantees
   zero lost requests.

Chaos coverage: the ``serving_canary_regression`` fault point
(resilience/faultinject.py) perturbs the canary's golden-set outputs
past any sane tolerance, so the auto-reject path is drillable —
``tools/servebench.py --canary`` runs the whole sequence under load
and is selfcheck stage 10. See docs/SERVING.md "Deploying a new
version".
"""
import os
import time

import numpy as np

from ..resilience import faultinject as _faultinject
from ..serving.metrics import ServingMetrics

__all__ = ["DeploymentError", "Guardrails", "ModelVersion",
           "DeploymentManager", "check_numerics",
           "evaluate_guardrails"]

# how hard the serving_canary_regression fault shoves the canary's
# outputs — far past any plausible promotion tolerance
_FAULT_PERTURBATION = 1.0


class DeploymentError(RuntimeError):
    """A deployment operation was impossible (no golden set, unknown
    version, canary already active, ...) — distinct from a REJECTED
    promotion, which is a normal, reported outcome."""


def check_numerics(reference, candidate, rtol=1e-5, atol=1e-7):
    """Tolerance-compare a candidate's golden-set outputs against the
    recorded references: every array must satisfy
    ``|got - ref| <= atol + rtol * |ref|`` elementwise (optcheck's
    comparison, applied to deployments). Returns a plain-dict report;
    shape/arity mismatches and non-finite drift fail loudly — a
    canary that changed its output contract must never promote."""
    report = {"ok": True, "n_requests": len(reference),
              "max_abs_err": 0.0, "max_rel_err": 0.0,
              "rtol": float(rtol), "atol": float(atol), "worst": None}
    if len(reference) != len(candidate):
        report["ok"] = False
        report["worst"] = (f"golden-set arity mismatch: "
                           f"{len(reference)} reference requests vs "
                           f"{len(candidate)} candidate")
        return report
    for i, (refs, gots) in enumerate(zip(reference, candidate)):
        if len(refs) != len(gots):
            report["ok"] = False
            report["worst"] = (f"request {i}: {len(refs)} reference "
                               f"fetches vs {len(gots)} candidate")
            return report
        for j, (ref, got) in enumerate(zip(refs, gots)):
            ref = np.asarray(ref, dtype=np.float64)
            got = np.asarray(got, dtype=np.float64)
            if ref.shape != got.shape:
                report["ok"] = False
                report["worst"] = (f"request {i} fetch {j}: shape "
                                   f"{got.shape} vs reference "
                                   f"{ref.shape}")
                return report
            abs_err = np.abs(got - ref)
            bound = atol + rtol * np.abs(ref)
            max_abs = float(abs_err.max()) if abs_err.size else 0.0
            denom = np.maximum(np.abs(ref), atol)
            max_rel = (float((abs_err / denom).max())
                       if abs_err.size else 0.0)
            report["max_abs_err"] = max(report["max_abs_err"], max_abs)
            report["max_rel_err"] = max(report["max_rel_err"], max_rel)
            bad = ~np.isfinite(got) | (abs_err > bound)
            if bad.any():
                report["ok"] = False
                if report["worst"] is None:
                    report["worst"] = (
                        f"request {i} fetch {j}: max |err| "
                        f"{max_abs:.3e} exceeds "
                        f"{atol:.1e} + {rtol:.1e}*|ref|")
    return report


class Guardrails:
    """The knobs a promotion must stay inside (docs/SERVING.md
    "Deploying a new version" documents each):

    - ``rtol``/``atol`` — numerics-gate tolerance for the golden-set
      comparison;
    - ``max_error_rate_delta`` — the canary's error rate (errors +
      timeouts over requests) may exceed the incumbent's by at most
      this absolute fraction;
    - ``max_p99_ratio``/``p99_floor_ms`` — the canary's request p99
      must stay under ``max(incumbent_p99 * ratio, floor)``; the
      floor keeps microsecond-noise from failing an idle canary;
    - ``min_canary_requests`` — error/latency guardrails only judge
      once the canary has answered this many requests at the current
      stage (the numerics gate needs no traffic and always runs).
    """

    def __init__(self, rtol=1e-5, atol=1e-7, max_error_rate_delta=0.02,
                 max_p99_ratio=3.0, p99_floor_ms=50.0,
                 min_canary_requests=20):
        self.rtol = float(rtol)
        self.atol = float(atol)
        self.max_error_rate_delta = float(max_error_rate_delta)
        self.max_p99_ratio = float(max_p99_ratio)
        self.p99_floor_ms = float(p99_floor_ms)
        self.min_canary_requests = int(min_canary_requests)

    def to_dict(self):
        return {"rtol": self.rtol, "atol": self.atol,
                "max_error_rate_delta": self.max_error_rate_delta,
                "max_p99_ratio": self.max_p99_ratio,
                "p99_floor_ms": self.p99_floor_ms,
                "min_canary_requests": self.min_canary_requests}


def _error_rate(stats, baseline=None):
    """(errors + timeouts) / requests over the window since
    ``baseline`` (a previous per-version stats snapshot), or over all
    time when no baseline. Returns (rate, n_requests)."""
    baseline = baseline or {}

    def delta(name):
        return (stats.get(name, 0) or 0) - (baseline.get(name, 0) or 0)

    requests = delta("requests_total")
    errors = delta("errors_total") + delta("timeouts_total")
    return ((errors / requests) if requests > 0 else 0.0,
            requests)


def evaluate_guardrails(canary_stats, incumbent_stats, guardrails,
                        canary_baseline=None, incumbent_baseline=None):
    """Pure guardrail check over two per-version merged stats
    snapshots (``pool.stats()["versions"][...]`` shape). Returns the
    list of violation strings — empty means the canary is inside the
    rails. Insufficient canary traffic (< ``min_canary_requests``
    since the baseline) returns no violations: an unjudgeable stage
    is not a failing stage (the numerics gate still guards it)."""
    violations = []
    can_rate, can_n = _error_rate(canary_stats, canary_baseline)
    if can_n < guardrails.min_canary_requests:
        return violations
    inc_rate, _ = _error_rate(incumbent_stats, incumbent_baseline)
    if can_rate > inc_rate + guardrails.max_error_rate_delta:
        violations.append(
            f"error-rate regression: canary {can_rate:.4f} vs "
            f"incumbent {inc_rate:.4f} "
            f"(+{guardrails.max_error_rate_delta} allowed)")
    can_lat = (canary_stats.get("request_latency") or {})
    inc_lat = (incumbent_stats.get("request_latency") or {})
    can_p99 = can_lat.get("p99_ms")
    inc_p99 = inc_lat.get("p99_ms")
    if (can_p99 is not None
            and can_lat.get("count", 0)
            >= guardrails.min_canary_requests):
        bound = guardrails.p99_floor_ms
        if inc_p99 is not None:
            bound = max(bound, inc_p99 * guardrails.max_p99_ratio)
        if can_p99 > bound:
            violations.append(
                f"p99 regression: canary {can_p99:.1f}ms vs bound "
                f"{bound:.1f}ms (incumbent p99 "
                f"{'n/a' if inc_p99 is None else f'{inc_p99:.1f}ms'}, "
                f"ratio {guardrails.max_p99_ratio}, floor "
                f"{guardrails.p99_floor_ms}ms)")
    return violations


class ModelVersion:
    """One immutable, nameable deployment unit.

    ``factory`` is the zero-arg engine factory the pool rebuilds
    replicas from; ``model_dir`` (optional but recommended) pins the
    identity — the params-manifest sha256, the ``__artifacts__``
    snapshot, and the export's ``model_version`` stamp are read from
    it. ``eval_fn`` (feed-dict → list of fetch arrays) overrides the
    default golden-set evaluation path — scriptable fakes use it to
    unit-test the gate without real engines."""

    def __init__(self, name, factory, model_dir=None, eval_fn=None,
                 golden=None):
        self.name = str(name)
        self.factory = factory
        self.model_dir = (None if model_dir is None
                          else os.path.abspath(model_dir))
        self.eval_fn = eval_fn
        self._golden = golden
        self.params_sha = None
        self.model_version = None
        self.has_artifacts = False
        if self.model_dir is not None:
            import json
            from ..io import PARAMS_MANIFEST
            from ..io.artifact_store import EMBEDDED_DIRNAME
            try:
                with open(os.path.join(self.model_dir,
                                       PARAMS_MANIFEST)) as f:
                    self.params_sha = json.load(f).get("sha256")
            except (OSError, ValueError):
                pass
            try:
                with open(os.path.join(self.model_dir,
                                       "__meta__.json")) as f:
                    self.model_version = json.load(f).get(
                        "model_version")
            except (OSError, ValueError):
                pass
            self.has_artifacts = os.path.isdir(
                os.path.join(self.model_dir, EMBEDDED_DIRNAME))

    def golden(self):
        """The recorded golden-request set ``(feeds, outputs)`` —
        explicit beats on-disk (``__golden__.npz`` next to the saved
        model), None when neither exists."""
        if self._golden is not None:
            return self._golden
        if self.model_dir is not None:
            from .. import io as fluid_io
            return fluid_io.load_golden_set(self.model_dir)
        return None

    def set_golden(self, feeds, outputs):
        self._golden = (list(feeds), [list(o) for o in outputs])
        return self

    def snapshot(self):
        return {"name": self.name, "model_dir": self.model_dir,
                "params_sha": self.params_sha,
                "model_version": self.model_version,
                "has_artifacts": self.has_artifacts}

    def __repr__(self):
        return (f"ModelVersion({self.name!r}, "
                f"model_version={self.model_version}, "
                f"sha={(self.params_sha or '?')[:12]})")


class DeploymentManager:
    """Versioned deployments over one Router + ReplicaPool.

    ::

        mgr = DeploymentManager(router)
        mgr.register("v1", model_dir=v1_dir)
        mgr.register("v2", model_dir=v2_dir)
        mgr.set_incumbent("v1")
        mgr.record_golden(sample_feeds)      # pin the references
        report = mgr.deploy_canary("v2")     # dark + numerics-gated
        if report["accepted"]:
            report = mgr.promote()           # 1% → 50% → 100%, gated

    Every gate failure auto-rolls-back; ``rollback()`` is also the
    operator's big red button. All traffic keeps flowing throughout —
    conversions ride the pool's drain-based restart, and the router's
    weighted candidate ordering keeps every weight>0 version available
    as a failover target."""

    def __init__(self, router, guardrails=None, drain_timeout=None):
        self.router = router
        self.pool = router.pool
        self.guardrails = guardrails or Guardrails()
        self.drain_timeout = drain_timeout
        self._versions = {}
        self._incumbent = None
        self._canary = None
        self.history = []           # every deploy/promote/rollback report

    # -- registry --------------------------------------------------------
    def register(self, name, model_dir=None, factory=None,
                 eval_fn=None, golden=None, **engine_kw):
        """Name a version. Either ``factory`` (zero-arg → started
        engine) or ``model_dir`` (a ``save_inference_model`` export —
        the factory becomes ``ServingEngine.from_saved_model`` over
        it, picking up embedded buckets + artifact store)."""
        if factory is None:
            if model_dir is None:
                raise DeploymentError(
                    f"version {name!r} needs a factory or a model_dir")
            from ..serving.engine import ServingEngine
            the_dir = os.path.abspath(model_dir)

            def factory(_dir=the_dir, _kw=dict(engine_kw)):
                return ServingEngine.from_saved_model(_dir, **_kw)
        version = ModelVersion(name, factory, model_dir=model_dir,
                               eval_fn=eval_fn, golden=golden)
        self._versions[version.name] = version
        return version

    def version(self, name):
        try:
            return self._versions[name]
        except KeyError:
            raise DeploymentError(
                f"unknown version {name!r}; registered: "
                f"{sorted(self._versions)}") from None

    @property
    def incumbent(self):
        return self._incumbent

    @property
    def canary(self):
        return self._canary

    def set_incumbent(self, name):
        """Declare the version the pool is CURRENTLY serving: every
        replica is labeled with it and the router routes to it alone
        (weight 1.0). The starting state of every deployment."""
        version = self.version(name)
        if self._canary is not None:
            raise DeploymentError(
                f"cannot repoint incumbent while canary "
                f"{self._canary!r} is active — promote or roll back "
                "first")
        for r in self.pool.replicas():
            r.version = version.name
        self.router.set_weights({version.name: 1.0})
        self._incumbent = version.name
        return version

    # -- golden set ------------------------------------------------------
    def record_golden(self, feeds, save=True):
        """Record the incumbent's outputs on ``feeds`` as the pinned
        references every candidate must reproduce; persisted next to
        the incumbent's saved model (``__golden__.npz``) when it has
        one, so the references survive the process."""
        incumbent = self.version(self._require_incumbent())
        feeds = list(feeds)
        outputs = self._eval_version(incumbent, feeds, canary=False)
        incumbent.set_golden(feeds, outputs)
        if save and incumbent.model_dir is not None:
            from .. import io as fluid_io
            fluid_io.save_golden_set(incumbent.model_dir, feeds,
                                     outputs)
        return outputs

    # -- the gauntlet ----------------------------------------------------
    def deploy_canary(self, name, replicas=1):
        """Dark-deploy ``name`` onto ``replicas`` pool members and run
        the pre-traffic numerics gate. The canary carries ZERO traffic
        until :meth:`promote` ramps it (the conversion happens behind
        an incumbent-only weight map, and the drain-based restart
        loses no in-flight request). A numerics failure auto-rolls
        back and returns the rejected report."""
        incumbent = self.version(self._require_incumbent())
        canary = self.version(name)
        if canary.name == incumbent.name:
            raise DeploymentError(
                f"{name!r} is already the incumbent")
        if self._canary is not None:
            raise DeploymentError(
                f"canary {self._canary!r} already active — promote "
                "or roll back first")
        pool_size = len(self.pool.replicas())
        replicas = int(replicas)
        if not 1 <= replicas < pool_size:
            raise DeploymentError(
                f"canary size {replicas} must leave at least one "
                f"incumbent replica (pool has {pool_size})")
        t0 = time.monotonic()
        # 1. the canary is dark: only the incumbent can win the draw
        self.router.set_weights({incumbent.name: 1.0})
        # 2. convert the newest k replicas (drain → rebuild → warm)
        targets = [r for r in self.pool.replicas()
                   if r.version == incumbent.name][-replicas:]
        convert = self.pool.restart_replicas(
            targets, factory=canary.factory, version=canary.name,
            drain_timeout=self.drain_timeout)
        self._canary = canary.name
        report = {"action": "deploy_canary", "canary": canary.snapshot(),
                  "incumbent": incumbent.snapshot(),
                  "replicas": convert["restarted"],
                  "rewarm": convert["rewarm"],
                  "rewarm_compiles": _sum_compiles(convert["rewarm"])}
        # 3. numerics gate BEFORE any traffic
        numerics = self._numerics_gate(canary)
        report["numerics"] = numerics
        if not numerics["ok"]:
            rollback = self.rollback(
                reason=f"numerics gate failed before traffic: "
                       f"{numerics.get('worst')}")
            report.update(accepted=False, rejected="numerics",
                          rollback=rollback)
        else:
            report.update(accepted=True,
                          wall_s=round(time.monotonic() - t0, 3))
        self.history.append(report)
        return report

    def promote(self, stages=(0.01, 0.5, 1.0), stage_s=2.0,
                poll_s=0.05, observe=None):
        """Walk the canary up the weight schedule, gated at every
        stage. Each sub-1.0 stage holds its weights for ``stage_s``
        seconds (polling every ``poll_s``; ``observe``, if given, is
        called once per stage as ``observe(stage_weight)`` and may
        drive traffic — tests and servebench use it), then judges:

        - **numerics re-sample** — the golden set replays through the
          canary again (in-flight regressions, e.g. a replica serving
          from corrupt memory, are caught mid-ramp, not just at t=0);
        - **guardrails** — the canary's error rate and p99 since the
          stage began, against the incumbent's, within
          ``Guardrails`` margins.

        Any violation auto-rejects: instant rollback, report says
        which gate and at which stage. The final 1.0 stage converts
        the remaining incumbent replicas to the canary (same
        zero-loss restart), makes the canary the new incumbent, and
        leaves the pool's factory pointing at it."""
        incumbent = self.version(self._require_incumbent())
        if self._canary is None:
            raise DeploymentError("no active canary to promote — "
                                  "deploy_canary() first")
        canary = self.version(self._canary)
        t0 = time.monotonic()
        timeline = []
        for stage in stages:
            stage = float(stage)
            if stage >= 1.0:
                break
            self.router.set_weights({incumbent.name: 1.0 - stage,
                                     canary.name: stage})
            baseline = self._version_stats()
            if observe is not None:
                observe(stage)
            deadline = time.monotonic() + float(stage_s)
            while time.monotonic() < deadline:
                time.sleep(poll_s)
            numerics = self._numerics_gate(canary)
            now = self._version_stats()
            violations = evaluate_guardrails(
                now.get(canary.name) or {},
                now.get(incumbent.name) or {},
                self.guardrails,
                canary_baseline=baseline.get(canary.name),
                incumbent_baseline=baseline.get(incumbent.name))
            entry = {"stage": stage, "numerics": numerics,
                     "violations": violations}
            timeline.append(entry)
            if not numerics["ok"] or violations:
                reason = ("numerics re-sample failed at stage "
                          f"{stage:g}: {numerics.get('worst')}"
                          if not numerics["ok"] else
                          f"guardrails at stage {stage:g}: "
                          + "; ".join(violations))
                rollback = self.rollback(reason=reason)
                report = {"action": "promote", "accepted": False,
                          "rejected": ("numerics"
                                       if not numerics["ok"]
                                       else "guardrails"),
                          "stage": stage, "timeline": timeline,
                          "reason": reason, "rollback": rollback,
                          "wall_s": round(time.monotonic() - t0, 3)}
                self.history.append(report)
                return report
        # final stage: the canary won — convert the rest of the pool
        numerics = self._numerics_gate(canary)
        if not numerics["ok"]:
            reason = ("numerics re-sample failed before full "
                      f"conversion: {numerics.get('worst')}")
            rollback = self.rollback(reason=reason)
            report = {"action": "promote", "accepted": False,
                      "rejected": "numerics", "stage": 1.0,
                      "timeline": timeline, "reason": reason,
                      "rollback": rollback,
                      "wall_s": round(time.monotonic() - t0, 3)}
            self.history.append(report)
            return report
        convert = self.pool.restart_replicas(
            None, factory=canary.factory, version=canary.name,
            drain_timeout=self.drain_timeout)
        self.router.set_weights({canary.name: 1.0})
        self._incumbent = canary.name
        self._canary = None
        report = {"action": "promote", "accepted": True,
                  "new_incumbent": canary.snapshot(),
                  "timeline": timeline,
                  "final_convert": convert["restarted"],
                  "rewarm_compiles": _sum_compiles(convert["rewarm"]),
                  "wall_s": round(time.monotonic() - t0, 3)}
        self.history.append(report)
        return report

    def rollback(self, reason="operator"):
        """Instant revert to the incumbent: the weight map repoints
        FIRST (the next candidate draw cannot pick the canary — the
        data-plane rollback is one dict swap), then the canary
        replicas drain and rebuild back onto the incumbent's factory.
        With the incumbent's artifact store embedded in its saved
        model, the re-warm performs zero XLA compiles
        (``rewarm_compiles`` in the report is the proof), and the
        drain guarantees the canary's in-flight requests finish —
        rollback loses nothing."""
        incumbent = self.version(self._require_incumbent())
        t0 = time.monotonic()
        self.router.set_weights({incumbent.name: 1.0})
        repoint_s = time.monotonic() - t0
        targets = [r for r in self.pool.replicas()
                   if r.version not in (None, incumbent.name)]
        convert = (self.pool.restart_replicas(
            targets, factory=incumbent.factory,
            version=incumbent.name,
            drain_timeout=self.drain_timeout)
            if targets else {"restarted": [], "rewarm": {}})
        self._canary = None
        report = {"action": "rollback", "reason": reason,
                  "incumbent": incumbent.snapshot(),
                  "replicas": convert["restarted"],
                  "rewarm": convert["rewarm"],
                  "rewarm_compiles": _sum_compiles(convert["rewarm"]),
                  "repoint_s": round(repoint_s, 6),
                  "serving_rollback_s": round(
                      time.monotonic() - t0, 3)}
        self.history.append(report)
        return report

    # -- gates -----------------------------------------------------------
    def _numerics_gate(self, canary):
        """Replay the incumbent's golden set through the canary and
        tolerance-compare. No golden set is a hard error — promoting
        unverified would defeat the whole subsystem."""
        incumbent = self.version(self._require_incumbent())
        golden = incumbent.golden()
        if golden is None:
            raise DeploymentError(
                f"incumbent {incumbent.name!r} has no recorded "
                "golden-request set — record_golden() (or export one "
                "with io.save_golden_set) before deploying a canary")
        feeds, reference = golden
        candidate = self._eval_version(canary, feeds, canary=True)
        return check_numerics(reference, candidate,
                              rtol=self.guardrails.rtol,
                              atol=self.guardrails.atol)

    def _eval_version(self, version, feeds, canary):
        """A version's outputs on the golden feeds, via its
        ``eval_fn`` when given (scriptable fakes), else by running
        the feeds through one of its live pool replicas' engines
        (or a throwaway engine when it has no replica yet). The
        ``serving_canary_regression`` fault point perturbs CANARY
        evaluations only — the incumbent's references stay honest."""
        if version.eval_fn is not None:
            outs = [list(version.eval_fn(feed)) for feed in feeds]
        else:
            eng, throwaway = self._eval_engine(version)
            try:
                outs = [_run_golden(eng, feed) for feed in feeds]
            finally:
                if throwaway:
                    eng.close()
        if canary and _faultinject.fires("serving_canary_regression"):
            outs = [[np.asarray(o, dtype=np.float64)
                     + _FAULT_PERTURBATION for o in row]
                    for row in outs]
        return outs

    def _eval_engine(self, version):
        for r in self.pool.replicas():
            if (r.version == version.name and not r.restarting
                    and hasattr(r, "engine")):
                return r.engine, False
        return version.factory(), True

    # -- introspection ---------------------------------------------------
    def _require_incumbent(self):
        if self._incumbent is None:
            raise DeploymentError(
                "no incumbent declared — set_incumbent() first")
        return self._incumbent

    def _version_stats(self):
        return self.pool.stats().get("versions") or {}

    def status(self):
        """Operator snapshot: live weights, per-version merged
        metrics, and the label-namespaced combined registry (every
        version's counters side by side under ``"<version>/..."``
        keys — nothing collides)."""
        by_version = {}
        for r in self.pool.replicas():
            m = r.metrics_obj()
            if m is not None and r.version is not None:
                by_version.setdefault(r.version, []).append(m)
        labeled = [ServingMetrics.merge(*ms, label=v)
                   for v, ms in sorted(by_version.items())]
        return {"incumbent": self._incumbent,
                "canary": self._canary,
                "weights": self.router.weights(),
                "versions": self._version_stats(),
                "combined": (ServingMetrics.merge(*labeled).stats()
                             if labeled else None),
                "guardrails": self.guardrails.to_dict(),
                "registered": {n: v.snapshot()
                               for n, v in self._versions.items()}}


def _sum_compiles(rewarm):
    """Total compiles across a restart report's rewarm entries — the
    number the zero-compile rollback guarantee pins to 0."""
    total = 0
    for rep in (rewarm or {}).values():
        if isinstance(rep, dict):
            total += int(rep.get("compiles") or 0)
    return total


def _run_golden(engine, feed):
    """One golden feed through an engine's executor, off the batching
    path (deterministic, single-row — the same shapes warmup pinned,
    so this compiles nothing new). The scope is passed explicitly:
    swapping the process-global scope would race the live engines'
    worker threads."""
    out = engine.exe.run(engine.program, feed=feed,
                         fetch_list=engine.fetch_list, mode="test",
                         scope=engine.scope)
    return [np.asarray(o) for o in out]
