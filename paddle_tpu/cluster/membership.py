"""Membership — the fabric's partition-tolerant discovery view.

A deliberately tiny design (static seeds + heartbeat refresh), because
the robustness property matters more than the gossip protocol: every
remote replica is periodically refreshed (one ``stats`` RPC that also
re-establishes a dead connection — the REJOIN path), and a replica
whose last successful contact is older than ``stale_after_s`` reads
DEGRADED, so the health-aware balancing policy ranks it behind every
fresh replica and the Router routes around it. A partition therefore
degrades a replica to *excluded*, and the first refresh after the
partition heals brings it back — never a hang, never an operator page
for a self-healing event.

``serve_remotes()`` is the one-call front door: seed addresses in, a
balanced Router over :class:`RemoteReplica` instances out, with the
membership refresher attached and closed together with the pool.
"""
import threading
import time

from ..serving.health import serving_rank
from .pool import ReplicaPool
from .remote import RemoteReplica
from .router import Router

__all__ = ["Membership", "serve_remotes"]


class Membership:
    """Heartbeat refresher + staleness view over a set of replicas.

    ``replicas`` is any list of Replica objects exposing
    ``refresh()`` (RemoteReplica does; a test fake needs one method).
    ``refresh_interval_s=0`` disables the thread — tests drive
    :meth:`refresh_once` by hand."""

    def __init__(self, replicas, refresh_interval_s=0.5,
                 stale_after_s=None):
        self._replicas = list(replicas)
        self.refresh_interval_s = float(refresh_interval_s)
        self.stale_after_s = (3 * self.refresh_interval_s
                              if stale_after_s is None
                              else float(stale_after_s))
        for r in self._replicas:
            # the replica's own health read honors the same staleness
            # bound the view reports, so router tiers and membership
            # agree about who is excluded
            if getattr(r, "stale_after_s", None) is None \
                    and hasattr(r, "stale_after_s"):
                r.stale_after_s = self.stale_after_s
        self._lock = threading.Lock()
        self._alive_view = {r.name: None for r in self._replicas}
        self.refreshes_total = 0
        self.evictions_total = 0
        self.rejoins_total = 0
        self._stop = threading.Event()
        self._thread = None
        if self.refresh_interval_s > 0:
            self._thread = threading.Thread(
                target=self._loop, name="paddle-tpu-membership",
                daemon=True)
            self._thread.start()

    def replicas(self):
        return list(self._replicas)

    def refresh_once(self):
        """One sweep: refresh every member, count evictions (answering
        → not) and rejoins (not → answering). Returns the number of
        members that answered."""
        answered = 0
        for r in self._replicas:
            try:
                ok = bool(r.refresh())
            except Exception:           # noqa: BLE001 — a failing
                ok = False              # member must not stop the sweep
            with self._lock:
                was = self._alive_view.get(r.name)
                self._alive_view[r.name] = ok
                if was is True and not ok:
                    self.evictions_total += 1
                if was is False and ok:
                    self.rejoins_total += 1
                self.refreshes_total += 1
            answered += ok
        return answered

    def _loop(self):
        while not self._stop.wait(self.refresh_interval_s):
            self.refresh_once()

    def view(self):
        """Per-member snapshot the operator (and servebench) reads."""
        out = []
        with self._lock:
            alive_view = dict(self._alive_view)
        for r in self._replicas:
            state = r.health_state()
            out.append({
                "name": r.name,
                "addr": getattr(r, "addr", None),
                "answering": alive_view.get(r.name),
                "alive": r.alive(),
                "health_state": state,
                "serving_rank": serving_rank(state),
                "outstanding": r.outstanding(),
                "last_seen_age_s": getattr(r, "_last_seen", None)
                and round(time.monotonic() - r._last_seen, 3),
                # which model version the member is actually serving:
                # the engine stamps model_version (from the export's
                # __meta__.json) into its stats, which remote replicas
                # cache from the welcome/stats frames — no extra RPC
                "model_version": (getattr(r, "_last_stats", None)
                                  or {}).get("model_version"),
            })
        return out

    def stats(self):
        with self._lock:
            return {"members": len(self._replicas),
                    "refreshes_total": self.refreshes_total,
                    "evictions_total": self.evictions_total,
                    "rejoins_total": self.rejoins_total,
                    "stale_after_s": self.stale_after_s}

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        return self


def serve_remotes(addresses, token=None, policy="health_aware",
                  max_cluster_queue=None, refresh_interval_s=0.25,
                  stale_after_s=None, lazy=False, **replica_kw):
    """A balanced, self-healing Router over remote replicas.

    ``addresses`` are ``"host:port"`` strings (or ``(host, port)``
    pairs, or ready RemoteReplica instances). The membership refresher
    owns reconnection (the pool's own revive monitor is disabled), so
    a partitioned replica is excluded by health tiering and rejoins
    within one refresh of the partition healing. Closing the router
    closes the membership thread and every client connection; the
    remote SERVERS keep running — they belong to their hosts."""
    replicas = [addr if isinstance(addr, RemoteReplica)
                else RemoteReplica(addr, token=token, lazy=lazy,
                                   **replica_kw)
                for addr in addresses]
    if not replicas:
        raise ValueError("serve_remotes needs at least one address")
    it = iter(replicas)
    pool = ReplicaPool(lambda: next(it), replicas=len(replicas),
                       revive_interval_s=0, name_prefix="remote")
    membership = Membership(pool.replicas(),
                            refresh_interval_s=refresh_interval_s,
                            stale_after_s=stale_after_s)
    pool.register_closer(membership.close)
    router = Router(pool, policy=policy,
                    max_cluster_queue=max_cluster_queue)
    router.membership = membership
    return router
