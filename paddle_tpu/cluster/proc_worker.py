"""Replica worker process — one ServingEngine behind a pipe protocol.

Spawned by :class:`~paddle_tpu.cluster.replica.ProcessReplica`:

    python -m paddle_tpu.cluster.proc_worker --dir <saved_model_dir>

Loads the ``save_inference_model`` artifact, builds a ServingEngine
over it (buckets from the artifact's serving manifest when present),
warms up, then serves ``cluster/net.py`` frames (magic + version +
CRC32, restricted unpickling — the same codec as the socket fabric)
read from stdin:

    {"type": "submit", "id": n, "feed": {...}, "timeout": s | None}
        -> {"type": "result", "id": n, "value": [arrays]}
         | {"type": "error", "id": n, "error": (type_name, message)}
    {"type": "stats", "id": n} -> {"type": "stats", "id": n, "value": {...}}
    {"type": "close", "drain": bool, "drain_timeout": s | None}
        -> drains (optionally) and exits 0

``--decode`` serves a :func:`~paddle_tpu.models.llama.save_decode_model`
directory with a DecodeEngine instead: ``submit`` feeds are prompt
arrays (``kw`` carries max_new / prefill_only / an SLO dict), results
are generated-token arrays — or a KV handoff blob for ``prefill_only``
— and the extra ``handoff`` verb adopts such a blob on a decode-role
worker:

    {"type": "handoff", "id": n, "state": {...}, "timeout": s | None,
     "kw": {...}} -> result | error, as for submit

The real stdout fd is reserved for protocol frames; python-level
stdout is re-pointed at stderr first, so a stray print (jax warmup
chatter, user code) can never corrupt a frame. A SIGKILL'd worker just
disappears — the parent's reader thread sees EOF and fails every
pending request with WorkerDiedError, which is exactly the replica-
crash drill's contract.
"""
import argparse
import os
import sys
import threading
from concurrent.futures import ThreadPoolExecutor


def _claim_stdout():
    """Duplicate the protocol fd, then point fd 1 (and sys.stdout) at
    stderr so nothing else can write frames."""
    proto_fd = os.dup(sys.stdout.fileno())
    os.dup2(sys.stderr.fileno(), sys.stdout.fileno())
    return os.fdopen(proto_fd, "wb")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", required=True)
    ap.add_argument("--no-warmup", action="store_true")
    ap.add_argument("--max-wait-ms", type=float, default=2.0)
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--default-timeout-s", type=float, default=30.0)
    # --decode serves a models.llama.save_decode_model directory with
    # a DecodeEngine (continuous batching + the handoff verb) instead
    # of a save_inference_model dir with a ServingEngine
    ap.add_argument("--decode", action="store_true")
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--prompt-buckets", default="16,32")
    ap.add_argument("--max-new-tokens", type=int, default=32)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--n-pages", type=int, default=None)
    ap.add_argument("--chunk-size", type=int, default=None)
    ap.add_argument("--scheduler", default=None)
    args = ap.parse_args(argv)

    proto_out = _claim_stdout()
    proto_in = sys.stdin.buffer
    # racecheck: ok(global-mutation) — worker-process entrypoint: owns
    # the env, runs before any thread or jax backend exists
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu import serving
    from paddle_tpu.cluster.net import (FrameError, read_frame,
                                        write_frame)
    from paddle_tpu.serving import ServingError

    # racecheck: ok(global-mutation) — entrypoint-owned process, called
    # once before the engine builds and before any serving thread
    fluid.force_cpu()
    if args.decode:
        from paddle_tpu.models.llama import load_decode_model
        cfg, scope = load_decode_model(args.dir)
        buckets = tuple(int(b) for b in
                        str(args.prompt_buckets).split(",") if b)
        engine = serving.DecodeEngine(
            cfg, scope=scope, place=fluid.CPUPlace(),
            config=serving.DecodeConfig(
                max_batch=args.max_batch, prompt_buckets=buckets,
                max_new_tokens=args.max_new_tokens,
                page_size=args.page_size, n_pages=args.n_pages,
                chunk_size=args.chunk_size, scheduler=args.scheduler,
                max_queue=args.max_queue,
                default_timeout_s=args.default_timeout_s))
    else:
        engine = serving.ServingEngine.from_saved_model(
            args.dir,
            config=serving.ServingConfig(
                max_wait_ms=args.max_wait_ms, max_queue=args.max_queue,
                default_timeout_s=args.default_timeout_s))
    warm = None if args.no_warmup else engine.warmup()

    write_lock = threading.Lock()

    def send(obj):
        with write_lock:
            # racecheck: ok(blocking-under-lock) — the lock exists only
            # to keep pool threads' reply frames from interleaving on
            # the protocol fd; frames fit the pipe buffer
            write_frame(proto_out, obj)

    send({"type": "ready", "warmup": warm, "stats": engine.stats()})

    def _wire_slo(kw):
        """An SLO crosses the pipe as a plain dict (the restricted
        unpickler refuses custom classes — by design); rebuild the
        SLOClass worker-side."""
        slo = kw.get("slo")
        if isinstance(slo, dict):
            kw["slo"] = serving.SLOClass(**slo)
        return kw

    def serve_one(req_id, feed, timeout, kw):
        try:
            if args.decode:
                handle = engine.submit(np.asarray(feed),
                                       timeout=timeout,
                                       **_wire_slo(kw))
                # grace past the serving deadline, like Router.infer:
                # the engine's typed error is the real signal
                value = handle.result(
                    None if timeout is None else float(timeout) + 10.0)
            else:
                value = engine.infer(feed, timeout=timeout)
            send({"type": "result", "id": req_id, "value": value})
        except (ServingError, ValueError) as exc:
            send({"type": "error", "id": req_id,
                  "error": (type(exc).__name__, str(exc))})
        except Exception as exc:             # noqa: BLE001 — forwarded
            send({"type": "error", "id": req_id,
                  "error": (type(exc).__name__, str(exc))})

    def serve_handoff(req_id, state, timeout, kw):
        try:
            handle = engine.import_handoff(state, timeout=timeout,
                                           **_wire_slo(kw))
            value = handle.result(
                None if timeout is None else float(timeout) + 10.0)
            send({"type": "result", "id": req_id, "value": value})
        except Exception as exc:             # noqa: BLE001 — forwarded
            send({"type": "error", "id": req_id,
                  "error": (type(exc).__name__, str(exc))})

    pool = ThreadPoolExecutor(max_workers=8,
                              thread_name_prefix="replica-serve")
    try:
        while True:
            try:
                msg = read_frame(proto_in)
            except FrameError:
                # protocol damage on OUR command stream: the stream
                # position is unknowable, so exit — the parent's
                # reader sees EOF and fails pending typed
                engine.close()
                return 1
            if msg is None:       # parent went away: treat as close
                engine.close()
                return 0
            kind = msg.get("type")
            if kind == "submit":
                pool.submit(serve_one, msg["id"], msg["feed"],
                            msg.get("timeout"), msg.get("kw") or {})
            elif kind == "handoff":
                pool.submit(serve_handoff, msg["id"], msg["state"],
                            msg.get("timeout"), msg.get("kw") or {})
            elif kind == "stats":
                send({"type": "stats", "id": msg["id"],
                      "value": engine.stats()})
            # protocheck: ok(verb-asymmetric) — 'close' is pipe-only
            # on purpose: a ProcessReplica OWNS its child and shuts it
            # down; a RemoteReplica is one client of a SHARED server
            # and must never be able to close it (the socket hangup is
            # 'bye', which drops only that connection)
            elif kind == "close":
                engine.close(drain=bool(msg.get("drain")),
                             drain_timeout=msg.get("drain_timeout"))
                # let in-flight serve_one threads flush their result
                # frames before the process exits — a drained request
                # whose reply died in the pipe would count as lost
                pool.shutdown(wait=True)
                return 0
    finally:
        pool.shutdown(wait=False)


if __name__ == "__main__":
    sys.exit(main())
