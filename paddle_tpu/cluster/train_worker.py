"""TrainWorkerServer — one training host behind a TCP socket.

The training-side sibling of ``net_worker.ReplicaServer``: where that
module serves *inference* over the CRC-framed transport, this one
serves gradient computation to a
:class:`~paddle_tpu.cluster.train_fabric.TrainCoordinator`. A worker
is deliberately passive and (almost) stateless: the coordinator sends
the authoritative params with EVERY ``train_step``, so a worker that
died and came back — or a brand-new replacement host — needs nothing
but this entrypoint, the task spec (re-sent on ``train_configure``),
and, for compiled tasks, an ``__artifacts__`` store it can
cold-provision over the wire from any live peer
(``net_worker.provision_from_remote`` — zero XLA compiles). The only
state a worker retains is the last COMMITTED ``(step, sha)`` it
verified, which is exactly what a parked worker needs to answer a new
coordinator's catch-up commit after the old coordinator died.

Wire verbs (after the hello/welcome handshake; see
``train_fabric`` for the frame schemas)::

    train_configure   rebuild the task from its spec
    train_step        compute per-shard gradient SUMS for the given
                      (step, state, shards); the determinism contract
                      is the task's, the worker just evaluates it
    train_commit      re-hash the broadcast state and VERIFY the
                      leader's sha (followers-verify half of the
                      commit barrier); remember (step, sha)
    stats/ping        ops plane + heartbeat
    fetch_manifest /  serve this worker's artifact dir so a PEER can
    fetch_artifact    provision itself over the wire (same
                      path-confined, checksummed protocol as serving)
    bye               close this connection (server stays up)

Parking: a worker whose coordinator vanished simply keeps listening —
``stats()`` reports ``coordinator_age_s`` so operators can see the
fleet is parked, and the ``--park-deadline`` entrypoint flag turns
"parked too long" into a clean typed exit (status 3) instead of a
zombie host.

Fault points (armed via ``PADDLE_TPU_FAULTS`` or
``faultinject.arm``): the step handler marks a ``train_step``
progress event, then checks ``trainer_crash_at_step`` (hard death:
``os._exit`` when ``--hard-exit``/``hard_exit=True`` — a real
SIGKILL-shaped hole for subprocess drills — else an abrupt
listener+connection teardown for in-process tests) and
``trainer_straggle`` (stall ``PADDLE_TPU_FAULT_STRAGGLE_S`` seconds —
the coordinator's straggler deadline must evict us).

Run in-process (tests) or as a host entrypoint::

    python -m paddle_tpu.cluster.train_worker --port 7731 \
        [--artifact-dir DIR] [--provision-from HOST:PORT] \
        [--park-deadline 60] [--hard-exit]
"""
import argparse
import os
import socket
import threading
import time

import numpy as np

from ..resilience import faultinject as _faultinject
from ..resilience.checkpoint import state_sha
from . import net
from .train_fabric import task_from_spec

__all__ = ["TrainWorkerServer"]

_HANDSHAKE_TIMEOUT_S = 10.0
_STRAGGLE_ENV = "PADDLE_TPU_FAULT_STRAGGLE_S"


class TrainWorkerServer:
    """Serve gradient computation over TCP for one training host.

    ``port=0`` picks a free port (read it back from ``.port``).
    ``artifact_dir`` doubles as the compile cache for program tasks
    AND the directory served to provisioning peers. ``hard_exit=True``
    makes an injected ``trainer_crash_at_step`` call ``os._exit`` —
    subprocess drills want the SIGKILL shape; in-process tests get an
    abrupt socket teardown instead."""

    def __init__(self, host="127.0.0.1", port=0, token=None,
                 name=None, artifact_dir=None, hard_exit=False,
                 backlog=16):
        self._token = token
        self.artifact_dir = (os.path.abspath(artifact_dir)
                             if artifact_dir else None)
        self.hard_exit = bool(hard_exit)
        self._task = None
        self._task_spec = None
        self._task_lock = threading.Lock()
        self._closed = threading.Event()
        self._conns = set()
        self._conns_lock = threading.Lock()
        self.last_step = None
        self.committed_step = None
        self.committed_sha = None
        self._last_contact = time.monotonic()
        self._counters = {"connections_total": 0,
                          "handshake_refused_total": 0,
                          "protocol_errors_total": 0,
                          "steps_total": 0,
                          "commits_total": 0,
                          "commit_mismatches_total": 0,
                          "artifacts_served_total": 0}
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(backlog)
        self.host, self.port = self._listener.getsockname()[:2]
        self.name = name or f"train-worker@{self.host}:{self.port}"
        self._acceptor = threading.Thread(
            target=self._accept_loop, name=f"{self.name}-accept",
            daemon=True)
        self._acceptor.start()

    @property
    def addr(self):
        return f"{self.host}:{self.port}"

    def total_compiles(self):
        """XLA compiles this worker's task has performed — 0 for pure
        tasks and for program tasks warmed from a provisioned
        ``__artifacts__`` store (the elastic-rejoin gate)."""
        with self._task_lock:
            task = self._task
        return task.total_compiles() if task is not None else 0

    def coordinator_age_s(self):
        """Seconds since the last coordinator contact — the parking
        clock."""
        return round(time.monotonic() - self._last_contact, 3)

    def _incr(self, key, n=1):
        with self._conns_lock:
            self._counters[key] += n

    # -- accept / per-connection ----------------------------------------
    def _accept_loop(self):
        while not self._closed.is_set():
            try:
                sock, peer = self._listener.accept()
            except OSError:
                return              # listener closed: shutting down
            self._incr("connections_total")
            with self._conns_lock:
                self._conns.add(sock)
            threading.Thread(
                target=self._serve_conn, args=(sock, peer),
                name=f"{self.name}-conn", daemon=True).start()

    def _drop_conn(self, sock):
        with self._conns_lock:
            self._conns.discard(sock)
        try:
            sock.close()
        except OSError:
            pass

    def _serve_conn(self, sock, peer):
        write_lock = threading.Lock()

        def send(obj):
            with write_lock:
                # racecheck: ok(blocking-under-lock) — the lock exists
                # ONLY to serialize frame writes on this socket;
                # nothing else ever waits on it
                net.send_frame(sock, obj)

        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            deadline = time.monotonic() + _HANDSHAKE_TIMEOUT_S
            hello = net.recv_frame(sock, deadline=deadline)
            if hello is None:
                return
            refusal = net.check_hello(hello, token=self._token)
            if refusal is not None:
                self._incr("handshake_refused_total")
                send({"type": "reject", "reason": refusal})
                return
            send({"type": "welcome", "name": self.name,
                  "fingerprint": net.schema_fingerprint(),
                  "stats": self.stats()})
            while not self._closed.is_set():
                msg = net.recv_frame(sock)
                if msg is None or msg.get("type") == "bye":
                    return
                self._last_contact = time.monotonic()
                self._dispatch(msg, send)
        except net.FrameError as exc:
            self._incr("protocol_errors_total")
            try:
                send({"type": "protocol_error",
                      "error": net.wire_error(exc)})
            except Exception:       # noqa: BLE001 — socket is gone
                pass
        except (OSError, net.RemoteUnavailableError,
                net.RequestTimeoutError):
            pass                    # peer vanished mid-frame
        finally:
            self._drop_conn(sock)

    # -- verbs -----------------------------------------------------------
    def _dispatch(self, msg, send):
        kind = msg.get("type")
        req_id = msg.get("id")
        try:
            if kind == "train_configure":
                self._handle_configure(req_id, msg, send)
            elif kind == "train_step":
                self._handle_step(req_id, msg, send)
            elif kind == "train_commit":
                self._handle_commit(req_id, msg, send)
            elif kind == "stats":
                send({"type": "stats", "id": req_id,
                      "value": self.stats()})
            # protocheck: ok(verb-dead) — operator liveness probe,
            # mirrors ReplicaServer; the coordinator heartbeats with
            # 'stats' because it also wants the worker's step serial
            elif kind == "ping":
                send({"type": "pong", "id": req_id})
            elif kind == "fetch_manifest":
                self._handle_manifest(req_id, send)
            elif kind == "fetch_artifact":
                self._send_artifact(req_id, msg.get("path"), send)
            else:
                send({"type": "error", "id": req_id,
                      "error": ("ServingError",
                                f"unknown verb {kind!r}")})
        except _faultinject.SimulatedCrash:
            raise
        except Exception as exc:    # noqa: BLE001 — forwarded typed
            send({"type": "error", "id": req_id,
                  "error": net.wire_error(exc)})

    def _handle_configure(self, req_id, msg, send):
        spec = msg.get("task")
        with self._task_lock:
            if spec != self._task_spec:
                self._task = task_from_spec(
                    spec, artifact_dir=self.artifact_dir)
                self._task_spec = spec
            task = self._task
        send({"type": "train_configured", "id": req_id,
              "name": self.name,
              "total_compiles": task.total_compiles()})

    def _die(self):
        """The injected-crash shape: with ``hard_exit`` the process is
        GONE (``os._exit`` — no atexit, no flush: models kill -9);
        in-process, the listener and every connection are torn down
        abruptly so the coordinator sees the same wire symptoms."""
        if self.hard_exit:
            os._exit(17)
        self._closed.set()
        self._close_listener()
        with self._conns_lock:
            conns = list(self._conns)
        for sock in conns:
            self._drop_conn(sock)

    def _handle_step(self, req_id, msg, send):
        _faultinject.event("train_step")
        if _faultinject.fires("trainer_crash_at_step"):
            self._die()
            return
        if _faultinject.fires("trainer_straggle"):
            time.sleep(float(os.environ.get(_STRAGGLE_ENV, "1.0")))
        with self._task_lock:
            task = self._task
        if task is None:
            send({"type": "error", "id": req_id,
                  "error": ("ServingError",
                            "train_step before train_configure")})
            return
        step = int(msg["step"])
        n_shards = int(msg["n_shards"])
        state = {k: np.asarray(v) for k, v in msg["state"].items()}
        out = {}
        for shard in msg["shards"]:
            shard = int(shard)
            loss_sum, gsums, rows = task.grad_sums(
                state, step, shard, n_shards)
            out[shard] = {"loss_sum": float(loss_sum),
                          "n_rows": int(rows),
                          "grads": {k: np.asarray(v, np.float32)
                                    for k, v in gsums.items()}}
        self.last_step = step
        self._incr("steps_total")
        send({"type": "train_grads", "id": req_id, "step": step,
              "shards": out})

    def _handle_commit(self, req_id, msg, send):
        """Followers-verify: re-hash the broadcast state and compare
        with the leader's manifest sha. A mismatch is reported
        honestly (ok=False) — the coordinator evicts us; agreeing
        with a sha we did not compute would defeat the barrier."""
        state = {k: np.asarray(v) for k, v in msg["state"].items()}
        ours = state_sha(state)
        ok = bool(ours == msg.get("sha"))
        if ok:
            self.committed_step = int(msg["step"])
            self.committed_sha = ours
            self._incr("commits_total")
        else:
            self._incr("commit_mismatches_total")
        _faultinject.event("train_commit")
        send({"type": "train_committed", "id": req_id, "ok": ok,
              "sha": ours})

    def _handle_manifest(self, req_id, send):
        if self.artifact_dir is None \
                or not os.path.isdir(self.artifact_dir):
            send({"type": "manifest", "id": req_id, "value": {}})
            return
        from ..io.artifact_store import dir_manifest
        send({"type": "manifest", "id": req_id,
              "value": dir_manifest(self.artifact_dir)})

    def _send_artifact(self, req_id, relpath, send):
        """One file of the artifact dir, path-confined and
        checksummed — lets a replacement worker provision its compile
        cache from this live peer."""
        try:
            if self.artifact_dir is None:
                raise ValueError(
                    f"worker {self.name} has no artifact dir to serve")
            if not isinstance(relpath, str) or os.path.isabs(relpath):
                raise ValueError(f"artifact path must be relative, "
                                 f"got {relpath!r}")
            root = os.path.realpath(self.artifact_dir)
            full = os.path.realpath(os.path.join(root, relpath))
            if not (full + os.sep).startswith(root + os.sep) \
                    and full != root:
                raise ValueError(
                    f"artifact path {relpath!r} escapes the "
                    "artifact dir")
            with open(full, "rb") as f:
                blob = f.read()
        except (OSError, ValueError) as exc:
            send({"type": "error", "id": req_id,
                  "error": net.wire_error(
                      exc if isinstance(exc, ValueError)
                      else ValueError(str(exc)))})
            return
        self._incr("artifacts_served_total")
        send({"type": "artifact", "id": req_id, "path": relpath,
              "blob": blob, "sha256": net.hash_blob(blob)})

    # -- introspection / lifecycle ---------------------------------------
    def stats(self):
        with self._task_lock:
            spec = dict(self._task_spec) if self._task_spec else None
        with self._conns_lock:
            snap = dict(self._counters)
            snap["open_connections"] = len(self._conns)
        snap.update({
            "addr": self.addr,
            "name": self.name,
            "task": spec,
            "last_step": self.last_step,
            "committed_step": self.committed_step,
            "committed_sha": self.committed_sha,
            "coordinator_age_s": self.coordinator_age_s(),
            "total_compiles": self.total_compiles(),
        })
        return snap

    def _close_listener(self):
        # shutdown BEFORE close: merely closing the fd leaves a
        # thread blocked in accept() stuck (Linux); shutdown wakes it
        # with a typed OSError immediately
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass

    def close(self):
        self._closed.set()
        self._close_listener()
        with self._conns_lock:
            conns = list(self._conns)
        for sock in conns:
            self._drop_conn(sock)
        self._acceptor.join(5.0)
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# host entrypoint
# ---------------------------------------------------------------------------


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="serve gradient computation for a train "
                    "coordinator over TCP")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=7731)
    ap.add_argument("--artifact-dir", default=None,
                    help="compile cache for program tasks; also "
                         "served to provisioning peers")
    ap.add_argument("--provision-from", default=None, metavar="ADDR",
                    help="cold-provision --artifact-dir over the wire "
                         "from a live peer worker before serving "
                         "(zero XLA compiles afterwards)")
    ap.add_argument("--park-deadline", type=float, default=None,
                    metavar="S",
                    help="exit status 3 when no coordinator has "
                         "spoken for S seconds (default: park "
                         "forever)")
    ap.add_argument("--hard-exit", action="store_true",
                    help="an injected trainer_crash_at_step calls "
                         "os._exit (SIGKILL shape) instead of a "
                         "socket teardown")
    args = ap.parse_args(argv)
    # racecheck: ok(global-mutation) — this IS the process entrypoint:
    # it owns the whole process and runs before any thread or jax
    # backend exists
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import paddle_tpu as fluid
    # racecheck: ok(global-mutation) — ditto: entrypoint-owned process,
    # called once before the first device op
    fluid.force_cpu()
    if args.provision_from:
        if not args.artifact_dir:
            ap.error("--provision-from requires --artifact-dir")
        from .net_worker import provision_from_remote
        report = provision_from_remote(args.provision_from,
                                       args.artifact_dir)
        print(f"provisioned {report['files']} files "
              f"({report['bytes']} bytes) from {args.provision_from} "
              f"in {report['wall_s']}s", flush=True)
    server = TrainWorkerServer(
        host=args.host, port=args.port,
        artifact_dir=args.artifact_dir, hard_exit=args.hard_exit)
    print(f"train worker ready on {server.addr} "
          f"(compiles={server.total_compiles()})", flush=True)
    try:
        while True:
            time.sleep(0.5)
            if args.park_deadline is not None \
                    and server.coordinator_age_s() > args.park_deadline:
                print(f"parked past the {args.park_deadline}s "
                      "deadline with no coordinator — exiting",
                      flush=True)
                return 3
    except KeyboardInterrupt:
        return 0
    finally:
        server.close()


if __name__ == "__main__":
    import sys
    sys.exit(main())
