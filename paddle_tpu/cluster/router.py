"""Router — the client-facing front of a replica pool.

One logical server over N replicas: ``submit()`` picks a replica via
a pluggable balancing policy, sheds at the cluster bound, and reroutes
a request whose chosen replica refuses it (full queue, open breaker,
dead worker); ``infer()`` adds transparent FAILOVER — a request that
died with its replica is resubmitted to a different one while its
deadline allows, so a replica crash costs latency, not answers. This
is the thin-routing-layer move of the reference Paddle's distribute
transpiler and the TF-Serving replica tier (arXiv:1605.08695), at
engine granularity.

Balancing policies (``POLICIES``):

- ``round_robin`` — rotate through eligible replicas; fair under
  uniform requests, blind to load and health beyond eligibility.
- ``least_outstanding`` — pick the replica with the fewest
  admitted-but-unfinished requests (``engine.outstanding()``, O(1)
  reads); the right default under variable request cost.
- ``health_aware`` (default) — least-outstanding over the healthiest
  tier: replicas whose circuit breaker currently admits and whose
  HealthMonitor reads READY sort before DEGRADED ones (breaker open /
  worker just died); non-serving states (STARTING, DRAINING, STOPPED)
  are excluded outright. The policy READS the existing per-engine
  health machinery — no second health system.

Every policy returns an ORDERED candidate list; the router tries each
in turn, so a single refusing replica never fails a request the next
replica would have taken.

On top of any policy, :meth:`Router.set_weights` splits traffic
across model VERSIONS (``replica.version`` labels) — the canary
traffic-shifting primitive ``cluster/deploy.py`` ramps deployments
with (docs/SERVING.md "Deploying a new version").
"""
import random
import threading
import time

from ..resilience import faultinject as _faultinject
from ..serving.batching import QueueFullError, ServerClosedError
from ..serving.health import (ServiceUnavailableError,
                              WorkerDiedError, serving_rank)
from ..serving.kv_pages import PagesExhaustedError
from ..serving.overload import (AdmissionController, RetryBudget,
                                RetryBudgetExhaustedError,
                                shed_counter)
from ..serving.sched import PRIORITIES, priority_rank
from . import net as _net

__all__ = ["BalancePolicy", "RoundRobinPolicy",
           "LeastOutstandingPolicy", "HealthAwarePolicy", "POLICIES",
           "ClusterOverloadError", "NoReadyReplicaError", "Router",
           "get_policy"]

# priority rank -> tier name (the inverse of sched.PRIORITIES)
_PRI_NAME = {rank: name for name, rank in PRIORITIES.items()}


class ClusterOverloadError(QueueFullError):
    """Cluster-level shed: every replica refused (or the pool-wide
    outstanding bound is hit). The typed signal that the POOL is the
    bottleneck — scale out — where a plain QueueFullError means one
    replica's queue filled. ``per_class`` (when the router built the
    error) maps each priority tier to its outstanding count at shed
    time, so the operator sees WHICH traffic holds the capacity, not
    just that the bound was hit."""

    def __init__(self, msg, per_class=None):
        super().__init__(msg)
        self.per_class = dict(per_class) if per_class else None


class NoReadyReplicaError(ServiceUnavailableError):
    """No replica is currently eligible to take traffic (all
    restarting, dead, or stopped). Distinct from overload: capacity is
    absent, not exhausted."""


# a router can front remote pools (a fleet coordinator routing across
# serve_remotes views); its typed sheds must survive the wire
_net.register_wire_error(ClusterOverloadError)
_net.register_wire_error(NoReadyReplicaError)


class BalancePolicy:
    """Order eligible replicas for one pick. Stateless unless noted."""

    name = "?"

    def order(self, replicas):
        raise NotImplementedError


class RoundRobinPolicy(BalancePolicy):
    name = "round_robin"

    def __init__(self):
        self._lock = threading.Lock()
        self._i = 0

    def order(self, replicas):
        if not replicas:
            return []
        with self._lock:
            i = self._i % len(replicas)
            self._i += 1
        return replicas[i:] + replicas[:i]


class LeastOutstandingPolicy(BalancePolicy):
    name = "least_outstanding"

    def order(self, replicas):
        return sorted(replicas, key=lambda r: r.outstanding())


class HealthAwarePolicy(BalancePolicy):
    name = "health_aware"

    # serving states, best first (health.SERVING_STATE_RANK — one
    # vocabulary with the membership view); anything unranked is not a
    # candidate

    def order(self, replicas):
        ranked = []
        for r in replicas:
            rank = serving_rank(r.health_state())
            if rank is None:
                continue
            ranked.append((0 if r.admits() else 2, rank,
                           r.outstanding(), r))
        ranked.sort(key=lambda t: t[:3])
        return [t[3] for t in ranked]


POLICIES = {p.name: p for p in (RoundRobinPolicy,
                                LeastOutstandingPolicy,
                                HealthAwarePolicy)}


def get_policy(policy):
    """A policy instance from a name, class, or instance."""
    if isinstance(policy, str):
        if policy not in POLICIES:
            raise ValueError(f"unknown balancing policy {policy!r}; "
                             f"one of {sorted(POLICIES)}")
        return POLICIES[policy]()
    if isinstance(policy, type):
        return policy()
    return policy


# submit-side refusals worth trying the NEXT replica for; anything
# else (BucketError, bad feed ValueError, never-fits
# PagesExhaustedError) would fail identically everywhere and
# propagates untouched
_REROUTABLE = (QueueFullError, ServiceUnavailableError,
               ServerClosedError, WorkerDiedError)


class Router:
    """Route requests across ``pool``'s replicas.

    ``max_cluster_queue`` bounds the POOL-WIDE outstanding count
    (queued + in dispatch, summed over replicas); beyond it, submits
    shed with :class:`ClusterOverloadError` before touching any
    replica — the cluster-level admission control on top of each
    engine's own ``max_queue``. ``None`` disables the pool bound (the
    per-replica bounds still hold).

    Overload controls (serving/overload.py, all off by default so the
    pre-PR-19 behavior is the zero-config baseline):

    - ``admission="adaptive"`` (or an AdmissionController) replaces
      the static bound with AIMD admission over observed sojourn —
      ``max_cluster_queue`` stays as the hard ceiling and is required;
      priority tiers see tiered effective limits, so batch sheds
      first and interactive last.
    - ``retry_budget`` (True / capacity / a RetryBudget) bounds
      failover + redrive + hedge amplification cluster-wide; a retry
      past the budget raises :class:`RetryBudgetExhaustedError`
      instead of storming.
    - ``hedge_delay_s`` hedges INTERACTIVE-tier ``infer`` traffic: a
      primary attempt slower than the delay gets a budget-funded
      duplicate on another replica; first settled answer wins.
    - ``default_timeout_s`` is resolved ONCE at ``infer``/``generate``
      entry when the caller gives no timeout, so every failover /
      redrive hop inherits the ORIGINAL deadline — a hop never
      restarts the clock against the engine's per-hop default.
    """

    def __init__(self, pool, policy="health_aware",
                 max_cluster_queue=None, weight_seed=None,
                 admission=None, retry_budget=None,
                 hedge_delay_s=None, default_timeout_s=30.0):
        self.pool = pool
        self.policy = get_policy(policy)
        self.max_cluster_queue = (None if max_cluster_queue is None
                                  else int(max_cluster_queue))
        self._weights = None            # version -> normalized weight
        self._weights_lock = threading.Lock()
        self._weight_rng = random.Random(weight_seed)
        if admission == "adaptive":
            if self.max_cluster_queue is None:
                raise ValueError(
                    "admission='adaptive' needs max_cluster_queue — "
                    "the fixed bound stays as the hard ceiling")
            admission = AdmissionController(
                hard_ceiling=self.max_cluster_queue)
        self.admission = admission      # AdmissionController or None
        if retry_budget is True:
            retry_budget = RetryBudget()
        elif isinstance(retry_budget, (int, float)):
            retry_budget = RetryBudget(capacity=retry_budget)
        self.retry_budget = retry_budget
        self.hedge_delay_s = (None if hedge_delay_s is None
                              else float(hedge_delay_s))
        self.default_timeout_s = (
            None if default_timeout_s is None
            else float(default_timeout_s))
        self._class_lock = threading.Lock()
        self._outstanding_by_class = {n: 0 for n in PRIORITIES}

    # -- weighted version-aware balancing --------------------------------
    def set_weights(self, weights, seed=None):
        """Split traffic across model VERSIONS (``replica.version``
        labels, stamped by cluster/deploy.py):
        ``set_weights({"v1": 0.99, "v2": 0.01})`` sends ~1% of picks
        to v2's replicas. Semantics the canary machinery leans on:

        - weight ``0.0`` (or a version absent from the dict) NEVER
          routes — a canary at weight 0 is deployed-but-dark, safe to
          numerics-check before any traffic touches it;
        - a single weight ``1.0`` ALWAYS routes to that version;
        - the per-request version draw is weighted-random from a
          router-owned RNG (``seed=``/``weight_seed=`` pin it for
          deterministic tests);
        - the non-chosen weight>0 versions stay in the candidate list
          AFTER the chosen version's replicas, so the reroute ladder
          and ``infer()`` failover still see the whole eligible pool —
          a refusing canary costs a reroute, never a lost request.

        ``set_weights(None)`` clears version routing (every replica is
        a candidate again, whatever its label). Weights need not sum
        to 1 — they are normalized at draw time."""
        if weights is None:
            with self._weights_lock:
                self._weights = None
                if seed is not None:
                    self._weight_rng = random.Random(seed)
            return
        cleaned = {}
        for version, w in weights.items():
            w = float(w)
            if w < 0 or not (w == w):       # negative or NaN
                raise ValueError(
                    f"weight for version {version!r} must be a "
                    f"finite value >= 0, got {w}")
            if w > 0:
                cleaned[version] = w
        if not cleaned:
            raise ValueError(
                "set_weights needs at least one version with "
                "weight > 0 (use set_weights(None) to clear "
                "version routing)")
        with self._weights_lock:
            self._weights = cleaned
            if seed is not None:
                self._weight_rng = random.Random(seed)

    def weights(self):
        """The live version-weight map (a copy), or None."""
        with self._weights_lock:
            return dict(self._weights) if self._weights else None

    # -- request path ----------------------------------------------------
    def _candidates(self, role=None):
        eligible = [r for r in self.pool.replicas()
                    if not r.restarting and r.alive()
                    and (role is None
                         or getattr(r, "role", None) == role)]
        with self._weights_lock:
            weights = self._weights
            rng = self._weight_rng
        if not weights:
            return self.policy.order(eligible)
        by_version = {}
        for r in eligible:
            by_version.setdefault(getattr(r, "version", None),
                                  []).append(r)
        # only versions that are both weighted AND currently have an
        # eligible replica can win the draw; zero-weight versions are
        # not candidates at all
        avail = [(v, w) for v, w in weights.items()
                 if by_version.get(v)]
        if not avail:
            return []
        total = sum(w for _, w in avail)
        with self._weights_lock:
            x = rng.random() * total
        chosen = avail[-1][0]
        for v, w in avail:
            x -= w
            if x < 0:
                chosen = v
                break
        ordered = self.policy.order(by_version[chosen])
        spill = [r for v, _ in avail if v != chosen
                 for r in by_version[v]]
        return ordered + self.policy.order(spill)

    def _resolve_rank(self, slo, priority):
        """The priority rank for a request: explicit ``priority=``
        outranks the SLO's tier; no signal at all = standard."""
        if priority is not None:
            return priority_rank(priority)
        if slo is not None:
            return priority_rank(slo)
        return PRIORITIES["standard"]

    def _shed(self, rank):
        self.pool.incr("cluster_shed_total")
        self.pool.incr(shed_counter(rank))

    def _per_class_outstanding(self):
        with self._class_lock:
            return dict(self._outstanding_by_class)

    def _track(self, handle, rank):
        """Per-class admission accounting on a successful submit: the
        class's outstanding count rises now and falls when the handle
        settles, and the settle latency (sojourn) feeds the adaptive
        admission controller's AIMD loop."""
        name = _PRI_NAME.get(rank, "standard")
        with self._class_lock:
            self._outstanding_by_class[name] += 1
        t0 = time.monotonic()

        def _done(_handle):
            with self._class_lock:
                self._outstanding_by_class[name] -= 1
            if self.admission is not None:
                self.admission.observe(time.monotonic() - t0)

        if hasattr(handle, "add_done_callback"):
            handle.add_done_callback(_done)
        else:           # untrackable foreign handle: release now
            with self._class_lock:
                self._outstanding_by_class[name] -= 1
        return handle

    def submit(self, item, timeout=None, role=None, slo=None,
               priority=None, **kw):
        """Pick a replica and submit; returns that replica's handle.
        ``role=`` restricts the pick to replicas carrying that
        disaggregation tag (``"prefill"`` / ``"decode"``).

        ``slo`` (an SLOClass, forwarded to the replica) and
        ``priority`` (a tier name, router-side only) set the request's
        overload tier; under adaptive admission the tiers see
        different effective limits, so batch sheds strictly before
        standard before interactive.

        Raises ClusterOverloadError (pool bound / adaptive admission
        refusal / every replica shed with a full queue),
        NoReadyReplicaError (no eligible replica), or the first
        non-reroutable submit error (BucketError etc.)."""
        rank = self._resolve_rank(slo, priority)
        outstanding = self.pool.total_outstanding()
        if self.max_cluster_queue is not None \
                and outstanding >= self.max_cluster_queue:
            self._shed(rank)
            raise ClusterOverloadError(
                f"cluster outstanding bound "
                f"({self.max_cluster_queue}) reached — every replica "
                "is saturated; back off or scale_up()",
                per_class=self._per_class_outstanding())
        if self.admission is not None \
                and not self.admission.admit(rank, outstanding):
            self._shed(rank)
            raise ClusterOverloadError(
                f"adaptive admission refused a "
                f"{_PRI_NAME.get(rank, 'standard')}-tier request at "
                f"{outstanding} outstanding (current limit "
                f"{self.admission.limit():.1f}) — the pool is past "
                "its knee; back off",
                per_class=self._per_class_outstanding())
        if slo is not None:
            kw = dict(kw, slo=slo)
        candidates = self._candidates(role=role)
        if _faultinject.fires("serving_replica_crash") and candidates:
            # chaos: the replica the policy just chose dies under the
            # request — the drill the pool's revival monitor + infer()
            # failover must absorb with zero losses
            candidates[0].crash()
        last = None
        rerouted = False
        for replica in candidates:
            try:
                return self._track(
                    replica.submit(item, timeout=timeout, **kw), rank)
            except PagesExhaustedError:
                raise            # never-fits: identical on every replica
            except _REROUTABLE as exc:
                last = exc
                rerouted = True
                self.pool.incr("reroutes_total")
        if rerouted:
            self._shed(rank)
            if isinstance(last, QueueFullError):
                raise ClusterOverloadError(
                    "every replica shed this request (all queues "
                    "full or breakers open)",
                    per_class=self._per_class_outstanding()) from last
            raise NoReadyReplicaError(
                "every replica refused this request") from last
        self._shed(rank)
        raise NoReadyReplicaError(
            "no eligible replica (all restarting, dead, or stopped)")

    def _spend_retry(self, cause):
        """Take a retry token before any failover / redrive / storm
        resubmission. No budget configured = unbounded (the pre-PR-19
        behavior). An empty bucket fails FAST with the typed error —
        retrying into an overload amplifies it."""
        if self.retry_budget is None:
            return
        if self.retry_budget.acquire():
            return
        self.pool.incr("retry_budget_exhausted_total")
        raise RetryBudgetExhaustedError(
            "cluster retry budget exhausted — failing fast instead "
            "of amplifying the overload; back off and resubmit"
        ) from cause

    def _note_success(self):
        if self.retry_budget is not None:
            self.retry_budget.note_success()

    def _await_hedged(self, handle, deadline, item, kw):
        """Interactive-tier hedging: give the primary attempt
        ``hedge_delay_s``; past that, a budget-funded duplicate goes
        to another replica and the first settled answer wins (the
        loser is abandoned — its cost is exactly what the retry
        budget meters). Falls back to a plain wait when the budget or
        the pool refuses the duplicate."""
        def _rem():
            return (None if deadline is None
                    else deadline - time.monotonic())

        def _wait_bound():
            r = _rem()
            return None if r is None else max(0.0, r) + 10.0

        first_wait = self.hedge_delay_s
        r = _rem()
        if r is not None:
            first_wait = min(first_wait, max(0.0, r) + 10.0)
        if handle.wait(first_wait):
            return handle.result(0)
        if not self.retry_budget.acquire():
            return handle.result(_wait_bound())
        try:
            other = self.submit(item, timeout=_rem(), **kw)
        except (PagesExhaustedError, *_REROUTABLE):
            self.retry_budget.note_success()   # unused token back
            return handle.result(_wait_bound())
        self.pool.incr("hedges_total")
        while True:
            if handle.wait(0.005):
                winner, loser = handle, other   # primary wins ties
                break
            if other.wait(0.005):
                winner, loser = other, handle
                break
            r = _rem()
            if r is not None and r <= -10.0:    # grace exhausted
                return handle.result(0)
        if winner is other:
            self.pool.incr("hedge_wins_total")
        try:
            return winner.result(0)
        except (WorkerDiedError, ServerClosedError):
            # the winner's replica died mid-answer; the other attempt
            # may still be good — drain it before giving up
            return loser.result(_wait_bound())

    def infer(self, item, timeout=None, failover=True, **kw):
        """Synchronous submit + wait, with cross-replica failover: if
        the serving replica dies (WorkerDiedError) or closes under the
        request (ServerClosedError), the request is resubmitted to a
        DIFFERENT replica — bounded by the remaining deadline, by one
        attempt per replica plus one (so a pool where everything is
        dying still terminates with the typed error), and by the
        retry budget when one is configured (exhaustion raises
        RetryBudgetExhaustedError instead of storming). Timeouts and
        request-content errors never fail over: a deadline that
        expired on one replica has expired everywhere, and a bad feed
        is bad everywhere.

        With no timeout the router's ``default_timeout_s`` applies —
        resolved ONCE here, so failover hops inherit the original
        deadline rather than restarting the clock per hop.

        Interactive-tier requests hedge when ``hedge_delay_s`` and a
        retry budget are configured (see _await_hedged). The
        ``serving_retry_storm`` fault point drops a completed
        attempt's answer in flight, forcing a retry — the drill that
        proves the budget bounds amplification."""
        if timeout is None:
            timeout = self.default_timeout_s
        deadline = (None if timeout is None
                    else time.monotonic() + float(timeout))
        rank = self._resolve_rank(kw.get("slo"), kw.get("priority"))
        hedged = (self.hedge_delay_s is not None
                  and self.retry_budget is not None
                  and rank == PRIORITIES["interactive"])
        attempts = max(2, len(self.pool.replicas()) + 1)
        last = None
        for _ in range(attempts):
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                break
            handle = self.submit(item, timeout=remaining, **kw)
            if _faultinject.fires("serving_retry_storm"):
                # chaos: the attempt's answer is lost in flight (the
                # replica still burns capacity serving it — exactly
                # how a real retry storm feeds itself); the retry
                # below must pass the budget gate
                last = WorkerDiedError(
                    "injected retry storm: response dropped in "
                    "flight")
                self._spend_retry(last)
                self.pool.incr("failovers_total")
                continue
            try:
                if hedged:
                    result = self._await_hedged(handle, deadline,
                                                item, kw)
                else:
                    # grace past the serving deadline, like
                    # engine.infer: the structured error is the real
                    # signal
                    result = handle.result(
                        None if remaining is None
                        else remaining + 10.0)
                self._note_success()
                return result
            except (WorkerDiedError, ServerClosedError) as exc:
                last = exc
                if not failover:
                    raise
                self._spend_retry(exc)
                self.pool.incr("failovers_total")
        if last is not None:
            raise last
        raise NoReadyReplicaError(
            "request deadline expired before any replica answered")

    # -- disaggregated prefill/decode ------------------------------------
    def generate(self, prompt, max_new=None, timeout=None, slo=None,
                 **kw):
        """Generate over a DISAGGREGATED pool: prefill on a
        ``role="prefill"`` replica (``prefill_only=True`` — it resolves
        with a KV handoff blob, never holding a decode slot), then hand
        the blob to a ``role="decode"`` replica via the ``handoff``
        verb and return its full token sequence. With no role split in
        the pool this degrades to the ordinary failover ``infer``.

        Fault containment is the same zero-loss contract as infer():
        every refusal or death is typed, and each phase redrives on a
        surviving replica of its role while the deadline allows. The
        ``serving_handoff_drop`` chaos point fires in the gap between
        prefill completing and the blob reaching a decode replica — the
        prefill replica dies WITH the KV state, so the only correct
        recovery is a fresh prefill on a survivor (counted in
        ``handoff_redrives_total``).

        Deadline/SLO inheritance: the timeout (caller's, or the
        router's ``default_timeout_s``) is resolved to ONE absolute
        deadline here, before any hop, and every re-prefill and
        failover hop runs against the remainder — a redrive can
        expire, it can never restart the clock. The SLO (class AND
        priority) rides ``sub_kw`` onto every hop, and redrive hops
        carry ``queued_for_s`` (time already burned since entry) so
        the serving engine backdates ``enqueued_at`` — TTFT and EDF
        order are measured from the ORIGINAL arrival on whichever
        replica finally serves the request. Redrives and failovers
        consume the retry budget when one is configured."""
        sub_kw = dict(kw)
        if max_new is not None:
            sub_kw["max_new"] = max_new
        if slo is not None:
            sub_kw["slo"] = slo
        if timeout is None:
            timeout = self.default_timeout_s
        if not self._candidates(role="prefill") \
                or not self._candidates(role="decode"):
            return self.infer(prompt, timeout=timeout, **sub_kw)
        t_entry = time.monotonic()
        deadline = (None if timeout is None
                    else t_entry + float(timeout))

        def _remaining():
            return (None if deadline is None
                    else deadline - time.monotonic())

        # phase 1: prefill → KV handoff blob
        attempts = max(2, len(self.pool.replicas()) + 1)
        state = None
        last = None
        first_hop = True
        for _ in range(attempts):
            rem = _remaining()
            if rem is not None and rem <= 0:
                break
            cands = self._candidates(role="prefill")
            if not cands:
                last = NoReadyReplicaError(
                    "no prefill-role replica is eligible")
                time.sleep(0.05)  # the pool monitor revives crashed ones
                continue
            rep = cands[0]
            hop_kw = dict(sub_kw)
            if not first_hop:
                # a redrive: the new replica must measure TTFT from
                # the original arrival, not from this hop
                hop_kw["queued_for_s"] = time.monotonic() - t_entry
            first_hop = False
            try:
                handle = rep.submit(prompt, timeout=rem,
                                    prefill_only=True, **hop_kw)
                state = handle.result(
                    None if rem is None else rem + 10.0)
            except PagesExhaustedError:
                raise        # never-fits: identical on every replica
            except _REROUTABLE as exc:
                last = exc
                self._spend_retry(exc)
                self.pool.incr("handoff_redrives_total")
                continue
            if _faultinject.fires("serving_handoff_drop"):
                # chaos: the prefill replica dies WITH the finished
                # blob, before any decode replica saw it — the KV
                # state is gone, so recovery is a fresh prefill on a
                # survivor, never a dangling half-handoff
                rep.crash()
                state = None
                last = WorkerDiedError(
                    f"prefill replica {rep.name} died mid-handoff")
                self._spend_retry(last)
                self.pool.incr("handoff_redrives_total")
                continue
            break
        if state is None:
            if last is not None:
                raise last
            raise NoReadyReplicaError(
                "request deadline expired before prefill completed")

        # phase 2: blob → decode-role replica
        hand_kw = {} if slo is None else {"slo": slo}
        last = None
        for _ in range(attempts):
            rem = _remaining()
            if rem is not None and rem <= 0:
                break
            cands = self._candidates(role="decode")
            if not cands:
                last = NoReadyReplicaError(
                    "no decode-role replica is eligible")
                time.sleep(0.05)
                continue
            rep = cands[0]
            try:
                handle = rep.handoff(state, timeout=rem, **hand_kw)
                self.pool.incr("handoffs_total")
                result = handle.result(
                    None if rem is None else rem + 10.0)
                self._note_success()
                return result
            except _REROUTABLE as exc:
                # the router still holds the blob, so a decode death
                # replays it on the next decode replica — the handoff
                # is idempotent (import allocates fresh pages)
                last = exc
                self._spend_retry(exc)
                self.pool.incr("failovers_total")
        if last is not None:
            raise last
        raise NoReadyReplicaError(
            "request deadline expired before any decode replica "
            "answered")

    # -- introspection / lifecycle ---------------------------------------
    def stats(self):
        snap = self.pool.stats()
        snap["policy"] = self.policy.name
        snap["max_cluster_queue"] = self.max_cluster_queue
        snap["weights"] = self.weights()
        # the operator's view of the knee: the admission controller's
        # live limit + pressure (sojourn EWMA over its target), the
        # retry-budget level, and the per-class outstanding/shed
        # split — visible, not inferred
        adm = (None if self.admission is None
               else self.admission.snapshot())
        pressure = None
        if adm is not None and adm["sojourn_ewma_s"] is not None:
            pressure = min(1.0, adm["sojourn_ewma_s"]
                           / adm["target_delay_s"])
        snap["overload"] = {
            "admission": adm,
            "pressure": pressure,
            "retry_budget": (None if self.retry_budget is None
                             else self.retry_budget.snapshot()),
            "hedge_delay_s": self.hedge_delay_s,
            "default_timeout_s": self.default_timeout_s,
            "outstanding_by_class": self._per_class_outstanding(),
            "shed_by_class": {
                name: snap.get(f"shed_{name}_total", 0)
                for name in PRIORITIES},
        }
        return snap

    def close(self, drain=False, drain_timeout=None):
        self.pool.close(drain=drain, drain_timeout=drain_timeout)
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
