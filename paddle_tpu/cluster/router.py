"""Router — the client-facing front of a replica pool.

One logical server over N replicas: ``submit()`` picks a replica via
a pluggable balancing policy, sheds at the cluster bound, and reroutes
a request whose chosen replica refuses it (full queue, open breaker,
dead worker); ``infer()`` adds transparent FAILOVER — a request that
died with its replica is resubmitted to a different one while its
deadline allows, so a replica crash costs latency, not answers. This
is the thin-routing-layer move of the reference Paddle's distribute
transpiler and the TF-Serving replica tier (arXiv:1605.08695), at
engine granularity.

Balancing policies (``POLICIES``):

- ``round_robin`` — rotate through eligible replicas; fair under
  uniform requests, blind to load and health beyond eligibility.
- ``least_outstanding`` — pick the replica with the fewest
  admitted-but-unfinished requests (``engine.outstanding()``, O(1)
  reads); the right default under variable request cost.
- ``health_aware`` (default) — least-outstanding over the healthiest
  tier: replicas whose circuit breaker currently admits and whose
  HealthMonitor reads READY sort before DEGRADED ones (breaker open /
  worker just died); non-serving states (STARTING, DRAINING, STOPPED)
  are excluded outright. The policy READS the existing per-engine
  health machinery — no second health system.

Every policy returns an ORDERED candidate list; the router tries each
in turn, so a single refusing replica never fails a request the next
replica would have taken.

On top of any policy, :meth:`Router.set_weights` splits traffic
across model VERSIONS (``replica.version`` labels) — the canary
traffic-shifting primitive ``cluster/deploy.py`` ramps deployments
with (docs/SERVING.md "Deploying a new version").
"""
import random
import threading
import time

from ..resilience import faultinject as _faultinject
from ..serving.batching import QueueFullError, ServerClosedError
from ..serving.health import (ServiceUnavailableError,
                              WorkerDiedError, serving_rank)
from ..serving.kv_pages import PagesExhaustedError

__all__ = ["BalancePolicy", "RoundRobinPolicy",
           "LeastOutstandingPolicy", "HealthAwarePolicy", "POLICIES",
           "ClusterOverloadError", "NoReadyReplicaError", "Router",
           "get_policy"]


class ClusterOverloadError(QueueFullError):
    """Cluster-level shed: every replica refused (or the pool-wide
    outstanding bound is hit). The typed signal that the POOL is the
    bottleneck — scale out — where a plain QueueFullError means one
    replica's queue filled."""


class NoReadyReplicaError(ServiceUnavailableError):
    """No replica is currently eligible to take traffic (all
    restarting, dead, or stopped). Distinct from overload: capacity is
    absent, not exhausted."""


class BalancePolicy:
    """Order eligible replicas for one pick. Stateless unless noted."""

    name = "?"

    def order(self, replicas):
        raise NotImplementedError


class RoundRobinPolicy(BalancePolicy):
    name = "round_robin"

    def __init__(self):
        self._lock = threading.Lock()
        self._i = 0

    def order(self, replicas):
        if not replicas:
            return []
        with self._lock:
            i = self._i % len(replicas)
            self._i += 1
        return replicas[i:] + replicas[:i]


class LeastOutstandingPolicy(BalancePolicy):
    name = "least_outstanding"

    def order(self, replicas):
        return sorted(replicas, key=lambda r: r.outstanding())


class HealthAwarePolicy(BalancePolicy):
    name = "health_aware"

    # serving states, best first (health.SERVING_STATE_RANK — one
    # vocabulary with the membership view); anything unranked is not a
    # candidate

    def order(self, replicas):
        ranked = []
        for r in replicas:
            rank = serving_rank(r.health_state())
            if rank is None:
                continue
            ranked.append((0 if r.admits() else 2, rank,
                           r.outstanding(), r))
        ranked.sort(key=lambda t: t[:3])
        return [t[3] for t in ranked]


POLICIES = {p.name: p for p in (RoundRobinPolicy,
                                LeastOutstandingPolicy,
                                HealthAwarePolicy)}


def get_policy(policy):
    """A policy instance from a name, class, or instance."""
    if isinstance(policy, str):
        if policy not in POLICIES:
            raise ValueError(f"unknown balancing policy {policy!r}; "
                             f"one of {sorted(POLICIES)}")
        return POLICIES[policy]()
    if isinstance(policy, type):
        return policy()
    return policy


# submit-side refusals worth trying the NEXT replica for; anything
# else (BucketError, bad feed ValueError, never-fits
# PagesExhaustedError) would fail identically everywhere and
# propagates untouched
_REROUTABLE = (QueueFullError, ServiceUnavailableError,
               ServerClosedError, WorkerDiedError)


class Router:
    """Route requests across ``pool``'s replicas.

    ``max_cluster_queue`` bounds the POOL-WIDE outstanding count
    (queued + in dispatch, summed over replicas); beyond it, submits
    shed with :class:`ClusterOverloadError` before touching any
    replica — the cluster-level admission control on top of each
    engine's own ``max_queue``. ``None`` disables the pool bound (the
    per-replica bounds still hold).
    """

    def __init__(self, pool, policy="health_aware",
                 max_cluster_queue=None, weight_seed=None):
        self.pool = pool
        self.policy = get_policy(policy)
        self.max_cluster_queue = (None if max_cluster_queue is None
                                  else int(max_cluster_queue))
        self._weights = None            # version -> normalized weight
        self._weights_lock = threading.Lock()
        self._weight_rng = random.Random(weight_seed)

    # -- weighted version-aware balancing --------------------------------
    def set_weights(self, weights, seed=None):
        """Split traffic across model VERSIONS (``replica.version``
        labels, stamped by cluster/deploy.py):
        ``set_weights({"v1": 0.99, "v2": 0.01})`` sends ~1% of picks
        to v2's replicas. Semantics the canary machinery leans on:

        - weight ``0.0`` (or a version absent from the dict) NEVER
          routes — a canary at weight 0 is deployed-but-dark, safe to
          numerics-check before any traffic touches it;
        - a single weight ``1.0`` ALWAYS routes to that version;
        - the per-request version draw is weighted-random from a
          router-owned RNG (``seed=``/``weight_seed=`` pin it for
          deterministic tests);
        - the non-chosen weight>0 versions stay in the candidate list
          AFTER the chosen version's replicas, so the reroute ladder
          and ``infer()`` failover still see the whole eligible pool —
          a refusing canary costs a reroute, never a lost request.

        ``set_weights(None)`` clears version routing (every replica is
        a candidate again, whatever its label). Weights need not sum
        to 1 — they are normalized at draw time."""
        if weights is None:
            with self._weights_lock:
                self._weights = None
                if seed is not None:
                    self._weight_rng = random.Random(seed)
            return
        cleaned = {}
        for version, w in weights.items():
            w = float(w)
            if w < 0 or not (w == w):       # negative or NaN
                raise ValueError(
                    f"weight for version {version!r} must be a "
                    f"finite value >= 0, got {w}")
            if w > 0:
                cleaned[version] = w
        if not cleaned:
            raise ValueError(
                "set_weights needs at least one version with "
                "weight > 0 (use set_weights(None) to clear "
                "version routing)")
        with self._weights_lock:
            self._weights = cleaned
            if seed is not None:
                self._weight_rng = random.Random(seed)

    def weights(self):
        """The live version-weight map (a copy), or None."""
        with self._weights_lock:
            return dict(self._weights) if self._weights else None

    # -- request path ----------------------------------------------------
    def _candidates(self, role=None):
        eligible = [r for r in self.pool.replicas()
                    if not r.restarting and r.alive()
                    and (role is None
                         or getattr(r, "role", None) == role)]
        with self._weights_lock:
            weights = self._weights
            rng = self._weight_rng
        if not weights:
            return self.policy.order(eligible)
        by_version = {}
        for r in eligible:
            by_version.setdefault(getattr(r, "version", None),
                                  []).append(r)
        # only versions that are both weighted AND currently have an
        # eligible replica can win the draw; zero-weight versions are
        # not candidates at all
        avail = [(v, w) for v, w in weights.items()
                 if by_version.get(v)]
        if not avail:
            return []
        total = sum(w for _, w in avail)
        with self._weights_lock:
            x = rng.random() * total
        chosen = avail[-1][0]
        for v, w in avail:
            x -= w
            if x < 0:
                chosen = v
                break
        ordered = self.policy.order(by_version[chosen])
        spill = [r for v, _ in avail if v != chosen
                 for r in by_version[v]]
        return ordered + self.policy.order(spill)

    def submit(self, item, timeout=None, role=None, **kw):
        """Pick a replica and submit; returns that replica's handle.
        ``role=`` restricts the pick to replicas carrying that
        disaggregation tag (``"prefill"`` / ``"decode"``).

        Raises ClusterOverloadError (pool bound, or every replica shed
        with a full queue), NoReadyReplicaError (no eligible replica),
        or the first non-reroutable submit error (BucketError etc.)."""
        if self.max_cluster_queue is not None \
                and self.pool.total_outstanding() \
                >= self.max_cluster_queue:
            self.pool.incr("cluster_shed_total")
            raise ClusterOverloadError(
                f"cluster outstanding bound "
                f"({self.max_cluster_queue}) reached — every replica "
                "is saturated; back off or scale_up()")
        candidates = self._candidates(role=role)
        if _faultinject.fires("serving_replica_crash") and candidates:
            # chaos: the replica the policy just chose dies under the
            # request — the drill the pool's revival monitor + infer()
            # failover must absorb with zero losses
            candidates[0].crash()
        last = None
        rerouted = False
        for replica in candidates:
            try:
                return replica.submit(item, timeout=timeout, **kw)
            except PagesExhaustedError:
                raise            # never-fits: identical on every replica
            except _REROUTABLE as exc:
                last = exc
                rerouted = True
                self.pool.incr("reroutes_total")
        if rerouted:
            self.pool.incr("cluster_shed_total")
            if isinstance(last, QueueFullError):
                raise ClusterOverloadError(
                    "every replica shed this request (all queues "
                    "full or breakers open)") from last
            raise NoReadyReplicaError(
                "every replica refused this request") from last
        self.pool.incr("cluster_shed_total")
        raise NoReadyReplicaError(
            "no eligible replica (all restarting, dead, or stopped)")

    def infer(self, item, timeout=None, failover=True, **kw):
        """Synchronous submit + wait, with cross-replica failover: if
        the serving replica dies (WorkerDiedError) or closes under the
        request (ServerClosedError), the request is resubmitted to a
        DIFFERENT replica — bounded by the remaining deadline and by
        one attempt per replica plus one (so a pool where everything
        is dying still terminates with the typed error). Timeouts and
        request-content errors never fail over: a deadline that
        expired on one replica has expired everywhere, and a bad feed
        is bad everywhere."""
        deadline = (None if timeout is None
                    else time.monotonic() + float(timeout))
        attempts = max(2, len(self.pool.replicas()) + 1)
        last = None
        for _ in range(attempts):
            remaining = (None if deadline is None
                         else deadline - time.monotonic())
            if remaining is not None and remaining <= 0:
                break
            handle = self.submit(item, timeout=remaining, **kw)
            try:
                # grace past the serving deadline, like engine.infer:
                # the structured error is the real signal
                return handle.result(
                    None if remaining is None else remaining + 10.0)
            except (WorkerDiedError, ServerClosedError) as exc:
                last = exc
                if not failover:
                    raise
                self.pool.incr("failovers_total")
        if last is not None:
            raise last
        raise NoReadyReplicaError(
            "request deadline expired before any replica answered")

    # -- disaggregated prefill/decode ------------------------------------
    def generate(self, prompt, max_new=None, timeout=None, slo=None,
                 **kw):
        """Generate over a DISAGGREGATED pool: prefill on a
        ``role="prefill"`` replica (``prefill_only=True`` — it resolves
        with a KV handoff blob, never holding a decode slot), then hand
        the blob to a ``role="decode"`` replica via the ``handoff``
        verb and return its full token sequence. With no role split in
        the pool this degrades to the ordinary failover ``infer``.

        Fault containment is the same zero-loss contract as infer():
        every refusal or death is typed, and each phase redrives on a
        surviving replica of its role while the deadline allows. The
        ``serving_handoff_drop`` chaos point fires in the gap between
        prefill completing and the blob reaching a decode replica — the
        prefill replica dies WITH the KV state, so the only correct
        recovery is a fresh prefill on a survivor (counted in
        ``handoff_redrives_total``)."""
        sub_kw = dict(kw)
        if max_new is not None:
            sub_kw["max_new"] = max_new
        if slo is not None:
            sub_kw["slo"] = slo
        if not self._candidates(role="prefill") \
                or not self._candidates(role="decode"):
            return self.infer(prompt, timeout=timeout, **sub_kw)
        deadline = (None if timeout is None
                    else time.monotonic() + float(timeout))

        def _remaining():
            return (None if deadline is None
                    else deadline - time.monotonic())

        # phase 1: prefill → KV handoff blob
        attempts = max(2, len(self.pool.replicas()) + 1)
        state = None
        last = None
        for _ in range(attempts):
            rem = _remaining()
            if rem is not None and rem <= 0:
                break
            cands = self._candidates(role="prefill")
            if not cands:
                last = NoReadyReplicaError(
                    "no prefill-role replica is eligible")
                time.sleep(0.05)  # the pool monitor revives crashed ones
                continue
            rep = cands[0]
            try:
                handle = rep.submit(prompt, timeout=rem,
                                    prefill_only=True, **sub_kw)
                state = handle.result(
                    None if rem is None else rem + 10.0)
            except PagesExhaustedError:
                raise        # never-fits: identical on every replica
            except _REROUTABLE as exc:
                last = exc
                self.pool.incr("handoff_redrives_total")
                continue
            if _faultinject.fires("serving_handoff_drop"):
                # chaos: the prefill replica dies WITH the finished
                # blob, before any decode replica saw it — the KV
                # state is gone, so recovery is a fresh prefill on a
                # survivor, never a dangling half-handoff
                rep.crash()
                state = None
                last = WorkerDiedError(
                    f"prefill replica {rep.name} died mid-handoff")
                self.pool.incr("handoff_redrives_total")
                continue
            break
        if state is None:
            if last is not None:
                raise last
            raise NoReadyReplicaError(
                "request deadline expired before prefill completed")

        # phase 2: blob → decode-role replica
        hand_kw = {} if slo is None else {"slo": slo}
        last = None
        for _ in range(attempts):
            rem = _remaining()
            if rem is not None and rem <= 0:
                break
            cands = self._candidates(role="decode")
            if not cands:
                last = NoReadyReplicaError(
                    "no decode-role replica is eligible")
                time.sleep(0.05)
                continue
            rep = cands[0]
            try:
                handle = rep.handoff(state, timeout=rem, **hand_kw)
                self.pool.incr("handoffs_total")
                return handle.result(
                    None if rem is None else rem + 10.0)
            except _REROUTABLE as exc:
                # the router still holds the blob, so a decode death
                # replays it on the next decode replica — the handoff
                # is idempotent (import allocates fresh pages)
                last = exc
                self.pool.incr("failovers_total")
        if last is not None:
            raise last
        raise NoReadyReplicaError(
            "request deadline expired before any decode replica "
            "answered")

    # -- introspection / lifecycle ---------------------------------------
    def stats(self):
        snap = self.pool.stats()
        snap["policy"] = self.policy.name
        snap["max_cluster_queue"] = self.max_cluster_queue
        snap["weights"] = self.weights()
        return snap

    def close(self, drain=False, drain_timeout=None):
        self.pool.close(drain=drain, drain_timeout=drain_timeout)
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
