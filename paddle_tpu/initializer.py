"""Parameter initializers.

Parity with python/paddle/fluid/initializer.py — each initializer appends
an init op to the *startup program* for the given variable; running the
startup Executor materializes all parameters on device, exactly like
fluid's two-program idiom.
"""
import math

import numpy as np

from .core import framework

__all__ = ["Constant", "Uniform", "Normal", "TruncatedNormal", "Xavier",
           "MSRA", "Bilinear", "NumpyArrayInitializer",
           "ConstantInitializer", "UniformInitializer", "NormalInitializer",
           "TruncatedNormalInitializer", "XavierInitializer",
           "MSRAInitializer", "BilinearInitializer", "force_init_on_cpu",
           "init_on_cpu"]


def force_init_on_cpu():  # fluid-compat; meaningless under XLA
    return False


import contextlib


@contextlib.contextmanager
def init_on_cpu():
    yield


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError

    @staticmethod
    def _fans(var):
        """Fan-in/out matching fluid's conventions: fc weights are
        [in, out]; conv kernels are fluid OIHW [cout, cin/g, k...] so the
        receptive field is shape[2:], fan_in = cin*prod(k), fan_out =
        cout*prod(k) (reference python/paddle/fluid/initializer.py
        _compute_fans)."""
        shape = var.shape
        if len(shape) < 2:
            n = int(shape[0]) if shape else 1
            return n, n
        if len(shape) == 2:
            return int(shape[0]), int(shape[1])
        receptive = int(np.prod(shape[2:]))
        fan_in = int(shape[1]) * receptive
        fan_out = int(shape[0]) * receptive
        return fan_in, fan_out


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, var, block):
        block.append_op(type="fill_constant", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "value": float(self.value)})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        block.append_op(type="uniform_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "min": float(self.low), "max": float(self.high),
                               "seed": self.seed})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(type="gaussian_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "mean": float(self.loc), "std": float(self.scale),
                               "seed": self.seed})


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        block.append_op(type="truncated_gaussian_random",
                        outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "mean": float(self.loc), "std": float(self.scale),
                               "seed": self.seed})


class XavierInitializer(Initializer):
    """Glorot init (reference python/paddle/fluid/initializer.py
    XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = \
            uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fan_in, fan_out = self._fans(var)
        fan_in = self.fan_in if self.fan_in is not None else fan_in
        fan_out = self.fan_out if self.fan_out is not None else fan_out
        if self.uniform:
            limit = math.sqrt(6.0 / (fan_in + fan_out))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (fan_in + fan_out))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """He init (reference MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fan_in, _ = self._fans(var)
        fan_in = self.fan_in if self.fan_in is not None else fan_in
        if self.uniform:
            limit = math.sqrt(6.0 / fan_in)
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / fan_in)
            NormalInitializer(0.0, std, self.seed)(var, block)


class BilinearInitializer(Initializer):
    """Bilinear upsampling kernels for conv_transpose (reference
    BilinearInitializer). Computes the weight on host and embeds it."""

    def __call__(self, var, block):
        # conv2d_transpose weights are fluid IOHW: [cin, cout/g, kh, kw]
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs 4D weights")
        kh, kw = shape[2], shape[3]
        f = math.ceil(kw / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        w = np.zeros(shape, dtype=np.float32)
        for i in range(kh):
            for j in range(kw):
                v = (1 - abs(j / f - c)) * (1 - abs(i / f - c))
                for ch in range(min(shape[0], shape[1])):
                    w[ch, ch, i, j] = v
        block.append_op(type="assign_value", outputs={"Out": [var.name]},
                        attrs={"values": w, "dtype": var.dtype})


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        block.append_op(type="assign_value", outputs={"Out": [var.name]},
                        attrs={"values": self.value, "dtype": var.dtype})


# fluid short aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer
