"""paddle_tpu — a TPU-native deep-learning framework with the capability
surface of PaddlePaddle (reference: dut3062796s/Paddle, Fluid era).

The public API mirrors ``paddle.fluid`` so reference users can write::

    import paddle_tpu as fluid
    x = fluid.layers.data(name="x", shape=[784])
    y = fluid.layers.fc(x, size=10, act="softmax")
    ...
    exe = fluid.Executor(fluid.TPUPlace())

while the implementation is jax/XLA/pallas end to end: programs lower to
single fused XLA executables, parallelism is jax.sharding over device
meshes, and hot kernels are Pallas.
"""
# op lowering rules must register before any program executes
from .ops import basic as _ops_basic          # noqa: F401
from .ops import nn as _ops_nn                # noqa: F401
from .ops import optimizer_ops as _ops_opt    # noqa: F401
from .ops import transformer_ops as _ops_tf   # noqa: F401
from .ops import moe as _ops_moe              # noqa: F401
from .ops import sequence as _ops_seq         # noqa: F401
from .ops import rnn as _ops_rnn              # noqa: F401
from .ops import control_flow as _ops_cf      # noqa: F401
from .ops import crf_ctc as _ops_crf          # noqa: F401
from .ops import detection as _ops_det        # noqa: F401
from .ops import eval_ops as _ops_eval        # noqa: F401
from .ops import extras as _ops_extras        # noqa: F401
from .ops import fused_loss as _ops_fused     # noqa: F401

from .core.framework import (                  # noqa: F401
    Program, Block, Variable, Parameter, Operator,
    default_main_program, default_startup_program, program_guard,
    switch_main_program, switch_startup_program, name_scope, get_var)
from .core.executor import force_cpu           # noqa: F401
from .core.executor import (                   # noqa: F401
    Executor, Scope, global_scope, scope_guard, _switch_scope,
    CPUPlace, TPUPlace, CUDAPlace)
from .core.backward import append_backward     # noqa: F401
from .core.sequence import SequenceBatch, to_sequence_batch  # noqa: F401
from .core import unique_name                  # noqa: F401

from . import layers                           # noqa: F401
from . import nets                             # noqa: F401
from . import parallel                         # noqa: F401
from .parallel import (ParallelExecutor, ExecutionStrategy,
                       BuildStrategy)          # noqa: F401
from .parallel.transpiler import DistributeTranspiler  # noqa: F401
from .transpiler import (InferenceTranspiler, memory_optimize,
                         release_memory)       # noqa: F401
from . import initializer                      # noqa: F401
from . import optimizer                        # noqa: F401
from . import regularizer                      # noqa: F401
from . import clip                             # noqa: F401
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from .data_feeder import DataFeeder            # noqa: F401
from . import io                               # noqa: F401
from . import resilience                       # noqa: F401
from . import serving                          # noqa: F401
from . import cluster                          # noqa: F401
from . import reader                           # noqa: F401
from . import dataset                          # noqa: F401
from .reader import batch                      # noqa: F401
from . import metrics                          # noqa: F401
from . import profiler                         # noqa: F401
from . import contrib                          # noqa: F401
from . import average                          # noqa: F401
from .trainer import (Trainer, BeginEpochEvent, EndEpochEvent,
                      BeginStepEvent, EndStepEvent,
                      CheckpointConfig)        # noqa: F401
from .inferencer import Inferencer             # noqa: F401
from . import evaluator                        # noqa: F401
from . import debugger                         # noqa: F401
from . import transpiler                       # noqa: F401
from . import lod_tensor                       # noqa: F401
from .lod_tensor import (create_lod_tensor,
                         create_random_int_lodtensor)  # noqa: F401
from . import recordio_writer                  # noqa: F401
from . import default_scope_funcs              # noqa: F401
from . import concurrency                      # noqa: F401
from .concurrency import (make_channel, channel_send, channel_recv,
                          channel_close, Select)  # noqa: F401

__version__ = "0.1.0"
