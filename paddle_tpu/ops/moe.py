"""Mixture-of-Experts FFN op — expert-parallel over the mesh 'ep' axis.

The reference has no MoE (it predates them); this extends the framework
the way its fused contrib ops extend the op set, but designed TPU-first
after the GShard/Switch recipe: top-k gating with a *static* per-expert
capacity, dispatch/combine expressed as einsums (MXU-friendly, static
shapes), and expert weights sharded over the mesh 'ep' axis so GSPMD
inserts the token all_to_all over ICI automatically via sharding
constraints on the [experts, capacity, dim] intermediates.

Everything is one fused XLA program: no per-expert Python loops, no
dynamic shapes, no host round-trips.
"""
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.registry import register_op

__all__ = ["top_k_gating", "moe_apply", "moe_apply_no_drop",
           "moe_apply_no_drop_q"]


def _ep_constraint(x, spec):
    """Pin ``x``'s sharding when the active mesh has a real 'ep' axis, so
    GSPMD materialises the expert all_to_all; no-op otherwise."""
    from ..parallel.mesh import current_mesh
    mesh = current_mesh()
    if mesh is None or mesh.axes.get("ep", 1) <= 1:
        return x
    if x.shape[0] % mesh.axes["ep"] != 0:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh.mesh, P(*spec)))


def top_k_gating(probs, top_k, capacity):
    """GShard-style gating. probs: [T, E] router softmax.

    Returns (combine [T, E, C] float, dispatch [T, E, C] bool, aux):
    combine carries the (renormalised) gate weight of token t in expert
    e's capacity slot c; tokens past an expert's capacity are dropped
    (their combine row is zero — the residual stream carries them, as in
    Switch). aux is the Switch load-balancing loss E * sum_e(f_e * P_e).
    """
    t, e = probs.shape
    gates, idx = jax.lax.top_k(probs, top_k)               # [T, K]
    gates = gates / jnp.maximum(
        jnp.sum(gates, axis=-1, keepdims=True), 1e-9)

    combine = jnp.zeros((t, e, capacity), dtype=probs.dtype)
    counts = jnp.zeros((e,), dtype=jnp.int32)
    for k in range(top_k):
        onehot = jax.nn.one_hot(idx[:, k], e, dtype=jnp.int32)   # [T, E]
        # position of each token within its chosen expert's queue,
        # offset by tokens already enqueued by earlier k-slots
        pos = jnp.cumsum(onehot, axis=0) - 1 + counts[None, :]
        pos_k = jnp.sum(pos * onehot, axis=-1)                   # [T]
        counts = counts + jnp.sum(onehot, axis=0)
        fits = (pos_k < capacity).astype(probs.dtype) * gates[:, k]
        slot = jax.nn.one_hot(pos_k, capacity, dtype=probs.dtype)
        combine = combine + (fits[:, None, None]
                             * onehot.astype(probs.dtype)[:, :, None]
                             * slot[:, None, :])
    dispatch = combine > 0

    # Switch aux loss on the top-1 assignment: mean prob vs dispatch freq
    top1 = jax.nn.one_hot(idx[:, 0], e, dtype=probs.dtype)
    aux = e * jnp.sum(jnp.mean(probs, axis=0) * jnp.mean(top1, axis=0))
    return combine, dispatch, aux


def _router_probs(xt, wg):
    """Router in f32 for stable softmax/top-k regardless of dtype."""
    logits = jnp.dot(xt.astype(jnp.float32), wg.astype(jnp.float32))
    return jax.nn.softmax(logits, axis=-1)


def moe_apply(xt, wg, w_gate, w_up, w_down, top_k, cap_factor):
    """Training-form MoE on flat tokens xt [T, D]: GShard top-k gating
    with static capacity (tokens past capacity fall back to the
    residual stream). Returns (out [T, D], aux scalar)."""
    t = xt.shape[0]
    e = w_up.shape[0]
    capacity = max(1, int(cap_factor * t * top_k / e))
    probs = _router_probs(xt, wg)
    combine, dispatch, aux = top_k_gating(probs, top_k, capacity)
    cdt = xt.dtype
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(cdt), xt)
    expert_in = _ep_constraint(expert_in, ("ep", None, None))
    gate_h = jnp.einsum("ecd,edh->ech", expert_in, w_gate)
    up_h = jnp.einsum("ecd,edh->ech", expert_in, w_up)
    h = (gate_h * jax.nn.sigmoid(gate_h)) * up_h
    expert_out = jnp.einsum("ech,ehd->ecd", h, w_down)
    expert_out = _ep_constraint(expert_out, ("ep", None, None))
    out = jnp.einsum("tec,ecd->td", combine.astype(cdt), expert_out)
    return out, aux


def moe_apply_no_drop(xt, wg, w_gate, w_up, w_down, top_k):
    """Inference-form MoE: exact top-k routing with NO capacity drops.
    Training capacity makes a token's output depend on which OTHER
    tokens competed for its experts — under KV-cache decoding that
    would make cached and recomputed logits diverge, so eval/serving
    uses the drop-free form (every expert evaluates every token, the
    combine mask keeps its top-k — E x FLOPs, the standard small-batch
    serving trade)."""
    w = _topk_combine(_router_probs(xt, wg), top_k)          # [T, E]
    cdt = xt.dtype
    gate_h = jnp.einsum("td,edh->teh", xt, w_gate)
    up_h = jnp.einsum("td,edh->teh", xt, w_up)
    h = (gate_h * jax.nn.sigmoid(gate_h)) * up_h
    expert_out = jnp.einsum("teh,ehd->ted", h, w_down)
    return jnp.einsum("te,ted->td", w.astype(cdt), expert_out)


def _topk_combine(probs, top_k):
    """Dense [T, E] combine weights of exact top-k routing (renormed
    gates scattered to their experts) — the ONE copy of the routing
    semantics shared by the float and W8A8 drop-free paths."""
    e = probs.shape[-1]
    gates, idx = jax.lax.top_k(probs, top_k)                 # [T, K]
    gates = gates / jnp.maximum(
        jnp.sum(gates, axis=-1, keepdims=True), 1e-9)
    w = jnp.zeros_like(probs)                                # [T, E]
    for k in range(top_k):
        w = w + gates[:, k:k + 1] * jax.nn.one_hot(
            idx[:, k], e, dtype=probs.dtype)
    return w


def _act_quant(x):
    """Per-row dynamic activation quantization (absmax over the
    contracted axis): int8 values + float scale, the A half of W8A8."""
    xf = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1, keepdims=True),
                    1e-8) / 127.0
    return jnp.round(xf / s).astype(jnp.int8), s


def moe_apply_no_drop_q(xt, wg, w_gate, w_up, w_down, scales, top_k):
    """W8A8 drop-free MoE serving: same routing/combine as
    :func:`moe_apply_no_drop` (the ROUTER stays float — it is tiny and
    its softmax ranking is precision-sensitive), but the three expert
    matmul stacks run natively int8 x int8 -> int32 on the MXU with
    dynamic per-row activation quantization — the same native path as
    the dense qmat (transformer_ops.py): TPU XLA does not fuse a
    convert into a dot operand, so dequantize-then-matmul would
    materialize full float copies of every expert weight per step.

    w_gate/w_up: int8 [E, D, H]; w_down: int8 [E, H, D];
    scales: {"gate": [E,1,H], "up": [E,1,H], "down": [E,1,D]} float.
    """
    probs = _router_probs(xt, wg)
    e = probs.shape[-1]
    w = _topk_combine(probs, top_k)                          # [T, E]
    cdt = xt.dtype
    xq, xs = _act_quant(xt)                        # [T,D] i8, [T,1] f32
    sg = scales["gate"].reshape(1, e, -1)                    # [1,E,H]
    su = scales["up"].reshape(1, e, -1)
    sd = scales["down"].reshape(1, e, -1)                    # [1,E,D]
    g32 = jnp.einsum("td,edh->teh", xq, w_gate,
                     preferred_element_type=jnp.int32)
    u32 = jnp.einsum("td,edh->teh", xq, w_up,
                     preferred_element_type=jnp.int32)
    gate_h = g32.astype(jnp.float32) * xs[:, :, None] * sg
    up_h = u32.astype(jnp.float32) * xs[:, :, None] * su
    h = (gate_h * jax.nn.sigmoid(gate_h)) * up_h             # [T,E,H]
    hq, hs = _act_quant(h)                                   # [T,E,1]
    d32 = jnp.einsum("teh,ehd->ted", hq, w_down,
                     preferred_element_type=jnp.int32)
    expert_out = d32.astype(jnp.float32) * hs * sd           # [T,E,D]
    return jnp.einsum("te,ted->td", w.astype(jnp.float32),
                      expert_out).astype(cdt)


@register_op("moe_ffn")
def _moe_ffn(ctx, ins, attrs):
    """X [B,S,D]; GateW [D,E]; W_up/W_gate [E,D,H]; W_down [E,H,D].

    SwiGLU experts: down(silu(gate(x)) * up(x)), matching the dense
    Llama FFN so a dense layer can be swapped for an MoE one 1:1.
    Outputs: Out [B,S,D], AuxLoss [] (scalar, pre-weighted by caller).
    Test mode routes drop-free (see moe_apply_no_drop).
    """
    x = ins["X"][0]
    wg = ins["GateW"][0]
    w_up, w_gate, w_down = ins["WUp"][0], ins["WGate"][0], ins["WDown"][0]
    top_k = int(attrs.get("top_k", 2))
    cap_factor = float(attrs.get("capacity_factor", 2.0))
    e = w_up.shape[0]
    b, s, d = x.shape
    # the ep sharding P('ep', ...) splits the EXPERT axis of [E, C, ...]
    # — E must divide evenly or experts silently replicate
    from ..parallel.mesh import current_mesh
    mesh = current_mesh()
    if mesh is not None and mesh.axes.get("ep", 1) > 1:
        ep = mesh.axes["ep"]
        if e % ep != 0:
            raise ValueError(
                f"moe_ffn: num_experts={e} is not divisible by the mesh "
                f"'ep' axis size {ep}; expert weights cannot shard — "
                "resize the mesh or the expert count")

    xt = x.reshape(b * s, d)
    if ctx.is_test:
        out = moe_apply_no_drop(xt, wg, w_gate, w_up, w_down, top_k)
        aux = jnp.float32(0.0)
    else:
        out, aux = moe_apply(xt, wg, w_gate, w_up, w_down, top_k,
                             cap_factor)
    return {"Out": [out.reshape(b, s, d)],
            "AuxLoss": [aux.astype(jnp.float32)]}
