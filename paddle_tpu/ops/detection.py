"""Detection op lowerings (SSD family).

Capability parity with paddle/fluid/operators/detection/:
  iou_similarity_op.h        — pairwise IoU
  box_coder_op.h             — center-size encode/decode with variances
  prior_box_op.h             — SSD prior boxes per feature-map cell
  bipartite_match_op.cc      — greedy bipartite (argmax) matching
  target_assign_op.h         — scatter matched targets per prior
  multiclass_nms_op.cc       — per-class NMS + cross-class top-k

The reference runs these on the host CPU with dynamic-size outputs
(LoD). TPU-native form: every op is dense and fixed-shape — NMS keeps
`keep_top_k` slots and marks empties with label -1, matching runs as a
`lax.scan` of argmax picks — so the whole detection head stays inside
one XLA program.
"""
import jax
import jax.numpy as jnp

from ..core.registry import register_op

NEG_INF = -1e30


def _iou_matrix(a, b, normalized=True):
    """a [M,4], b [N,4] in (xmin, ymin, xmax, ymax) -> [M,N] IoU.
    ``normalized=False`` applies the reference's +1 pixel-coordinate
    width/height correction."""
    off = 0.0 if normalized else 1.0
    area_a = jnp.maximum(a[:, 2] - a[:, 0] + off, 0) * \
        jnp.maximum(a[:, 3] - a[:, 1] + off, 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0] + off, 0) * \
        jnp.maximum(b[:, 3] - b[:, 1] + off, 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt + off, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register_op("iou_similarity")
def _iou_similarity(ctx, ins, attrs):
    x = ins["X"][0]
    y = ins["Y"][0]
    if x.ndim == 3 and y.ndim == 3:
        out = jax.vmap(_iou_matrix)(x, y)
    elif x.ndim == 3:
        out = jax.vmap(_iou_matrix, in_axes=(0, None))(x, y)
    elif y.ndim == 3:
        out = jax.vmap(_iou_matrix, in_axes=(None, 0))(x, y)
    else:
        out = _iou_matrix(x, y)
    return {"Out": [out]}


def _encode_center_size(target, prior, var):
    """target/prior [*, 4] corner boxes -> offsets (reference box_coder
    encode_center_size)."""
    pw = prior[..., 2] - prior[..., 0]
    ph = prior[..., 3] - prior[..., 1]
    pcx = (prior[..., 0] + prior[..., 2]) / 2
    pcy = (prior[..., 1] + prior[..., 3]) / 2
    tw = target[..., 2] - target[..., 0]
    th = target[..., 3] - target[..., 1]
    tcx = (target[..., 0] + target[..., 2]) / 2
    tcy = (target[..., 1] + target[..., 3]) / 2
    out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                     jnp.log(jnp.maximum(tw / pw, 1e-10)),
                     jnp.log(jnp.maximum(th / ph, 1e-10))], axis=-1)
    return out / var


def _decode_center_size(code, prior, var):
    pw = prior[..., 2] - prior[..., 0]
    ph = prior[..., 3] - prior[..., 1]
    pcx = (prior[..., 0] + prior[..., 2]) / 2
    pcy = (prior[..., 1] + prior[..., 3]) / 2
    c = code * var
    cx = c[..., 0] * pw + pcx
    cy = c[..., 1] * ph + pcy
    w = jnp.exp(c[..., 2]) * pw
    h = jnp.exp(c[..., 3]) * ph
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


@register_op("box_coder")
def _box_coder(ctx, ins, attrs):
    prior = ins["PriorBox"][0]                       # [M, 4]
    var = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else \
        jnp.ones_like(prior)
    target = ins["TargetBox"][0]
    code_type = attrs.get("code_type", "encode_center_size")
    if code_type.lower().endswith("encode_center_size"):
        out = _encode_center_size(target, prior, var)
    else:
        # decode: target codes may be [B, M, 4] against [M, 4] priors
        out = _decode_center_size(target, prior, var)
    return {"OutputBox": [out]}


@register_op("prior_box")
def _prior_box(ctx, ins, attrs):
    """SSD priors for one feature map (reference prior_box_op.h): for
    every cell, boxes at each (min_size, aspect_ratio) plus the
    sqrt(min*max) box."""
    feat = ins["Input"][0]                           # [B, C, H, W]
    image = ins["Image"][0]                          # [B, C, IH, IW]
    h, w = feat.shape[2], feat.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    ars = [1.0]
    for ar in attrs.get("aspect_ratios", [1.0]):
        ar = float(ar)
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
            if attrs.get("flip", False):
                ars.append(1.0 / ar)
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    step_w = attrs.get("step_w", 0.0) or img_w / w
    step_h = attrs.get("step_h", 0.0) or img_h / h
    offset = attrs.get("offset", 0.5)
    clip = attrs.get("clip", False)

    # box widths/heights per prior kind (static python); ordering
    # follows the reference's min_max_aspect_ratios_order switch so conv
    # head channels pair with the same priors
    min_max_order = attrs.get("min_max_aspect_ratios_order", False)
    whs = []
    for k, ms in enumerate(min_sizes):
        whs.append((ms, ms))
        ar_boxes = [(ms * (ar ** 0.5), ms / (ar ** 0.5))
                    for ar in ars if abs(ar - 1.0) > 1e-6]
        max_boxes = []
        if max_sizes:
            big = (ms * max_sizes[k]) ** 0.5
            max_boxes.append((big, big))
        if min_max_order:
            whs.extend(max_boxes + ar_boxes)
        else:
            whs.extend(ar_boxes + max_boxes)
    whs = jnp.asarray(whs, jnp.float32)              # [P, 2]

    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)                  # [H, W]
    centers = jnp.stack([cxg, cyg], axis=-1)         # [H, W, 2]
    half = whs / 2                                   # [P, 2]
    mins = (centers[:, :, None, :] - half[None, None]) / \
        jnp.asarray([img_w, img_h], jnp.float32)
    maxs = (centers[:, :, None, :] + half[None, None]) / \
        jnp.asarray([img_w, img_h], jnp.float32)
    boxes = jnp.concatenate([mins, maxs], axis=-1)   # [H, W, P, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    boxes = boxes.reshape(-1, 4)
    var = jnp.tile(jnp.asarray(variances, jnp.float32)[None],
                   (boxes.shape[0], 1))
    return {"Boxes": [boxes], "Variances": [var]}


def _bipartite_match_single(dist):
    """Greedy argmax matching (reference bipartite_match_op.cc): pick the
    globally best (row, col) pair, retire both, repeat. dist [M, N]
    (M ground-truths, N priors). Returns (col->row match [N],
    col match dist [N]); unmatched cols get -1."""
    M, N = dist.shape

    def step(state, _):
        d, row_free, col_match, col_dist = state
        masked = jnp.where(row_free[:, None], d, NEG_INF)
        flat = jnp.argmax(masked)
        r, c = flat // N, flat % N
        best = masked[r, c]
        ok = best > NEG_INF / 2
        col_match = jnp.where(ok, col_match.at[c].set(r), col_match)
        col_dist = jnp.where(ok, col_dist.at[c].set(best), col_dist)
        row_free = jnp.where(ok, row_free.at[r].set(False), row_free)
        d = jnp.where(ok, d.at[:, c].set(NEG_INF), d)
        return (d, row_free, col_match, col_dist), None

    init = (dist, jnp.ones((M,), bool),
            jnp.full((N,), -1, jnp.int32), jnp.zeros((N,), dist.dtype))
    (d, row_free, col_match, col_dist), _ = jax.lax.scan(
        step, init, None, length=min(M, N))
    return col_match, col_dist


@register_op("bipartite_match")
def _bipartite_match(ctx, ins, attrs):
    dist = ins["DistMat"][0]
    match_type = attrs.get("match_type", "bipartite")
    overlap_threshold = attrs.get("dist_threshold", 0.5)
    if dist.ndim == 2:
        dist = dist[None]
    col_match, col_dist = jax.vmap(_bipartite_match_single)(dist)
    if match_type == "per_prediction":
        # additionally match any unmatched prior to its best row if the
        # distance clears the threshold
        best_row = jnp.argmax(dist, axis=1).astype(jnp.int32)   # [B, N]
        best_val = jnp.max(dist, axis=1)
        extra = (col_match < 0) & (best_val >= overlap_threshold)
        col_match = jnp.where(extra, best_row, col_match)
        col_dist = jnp.where(extra, best_val, col_dist)
    return {"ColToRowMatchIndices": [col_match],
            "ColToRowMatchDist": [col_dist]}


@register_op("target_assign")
def _target_assign(ctx, ins, attrs):
    """Gather per-prior targets by match index (reference
    target_assign_op.h). X [B, M, K] per-gt targets, MatchIndices
    [B, N] (col->gt row or -1). Out [B, N, K]; OutWeight [B, N, 1]
    zero for unmatched (mismatch_value fills the target)."""
    x = ins["X"][0]
    match = ins["MatchIndices"][0]
    mismatch_value = attrs.get("mismatch_value", 0)
    idx = jnp.maximum(match, 0)
    gathered = jnp.take_along_axis(
        x, idx[..., None].astype(jnp.int32), axis=1)
    matched = (match >= 0)[..., None]
    out = jnp.where(matched, gathered,
                    jnp.full_like(gathered, mismatch_value))
    weight = matched.astype(x.dtype)
    if ins.get("NegIndices"):
        # mined negatives get weight 1 with the mismatch (background)
        # target, so they contribute to the confidence loss (reference
        # target_assign_op.h NegIndices path). Dense [B, Nn], -1 pads.
        neg = ins["NegIndices"][0]
        if hasattr(neg, "data"):          # SequenceBatch
            neg_idx, neg_lens = neg.data, neg.lengths
            pos_valid = jnp.arange(neg_idx.shape[1])[None, :] < \
                neg_lens[:, None]
        else:
            neg_idx = neg
            pos_valid = neg_idx >= 0
        if neg_idx.ndim == 3:
            neg_idx = neg_idx[..., 0]
            pos_valid = pos_valid if pos_valid.ndim == 2 else pos_valid[..., 0]
        neg_idx = neg_idx.astype(jnp.int32)
        n = weight.shape[1]
        dump = jnp.full_like(neg_idx, n)
        safe = jnp.where(pos_valid & (neg_idx >= 0), neg_idx, dump)

        def mark(w_row, idx_row):
            return w_row.at[idx_row].max(1.0, mode="drop")

        w2 = jax.vmap(mark)(weight[..., 0], safe)
        weight = w2[..., None]
    return {"Out": [out], "OutWeight": [weight]}


def _nms_single(boxes, scores, score_threshold, nms_threshold, nms_top_k,
                keep_top_k, normalized=True, eta=1.0):
    """Per-class NMS over one image, fixed shapes. boxes [N,4], scores
    [C, N]. Returns (labels [keep_top_k], kept_scores, kept_boxes) with
    label -1 in empty slots."""
    C, N = scores.shape
    top = min(nms_top_k if nms_top_k > 0 else N, N)

    def one_class(cls_scores):
        s, order = jax.lax.top_k(cls_scores, top)
        b = boxes[order]
        iou = _iou_matrix(b, b, normalized=normalized)

        def suppress(carry, i):
            keep, thr = carry
            sup = (iou[i] > thr) & keep & \
                (jnp.arange(top) > i) & keep[i]
            # reference NMSFast: adaptive threshold decays by eta while
            # above 0.5 after every survivor considered
            thr = jnp.where((eta < 1.0) & (thr > 0.5) & keep[i],
                            thr * eta, thr)
            return (keep & ~sup, thr), None

        keep0 = s > score_threshold
        (keep, _), _ = jax.lax.scan(
            suppress, (keep0, jnp.asarray(nms_threshold, s.dtype)),
            jnp.arange(top))
        return jnp.where(keep, s, NEG_INF), order

    cls_scores, cls_order = jax.vmap(one_class)(scores)   # [C, top]
    flat = cls_scores.reshape(-1)
    k = min(keep_top_k if keep_top_k > 0 else flat.shape[0], flat.shape[0])
    best, best_idx = jax.lax.top_k(flat, k)
    labels = (best_idx // top).astype(jnp.int32)
    within = best_idx % top
    box_idx = cls_order[labels, within]
    kept_boxes = boxes[box_idx]
    valid = best > NEG_INF / 2
    labels = jnp.where(valid, labels, -1)
    best = jnp.where(valid, best, 0.0)
    kept_boxes = jnp.where(valid[:, None], kept_boxes, 0.0)
    return labels, best, kept_boxes


@register_op("multiclass_nms")
def _multiclass_nms(ctx, ins, attrs):
    boxes = ins["BBoxes"][0]                         # [B, N, 4]
    scores = ins["Scores"][0]                        # [B, C, N]
    background_label = attrs.get("background_label", 0)
    score_threshold = attrs.get("score_threshold", 0.0)
    nms_top_k = attrs.get("nms_top_k", -1)
    nms_threshold = attrs.get("nms_threshold", 0.3)
    keep_top_k = attrs.get("keep_top_k", -1)
    normalized = attrs.get("normalized", True)
    nms_eta = attrs.get("nms_eta", 1.0)
    if background_label >= 0:
        scores = scores.at[:, background_label].set(NEG_INF)
    labels, kept_scores, kept_boxes = jax.vmap(
        lambda b, s: _nms_single(b, s, score_threshold, nms_threshold,
                                 nms_top_k, keep_top_k,
                                 normalized=normalized,
                                 eta=nms_eta))(boxes, scores)
    # reference emits LoD [label, score, x1, y1, x2, y2]; dense form:
    out = jnp.concatenate([labels[..., None].astype(kept_scores.dtype),
                           kept_scores[..., None], kept_boxes], axis=-1)
    return {"Out": [out]}


@register_op("polygon_box_transform")
def _polygon_box_transform(ctx, ins, attrs):
    """(reference polygon_box_transform_op.cc): input [B, 2K, H, W] of
    offsets; even channels get x-coords added, odd channels y."""
    x = ins["Input"][0]
    B, C, H, W = x.shape
    xs = jnp.tile(jnp.arange(W, dtype=x.dtype)[None, :], (H, 1))
    ys = jnp.tile(jnp.arange(H, dtype=x.dtype)[:, None], (1, W))
    grid = jnp.stack([xs, ys])                       # [2, H, W]
    grid_full = jnp.tile(grid, (C // 2, 1, 1))       # [C, H, W]
    return {"Output": [grid_full[None] * 4 - x]}


def _smooth_l1(x):
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0, 0.5 * x * x, ax - 0.5)


@register_op("ssd_loss", seq_aware=True)
def _ssd_loss(ctx, ins, attrs):
    """Fused SSD multibox loss — the reference composes iou_similarity →
    bipartite_match → mine_hard_examples → target_assign → smooth_l1 +
    softmax_with_cross_entropy (detection.py ssd_loss); here it is one
    masked dense computation per image, vmapped over the batch."""
    loc = ins["Location"][0]                         # [B, Np, 4]
    conf = ins["Confidence"][0]                      # [B, Np, C]
    gt_box = ins["GTBox"][0]                         # SequenceBatch
    gt_label = ins["GTLabel"][0]
    prior = ins["PriorBox"][0]                       # [Np, 4]
    var = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else \
        jnp.ones_like(prior)
    background = attrs.get("background_label", 0)
    overlap_threshold = attrs.get("overlap_threshold", 0.5)
    neg_pos_ratio = attrs.get("neg_pos_ratio", 3.0)
    neg_overlap = attrs.get("neg_overlap", 0.5)
    loc_w = attrs.get("loc_loss_weight", 1.0)
    conf_w = attrs.get("conf_loss_weight", 1.0)
    match_type = attrs.get("match_type", "per_prediction")
    normalize = attrs.get("normalize", True)

    gt_boxes, gt_lens = gt_box.data, gt_box.lengths
    labels = gt_label.data
    if labels.ndim == 3:
        labels = labels[..., 0]
    labels = labels.astype(jnp.int32)

    def one(loc_i, conf_i, gtb, gtl, glen):
        G = gtb.shape[0]
        Np = prior.shape[0]
        valid_gt = jnp.arange(G) < glen
        iou = _iou_matrix(gtb, prior)
        dist = jnp.where(valid_gt[:, None], iou, NEG_INF)
        col_match, col_dist = _bipartite_match_single(dist)
        if match_type == "per_prediction":
            best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
            best_val = jnp.max(dist, axis=0)
            extra = (col_match < 0) & (best_val >= overlap_threshold)
            col_match = jnp.where(extra, best_row, col_match)
            col_dist = jnp.where(extra, best_val, col_dist)
        matched = col_match >= 0
        safe_idx = jnp.maximum(col_match, 0)

        # confidence loss on every prior (target = matched gt label or bg)
        tgt_label = jnp.where(matched, gtl[safe_idx], background)
        logp = jax.nn.log_softmax(conf_i)
        conf_loss_all = -jnp.take_along_axis(
            logp, tgt_label[:, None], axis=1)[:, 0]

        # max-negative mining: hardest unmatched priors, ratio-capped
        num_pos = matched.sum()
        neg_cand = (~matched) & (col_dist < neg_overlap)
        neg_score = jnp.where(neg_cand, conf_loss_all, NEG_INF)
        rank = jnp.argsort(jnp.argsort(-neg_score))
        num_neg = jnp.minimum((neg_pos_ratio * num_pos).astype(jnp.int32),
                              neg_cand.sum())
        selected_neg = neg_cand & (rank < num_neg)
        conf_loss = conf_loss_all * (matched | selected_neg)

        # localization loss on positives only
        enc = _encode_center_size(gtb[safe_idx], prior, var)
        loc_loss = _smooth_l1(loc_i - enc).sum(-1) * matched

        total = conf_w * conf_loss + loc_w * loc_loss
        if normalize:
            total = total / jnp.maximum(num_pos, 1).astype(total.dtype)
        return total[:, None]

    out = jax.vmap(one)(loc, conf, gt_boxes, labels, gt_lens)
    return {"Loss": [out]}
