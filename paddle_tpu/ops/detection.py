"""Detection op lowerings (SSD family).

Capability parity with paddle/fluid/operators/detection/:
  iou_similarity_op.h        — pairwise IoU
  box_coder_op.h             — center-size encode/decode with variances
  prior_box_op.h             — SSD prior boxes per feature-map cell
  bipartite_match_op.cc      — greedy bipartite (argmax) matching
  target_assign_op.h         — scatter matched targets per prior
  multiclass_nms_op.cc       — per-class NMS + cross-class top-k

The reference runs these on the host CPU with dynamic-size outputs
(LoD). TPU-native form: every op is dense and fixed-shape — NMS keeps
`keep_top_k` slots and marks empties with label -1, matching runs as a
`lax.scan` of argmax picks — so the whole detection head stays inside
one XLA program.
"""
import jax
import jax.numpy as jnp

from ..core.registry import canonical_int, register_op

NEG_INF = -1e30


def _iou_matrix(a, b, normalized=True):
    """a [M,4], b [N,4] in (xmin, ymin, xmax, ymax) -> [M,N] IoU.
    ``normalized=False`` applies the reference's +1 pixel-coordinate
    width/height correction."""
    off = 0.0 if normalized else 1.0
    area_a = jnp.maximum(a[:, 2] - a[:, 0] + off, 0) * \
        jnp.maximum(a[:, 3] - a[:, 1] + off, 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0] + off, 0) * \
        jnp.maximum(b[:, 3] - b[:, 1] + off, 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt + off, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register_op("iou_similarity")
def _iou_similarity(ctx, ins, attrs):
    x = ins["X"][0]
    y = ins["Y"][0]
    if x.ndim == 3 and y.ndim == 3:
        out = jax.vmap(_iou_matrix)(x, y)
    elif x.ndim == 3:
        out = jax.vmap(_iou_matrix, in_axes=(0, None))(x, y)
    elif y.ndim == 3:
        out = jax.vmap(_iou_matrix, in_axes=(None, 0))(x, y)
    else:
        out = _iou_matrix(x, y)
    return {"Out": [out]}


def _encode_center_size(target, prior, var):
    """target/prior [*, 4] corner boxes -> offsets (reference box_coder
    encode_center_size)."""
    pw = prior[..., 2] - prior[..., 0]
    ph = prior[..., 3] - prior[..., 1]
    pcx = (prior[..., 0] + prior[..., 2]) / 2
    pcy = (prior[..., 1] + prior[..., 3]) / 2
    tw = target[..., 2] - target[..., 0]
    th = target[..., 3] - target[..., 1]
    tcx = (target[..., 0] + target[..., 2]) / 2
    tcy = (target[..., 1] + target[..., 3]) / 2
    out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                     jnp.log(jnp.maximum(tw / pw, 1e-10)),
                     jnp.log(jnp.maximum(th / ph, 1e-10))], axis=-1)
    return out / var


def _decode_center_size(code, prior, var):
    pw = prior[..., 2] - prior[..., 0]
    ph = prior[..., 3] - prior[..., 1]
    pcx = (prior[..., 0] + prior[..., 2]) / 2
    pcy = (prior[..., 1] + prior[..., 3]) / 2
    c = code * var
    cx = c[..., 0] * pw + pcx
    cy = c[..., 1] * ph + pcy
    w = jnp.exp(c[..., 2]) * pw
    h = jnp.exp(c[..., 3]) * ph
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


@register_op("box_coder")
def _box_coder(ctx, ins, attrs):
    prior = ins["PriorBox"][0]                       # [M, 4]
    var = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else \
        jnp.ones_like(prior)
    target = ins["TargetBox"][0]
    code_type = attrs.get("code_type", "encode_center_size")
    if code_type.lower().endswith("encode_center_size"):
        out = _encode_center_size(target, prior, var)
    else:
        # decode: target codes may be [B, M, 4] against [M, 4] priors
        out = _decode_center_size(target, prior, var)
    return {"OutputBox": [out]}


@register_op("prior_box")
def _prior_box(ctx, ins, attrs):
    """SSD priors for one feature map (reference prior_box_op.h): for
    every cell, boxes at each (min_size, aspect_ratio) plus the
    sqrt(min*max) box."""
    feat = ins["Input"][0]                           # [B, C, H, W]
    image = ins["Image"][0]                          # [B, C, IH, IW]
    h, w = feat.shape[2], feat.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    ars = [1.0]
    for ar in attrs.get("aspect_ratios", [1.0]):
        ar = float(ar)
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
            if attrs.get("flip", False):
                ars.append(1.0 / ar)
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    step_w = attrs.get("step_w", 0.0) or img_w / w
    step_h = attrs.get("step_h", 0.0) or img_h / h
    offset = attrs.get("offset", 0.5)
    clip = attrs.get("clip", False)

    # box widths/heights per prior kind (static python); ordering
    # follows the reference's min_max_aspect_ratios_order switch so conv
    # head channels pair with the same priors
    min_max_order = attrs.get("min_max_aspect_ratios_order", False)
    whs = []
    for k, ms in enumerate(min_sizes):
        whs.append((ms, ms))
        ar_boxes = [(ms * (ar ** 0.5), ms / (ar ** 0.5))
                    for ar in ars if abs(ar - 1.0) > 1e-6]
        max_boxes = []
        if max_sizes:
            big = (ms * max_sizes[k]) ** 0.5
            max_boxes.append((big, big))
        if min_max_order:
            whs.extend(max_boxes + ar_boxes)
        else:
            whs.extend(ar_boxes + max_boxes)
    whs = jnp.asarray(whs, jnp.float32)              # [P, 2]

    cx = (jnp.arange(w, dtype=jnp.float32) + offset) * step_w
    cy = (jnp.arange(h, dtype=jnp.float32) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy)                  # [H, W]
    centers = jnp.stack([cxg, cyg], axis=-1)         # [H, W, 2]
    half = whs / 2                                   # [P, 2]
    mins = (centers[:, :, None, :] - half[None, None]) / \
        jnp.asarray([img_w, img_h], jnp.float32)
    maxs = (centers[:, :, None, :] + half[None, None]) / \
        jnp.asarray([img_w, img_h], jnp.float32)
    boxes = jnp.concatenate([mins, maxs], axis=-1)   # [H, W, P, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    boxes = boxes.reshape(-1, 4)
    var = jnp.tile(jnp.asarray(variances, jnp.float32)[None],
                   (boxes.shape[0], 1))
    return {"Boxes": [boxes], "Variances": [var]}


def _bipartite_match_single(dist):
    """Greedy argmax matching (reference bipartite_match_op.cc): pick the
    globally best (row, col) pair, retire both, repeat. dist [M, N]
    (M ground-truths, N priors). Returns (col->row match [N],
    col match dist [N]); unmatched cols get -1."""
    M, N = dist.shape

    def step(state, _):
        d, row_free, col_match, col_dist = state
        masked = jnp.where(row_free[:, None], d, NEG_INF)
        flat = jnp.argmax(masked)
        r, c = flat // N, flat % N
        best = masked[r, c]
        ok = best > NEG_INF / 2
        col_match = jnp.where(ok, col_match.at[c].set(r), col_match)
        col_dist = jnp.where(ok, col_dist.at[c].set(best), col_dist)
        row_free = jnp.where(ok, row_free.at[r].set(False), row_free)
        d = jnp.where(ok, d.at[:, c].set(NEG_INF), d)
        return (d, row_free, col_match, col_dist), None

    init = (dist, jnp.ones((M,), bool),
            jnp.full((N,), -1, jnp.int32), jnp.zeros((N,), dist.dtype))
    (d, row_free, col_match, col_dist), _ = jax.lax.scan(
        step, init, None, length=min(M, N))
    return col_match, col_dist


@register_op("bipartite_match")
def _bipartite_match(ctx, ins, attrs):
    dist = ins["DistMat"][0]
    match_type = attrs.get("match_type", "bipartite")
    overlap_threshold = attrs.get("dist_threshold", 0.5)
    if dist.ndim == 2:
        dist = dist[None]
    col_match, col_dist = jax.vmap(_bipartite_match_single)(dist)
    if match_type == "per_prediction":
        # additionally match any unmatched prior to its best row if the
        # distance clears the threshold
        best_row = jnp.argmax(dist, axis=1).astype(jnp.int32)   # [B, N]
        best_val = jnp.max(dist, axis=1)
        extra = (col_match < 0) & (best_val >= overlap_threshold)
        col_match = jnp.where(extra, best_row, col_match)
        col_dist = jnp.where(extra, best_val, col_dist)
    return {"ColToRowMatchIndices": [col_match],
            "ColToRowMatchDist": [col_dist]}


@register_op("target_assign")
def _target_assign(ctx, ins, attrs):
    """Gather per-prior targets by match index (reference
    target_assign_op.h). X [B, M, K] per-gt targets, MatchIndices
    [B, N] (col->gt row or -1). Out [B, N, K]; OutWeight [B, N, 1]
    zero for unmatched (mismatch_value fills the target)."""
    x = ins["X"][0]
    match = ins["MatchIndices"][0]
    mismatch_value = attrs.get("mismatch_value", 0)
    idx = jnp.maximum(match, 0)
    gathered = jnp.take_along_axis(
        x, idx[..., None].astype(jnp.int32), axis=1)
    matched = (match >= 0)[..., None]
    out = jnp.where(matched, gathered,
                    jnp.full_like(gathered, mismatch_value))
    weight = matched.astype(x.dtype)
    if ins.get("NegIndices"):
        # mined negatives get weight 1 with the mismatch (background)
        # target, so they contribute to the confidence loss (reference
        # target_assign_op.h NegIndices path). Dense [B, Nn], -1 pads.
        neg = ins["NegIndices"][0]
        if hasattr(neg, "data"):          # SequenceBatch
            neg_idx, neg_lens = neg.data, neg.lengths
            pos_valid = jnp.arange(neg_idx.shape[1])[None, :] < \
                neg_lens[:, None]
        else:
            neg_idx = neg
            pos_valid = neg_idx >= 0
        if neg_idx.ndim == 3:
            neg_idx = neg_idx[..., 0]
            pos_valid = pos_valid if pos_valid.ndim == 2 else pos_valid[..., 0]
        neg_idx = neg_idx.astype(jnp.int32)
        n = weight.shape[1]
        dump = jnp.full_like(neg_idx, n)
        safe = jnp.where(pos_valid & (neg_idx >= 0), neg_idx, dump)

        def mark(w_row, idx_row):
            return w_row.at[idx_row].max(1.0, mode="drop")

        w2 = jax.vmap(mark)(weight[..., 0], safe)
        weight = w2[..., None]
    return {"Out": [out], "OutWeight": [weight]}


def _nms_single(boxes, scores, score_threshold, nms_threshold, nms_top_k,
                keep_top_k, normalized=True, eta=1.0):
    """Per-class NMS over one image, fixed shapes. boxes [N,4], scores
    [C, N]. Returns (labels [keep_top_k], kept_scores, kept_boxes) with
    label -1 in empty slots."""
    C, N = scores.shape
    top = min(nms_top_k if nms_top_k > 0 else N, N)

    def one_class(cls_scores):
        s, order = jax.lax.top_k(cls_scores, top)
        b = boxes[order]
        iou = _iou_matrix(b, b, normalized=normalized)

        def suppress(carry, i):
            keep, thr = carry
            sup = (iou[i] > thr) & keep & \
                (jnp.arange(top) > i) & keep[i]
            # reference NMSFast: adaptive threshold decays by eta while
            # above 0.5 after every survivor considered
            thr = jnp.where((eta < 1.0) & (thr > 0.5) & keep[i],
                            thr * eta, thr)
            return (keep & ~sup, thr), None

        keep0 = s > score_threshold
        (keep, _), _ = jax.lax.scan(
            suppress, (keep0, jnp.asarray(nms_threshold, s.dtype)),
            jnp.arange(top))
        return jnp.where(keep, s, NEG_INF), order

    cls_scores, cls_order = jax.vmap(one_class)(scores)   # [C, top]
    flat = cls_scores.reshape(-1)
    k = min(keep_top_k if keep_top_k > 0 else flat.shape[0], flat.shape[0])
    best, best_idx = jax.lax.top_k(flat, k)
    labels = (best_idx // top).astype(jnp.int32)
    within = best_idx % top
    box_idx = cls_order[labels, within]
    kept_boxes = boxes[box_idx]
    valid = best > NEG_INF / 2
    labels = jnp.where(valid, labels, -1)
    best = jnp.where(valid, best, 0.0)
    kept_boxes = jnp.where(valid[:, None], kept_boxes, 0.0)
    return labels, best, kept_boxes


@register_op("multiclass_nms")
def _multiclass_nms(ctx, ins, attrs):
    boxes = ins["BBoxes"][0]                         # [B, N, 4]
    scores = ins["Scores"][0]                        # [B, C, N]
    background_label = attrs.get("background_label", 0)
    score_threshold = attrs.get("score_threshold", 0.0)
    nms_top_k = attrs.get("nms_top_k", -1)
    nms_threshold = attrs.get("nms_threshold", 0.3)
    keep_top_k = attrs.get("keep_top_k", -1)
    normalized = attrs.get("normalized", True)
    nms_eta = attrs.get("nms_eta", 1.0)
    if background_label >= 0:
        scores = scores.at[:, background_label].set(NEG_INF)
    labels, kept_scores, kept_boxes = jax.vmap(
        lambda b, s: _nms_single(b, s, score_threshold, nms_threshold,
                                 nms_top_k, keep_top_k,
                                 normalized=normalized,
                                 eta=nms_eta))(boxes, scores)
    # reference emits LoD [label, score, x1, y1, x2, y2]; dense form:
    out = jnp.concatenate([labels[..., None].astype(kept_scores.dtype),
                           kept_scores[..., None], kept_boxes], axis=-1)
    return {"Out": [out]}


@register_op("polygon_box_transform")
def _polygon_box_transform(ctx, ins, attrs):
    """(reference polygon_box_transform_op.cc): input [B, 2K, H, W] of
    offsets; even channels get x-coords added, odd channels y."""
    x = ins["Input"][0]
    B, C, H, W = x.shape
    xs = jnp.tile(jnp.arange(W, dtype=x.dtype)[None, :], (H, 1))
    ys = jnp.tile(jnp.arange(H, dtype=x.dtype)[:, None], (1, W))
    grid = jnp.stack([xs, ys])                       # [2, H, W]
    grid_full = jnp.tile(grid, (C // 2, 1, 1))       # [C, H, W]
    return {"Output": [grid_full[None] * 4 - x]}


def _smooth_l1(x):
    ax = jnp.abs(x)
    return jnp.where(ax < 1.0, 0.5 * x * x, ax - 0.5)


@register_op("ssd_loss", seq_aware=True)
def _ssd_loss(ctx, ins, attrs):
    """Fused SSD multibox loss — the reference composes iou_similarity →
    bipartite_match → mine_hard_examples → target_assign → smooth_l1 +
    softmax_with_cross_entropy (detection.py ssd_loss); here it is one
    masked dense computation per image, vmapped over the batch."""
    loc = ins["Location"][0]                         # [B, Np, 4]
    conf = ins["Confidence"][0]                      # [B, Np, C]
    gt_box = ins["GTBox"][0]                         # SequenceBatch
    gt_label = ins["GTLabel"][0]
    prior = ins["PriorBox"][0]                       # [Np, 4]
    var = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else \
        jnp.ones_like(prior)
    background = attrs.get("background_label", 0)
    overlap_threshold = attrs.get("overlap_threshold", 0.5)
    neg_pos_ratio = attrs.get("neg_pos_ratio", 3.0)
    neg_overlap = attrs.get("neg_overlap", 0.5)
    loc_w = attrs.get("loc_loss_weight", 1.0)
    conf_w = attrs.get("conf_loss_weight", 1.0)
    match_type = attrs.get("match_type", "per_prediction")
    normalize = attrs.get("normalize", True)

    gt_boxes, gt_lens = gt_box.data, gt_box.lengths
    labels = gt_label.data
    if labels.ndim == 3:
        labels = labels[..., 0]
    labels = labels.astype(jnp.int32)

    def one(loc_i, conf_i, gtb, gtl, glen):
        G = gtb.shape[0]
        Np = prior.shape[0]
        valid_gt = jnp.arange(G) < glen
        iou = _iou_matrix(gtb, prior)
        dist = jnp.where(valid_gt[:, None], iou, NEG_INF)
        col_match, col_dist = _bipartite_match_single(dist)
        if match_type == "per_prediction":
            best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
            best_val = jnp.max(dist, axis=0)
            extra = (col_match < 0) & (best_val >= overlap_threshold)
            col_match = jnp.where(extra, best_row, col_match)
            col_dist = jnp.where(extra, best_val, col_dist)
        matched = col_match >= 0
        safe_idx = jnp.maximum(col_match, 0)

        # confidence loss on every prior (target = matched gt label or bg)
        tgt_label = jnp.where(matched, gtl[safe_idx], background)
        logp = jax.nn.log_softmax(conf_i)
        conf_loss_all = -jnp.take_along_axis(
            logp, tgt_label[:, None], axis=1)[:, 0]

        # max-negative mining: hardest unmatched priors, ratio-capped
        num_pos = matched.sum()
        neg_cand = (~matched) & (col_dist < neg_overlap)
        neg_score = jnp.where(neg_cand, conf_loss_all, NEG_INF)
        rank = jnp.argsort(jnp.argsort(-neg_score))
        num_neg = jnp.minimum((neg_pos_ratio * num_pos).astype(jnp.int32),
                              neg_cand.sum())
        selected_neg = neg_cand & (rank < num_neg)
        conf_loss = conf_loss_all * (matched | selected_neg)

        # localization loss on positives only
        enc = _encode_center_size(gtb[safe_idx], prior, var)
        loc_loss = _smooth_l1(loc_i - enc).sum(-1) * matched

        total = conf_w * conf_loss + loc_w * loc_loss
        if normalize:
            total = total / jnp.maximum(num_pos, 1).astype(total.dtype)
        return total[:, None]

    out = jax.vmap(one)(loc, conf, gt_boxes, labels, gt_lens)
    return {"Loss": [out]}


# ---------------------------------------------------------------------------
# Faster-RCNN / RPN family. The reference runs these on host CPU with
# dynamic-size outputs (rpn_target_assign_op.cc, generate_proposals_op.cc,
# generate_proposal_labels_op.cc); here every output is fixed-shape with
# zero-gradient padding so the whole RPN training path stays in XLA.

def _box_to_delta(ex, gt, weights=None, normalized=True):
    """Regression deltas from ex(anchor/roi) to gt (reference
    bbox_util.h BoxToDelta). Pixel boxes use the +1 width convention."""
    off = 0.0 if normalized else 1.0
    ex_w = ex[..., 2] - ex[..., 0] + off
    ex_h = ex[..., 3] - ex[..., 1] + off
    ex_cx = ex[..., 0] + 0.5 * ex_w
    ex_cy = ex[..., 1] + 0.5 * ex_h
    gt_w = gt[..., 2] - gt[..., 0] + off
    gt_h = gt[..., 3] - gt[..., 1] + off
    gt_cx = gt[..., 0] + 0.5 * gt_w
    gt_cy = gt[..., 1] + 0.5 * gt_h
    d = jnp.stack([(gt_cx - ex_cx) / ex_w, (gt_cy - ex_cy) / ex_h,
                   jnp.log(jnp.maximum(gt_w / ex_w, 1e-10)),
                   jnp.log(jnp.maximum(gt_h / ex_h, 1e-10))], axis=-1)
    if weights is not None:
        d = d / jnp.asarray(weights, d.dtype)
    return d


@register_op("anchor_generator")
def _anchor_generator(ctx, ins, attrs):
    """(reference anchor_generator_op.h): per feature-map cell, one
    anchor per (aspect_ratio, anchor_size) — ratio loop outer — with
    base w/h snapped to integers like the reference."""
    feat = ins["Input"][0]                           # [B, C, H, W]
    h, w = feat.shape[2], feat.shape[3]
    sizes = [float(s) for s in attrs["anchor_sizes"]]
    ars = [float(a) for a in attrs["aspect_ratios"]]
    stride_w, stride_h = [float(s) for s in attrs["stride"]]
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    offset = float(attrs.get("offset", 0.5))

    whs = []
    area = stride_w * stride_h
    for ar in ars:
        base_w = round((area / ar) ** 0.5)
        base_h = round(base_w * ar)
        for size in sizes:
            whs.append((size / stride_w * base_w, size / stride_h * base_h))
    whs = jnp.asarray(whs, jnp.float32)              # [A, 2]

    cx = jnp.arange(w, dtype=jnp.float32) * stride_w + \
        offset * (stride_w - 1)
    cy = jnp.arange(h, dtype=jnp.float32) * stride_h + \
        offset * (stride_h - 1)
    cxg, cyg = jnp.meshgrid(cx, cy)                  # [H, W]
    centers = jnp.stack([cxg, cyg], axis=-1)         # [H, W, 2]
    half = 0.5 * (whs - 1.0)                         # [A, 2]
    mins = centers[:, :, None, :] - half[None, None]
    maxs = centers[:, :, None, :] + half[None, None]
    anchors = jnp.concatenate([mins, maxs], axis=-1)  # [H, W, A, 4]
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           anchors.shape)
    return {"Anchors": [anchors], "Variances": [var]}


def _sample_mask(candidates, quota, key):
    """Pick up to ``quota`` True entries of ``candidates`` [N] uniformly
    at random (the reference's ReservoirSampling), as a bool mask —
    fixed shapes via randomized rank + threshold."""
    n = candidates.shape[0]
    noise = jax.random.uniform(key, (n,))
    score = jnp.where(candidates, noise, -1.0)
    rank = jnp.argsort(jnp.argsort(-score))          # 0 = best
    return candidates & (rank < quota)


@register_op("rpn_target_assign", stateful=True, seq_aware=True)
def _rpn_target_assign(ctx, ins, attrs):
    """Fused RPN target assignment (reference rpn_target_assign_op.cc):
    label anchors fg (best per gt, or IoU >= pos_thresh), bg
    (max IoU < neg_thresh), randomly subsample a fixed fg/bg budget,
    gather predictions and encode matched gt deltas.

    Fixed-shape outputs per image: F = rpn_batch_size*fg_fraction fg
    slots, S = rpn_batch_size score slots. Padded slots are constants
    with zero loss/gradient (loc: pred == target == 0; score: logit +20
    with label 1 → ~0 loss, no gradient into the model).
    """
    loc = ins["Loc"][0]                              # [B, M, 4]
    scores = ins["Scores"][0]                        # [B, M, 1]
    anchors = ins["Anchor"][0]                       # [M, 4]
    gt = ins["GtBox"][0]                             # SequenceBatch
    rpn_batch = int(attrs.get("rpn_batch_size_per_im", 256))
    fg_fraction = float(attrs.get("fg_fraction", 0.25))
    pos_thr = float(attrs.get("rpn_positive_overlap", 0.7))
    neg_thr = float(attrs.get("rpn_negative_overlap", 0.3))
    n_fg = int(rpn_batch * fg_fraction)
    n_s = rpn_batch
    gt_boxes, gt_lens = gt.data, gt.lengths
    key = ctx.next_key()

    def one(loc_i, score_i, gtb, glen, k):
        g = gtb.shape[0]
        m_anch = anchors.shape[0]
        valid_gt = jnp.arange(g) < glen
        iou = jnp.where(valid_gt[:, None],
                        _iou_matrix(gtb, anchors, normalized=False), 0.0)
        a2g_max = jnp.max(iou, axis=0)               # [M]
        a2g_arg = jnp.argmax(iou, axis=0).astype(jnp.int32)
        # (i) best anchor per valid gt is fg; padded gt rows scatter
        # out of range so they can't clobber anchor 0
        g2a_arg = jnp.argmax(iou, axis=1)            # [G]
        best_of_gt = jnp.zeros_like(a2g_max, bool).at[
            jnp.where(valid_gt, g2a_arg, m_anch)].set(True, mode="drop")
        fg_cand = best_of_gt | (a2g_max >= pos_thr)
        bg_cand = (~fg_cand) & (a2g_max < neg_thr)

        k1, k2 = jax.random.split(k)
        fg_sel = _sample_mask(fg_cand, n_fg, k1)
        num_fg = fg_sel.sum()
        bg_sel = _sample_mask(bg_cand, n_s - num_fg, k2)

        def pack(mask, quota):
            """indices of up to quota selected anchors, -1 padded."""
            score = jnp.where(mask, 1.0, 0.0)
            _, idx = jax.lax.top_k(score, quota)
            ok = mask[idx]
            return jnp.where(ok, idx, -1), ok

        fg_idx, fg_ok = pack(fg_sel, n_fg)
        safe_fg = jnp.maximum(fg_idx, 0)
        pred_loc = jnp.where(fg_ok[:, None], loc_i[safe_fg], 0.0)
        tgt_bbox = _box_to_delta(anchors[safe_fg],
                                 gtb[a2g_arg[safe_fg]], normalized=False)
        tgt_bbox = jnp.where(fg_ok[:, None], tgt_bbox, 0.0)

        # score slots: the full n_s minibatch — fg and bg packed
        # together so back-fill negatives (sampled when fg falls short
        # of quota, reference SampleFgBgGt) are kept, not truncated
        sel_rank = jnp.where(fg_sel, 2.0, 0.0) + jnp.where(bg_sel, 1.0,
                                                           0.0)
        _, s_idx = jax.lax.top_k(sel_rank, n_s)
        s_ok = sel_rank[s_idx] > 0
        pred_sc = jnp.where(s_ok[:, None], score_i[s_idx], 20.0)
        tgt_lbl = jnp.where(s_ok, fg_sel[s_idx], True).astype(canonical_int())
        return pred_sc, pred_loc, tgt_lbl[:, None], tgt_bbox

    keys = jax.random.split(key, loc.shape[0])
    ps, pl, tl, tb = jax.vmap(one)(loc, scores, gt_boxes, gt_lens, keys)
    b = loc.shape[0]
    return {"ScorePred": [ps.reshape(b * n_s, 1)],
            "LocPred": [pl.reshape(b * n_fg, 4)],
            "ScoreTarget": [tl.reshape(b * n_s, 1)],
            "LocTarget": [tb.reshape(b * n_fg, 4)]}


@register_op("generate_proposals")
def _generate_proposals(ctx, ins, attrs):
    """(reference generate_proposals_op.cc): decode RPN deltas against
    anchors, clip to image, drop boxes under min_size, top pre_nms_top_n
    by score, NMS, keep post_nms_top_n — all fixed-shape, zero-padded."""
    scores = ins["Scores"][0]                        # [N, A, H, W]
    deltas = ins["BboxDeltas"][0]                    # [N, 4A, H, W]
    im_info = ins["ImInfo"][0]                       # [N, 3] (h, w, scale)
    anchors = ins["Anchors"][0].reshape(-1, 4)       # [H*W*A, 4]
    variances = ins["Variances"][0].reshape(-1, 4)
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    nms_thresh = float(attrs.get("nms_thresh", 0.5))
    min_size = float(attrs.get("min_size", 0.1))
    eta = float(attrs.get("eta", 1.0))

    n, a, h, w = scores.shape
    m = h * w * a
    # NCHW -> [H, W, A(,4)] flat, matching the anchor layout
    sc = jnp.transpose(scores, (0, 2, 3, 1)).reshape(n, m)
    dl = jnp.transpose(deltas.reshape(n, a, 4, h, w),
                       (0, 3, 4, 1, 2)).reshape(n, m, 4)

    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + 0.5 * aw
    acy = anchors[:, 1] + 0.5 * ah

    def one(sc_i, dl_i, info):
        cx = acx + dl_i[:, 0] * variances[:, 0] * aw
        cy = acy + dl_i[:, 1] * variances[:, 1] * ah
        bw = jnp.exp(jnp.minimum(dl_i[:, 2] * variances[:, 2], 10.0)) * aw
        bh = jnp.exp(jnp.minimum(dl_i[:, 3] * variances[:, 3], 10.0)) * ah
        boxes = jnp.stack([cx - 0.5 * bw, cy - 0.5 * bh,
                           cx + 0.5 * bw - 1.0, cy + 0.5 * bh - 1.0],
                          axis=-1)
        # clip to image (reference ClipTiledBoxes)
        imh, imw = info[0], info[1]
        lim = jnp.stack([imw - 1.0, imh - 1.0, imw - 1.0, imh - 1.0])
        boxes = jnp.clip(boxes, 0.0, lim)
        # filter small boxes (reference FilterBoxes: min_size scaled)
        ms = jnp.maximum(min_size * info[2], 1.0)
        ws = boxes[:, 2] - boxes[:, 0] + 1.0
        hs = boxes[:, 3] - boxes[:, 1] + 1.0
        keep = (ws >= ms) & (hs >= ms) & \
            (boxes[:, 0] + 0.5 * ws <= imw) & (boxes[:, 1] + 0.5 * hs <= imh)
        s = jnp.where(keep, sc_i, NEG_INF)
        top = min(pre_n, m)
        k = min(post_n, top)
        s_top, order = jax.lax.top_k(s, top)
        b_top = boxes[order]

        if top <= 2048:
            # dense path: one [top, top] IoU matrix + suppression scan
            iou = _iou_matrix(b_top, b_top, normalized=False)

            def suppress(carry, i):
                alive, thr = carry
                sup = (iou[i] > thr) & alive & \
                    (jnp.arange(top) > i) & alive[i]
                thr = jnp.where((eta < 1.0) & (thr > 0.5) & alive[i],
                                thr * eta, thr)
                return (alive & ~sup, thr), None

            (alive, _), _ = jax.lax.scan(
                suppress, (s_top > NEG_INF / 2,
                           jnp.asarray(nms_thresh, s_top.dtype)),
                jnp.arange(top))
            final = jnp.where(alive, s_top, NEG_INF)
            fs, fi = jax.lax.top_k(final, k)
            ok = fs > NEG_INF / 2
            rois = jnp.where(ok[:, None], b_top[fi], 0.0)
            probs = jnp.where(ok, fs, 0.0)
            return rois, probs[:, None]

        # large pre_nms pools (reference default 6000): a [top, top]
        # matrix is O(top^2) HBM — select the post_nms_top_n survivors
        # iteratively instead, one [top]-sized IoU row per pick
        def pick(carry, _):
            alive, thr = carry
            i = jnp.argmax(jnp.where(alive, s_top, NEG_INF))
            good = alive[i]
            iou_row = _iou_matrix(b_top[i][None], b_top,
                                  normalized=False)[0]
            alive = alive & (iou_row <= thr)
            alive = alive.at[i].set(False)
            thr = jnp.where((eta < 1.0) & (thr > 0.5) & good, thr * eta,
                            thr)
            score = jnp.where(good, s_top[i], NEG_INF)
            return (alive, thr), (i, score)

        (alive, _), (idx_sel, sc_sel) = jax.lax.scan(
            pick, (s_top > NEG_INF / 2,
                   jnp.asarray(nms_thresh, s_top.dtype)),
            None, length=k)
        ok = sc_sel > NEG_INF / 2
        rois = jnp.where(ok[:, None], b_top[idx_sel], 0.0)
        probs = jnp.where(ok, sc_sel, 0.0)
        return rois, probs[:, None]

    rois, probs = jax.vmap(one)(sc, dl, im_info)
    return {"RpnRois": [rois], "RpnRoiProbs": [probs]}


@register_op("generate_proposal_labels", stateful=True, seq_aware=True)
def _generate_proposal_labels(ctx, ins, attrs):
    """(reference generate_proposal_labels_op.cc): append gt boxes to the
    proposals, match by IoU, sample a fixed fg/bg RoI minibatch, emit
    per-class bbox regression targets. Fixed [B, S, ...] outputs; padded
    rows have label -1 (mask them from the cls loss) and zero weights."""
    rois = ins["RpnRois"][0]                         # [B, R, 4]
    gt_cls = ins["GtClasses"][0]                     # SequenceBatch int
    gt_box = ins["GtBoxes"][0]                       # SequenceBatch [G,4]
    im_scales = ins["ImScales"][0]                   # [B, 1] or [B]
    batch_size = int(attrs.get("batch_size_per_im", 256))
    fg_fraction = float(attrs.get("fg_fraction", 0.25))
    fg_thresh = float(attrs.get("fg_thresh", 0.25))
    bg_hi = float(attrs.get("bg_thresh_hi", 0.5))
    bg_lo = float(attrs.get("bg_thresh_lo", 0.0))
    reg_w = [float(v) for v in attrs.get("bbox_reg_weights",
                                         [0.1, 0.1, 0.2, 0.2])]
    n_cls = int(attrs["class_nums"])
    n_fg = int(round(fg_fraction * batch_size))
    gtb, glens = gt_box.data, gt_box.lengths
    gtc = gt_cls.data
    if gtc.ndim == 3:
        gtc = gtc[..., 0]
    gtc = gtc.astype(jnp.int32)
    scales = im_scales.reshape(-1)
    key = ctx.next_key()

    def one(rois_i, gtb_i, gtc_i, glen, scale, k):
        g = gtb_i.shape[0]
        valid_gt = jnp.arange(g) < glen
        gt_scaled = gtb_i * scale
        cand = jnp.concatenate([rois_i, jnp.where(valid_gt[:, None],
                                                  gt_scaled, 0.0)])
        # match in the scaled coordinate frame the candidates live in,
        # with the reference's +1 pixel-width convention
        iou = jnp.where(valid_gt[:, None],
                        _iou_matrix(gt_scaled, cand, normalized=False),
                        0.0)
        max_iou = jnp.max(iou, axis=0)               # [R+G]
        argmax = jnp.argmax(iou, axis=0)
        # non-box padding (all-zero candidate rows) never matches
        real = jnp.any(cand != 0.0, axis=-1)
        fg_cand = real & (max_iou >= fg_thresh)
        bg_cand = real & (max_iou < bg_hi) & (max_iou >= bg_lo)
        k1, k2 = jax.random.split(k)
        fg_sel = _sample_mask(fg_cand, n_fg, k1)
        bg_sel = _sample_mask(bg_cand, batch_size - fg_sel.sum(), k2)
        # pack fg + back-fill bg into the full fixed minibatch (fg
        # slots lead; when fg is short, extra sampled bg fill the rest)
        sel_rank = jnp.where(fg_sel, 2.0, 0.0) + jnp.where(bg_sel, 1.0,
                                                           0.0)
        _, idx = jax.lax.top_k(sel_rank, batch_size)
        ok = sel_rank[idx] > 0
        is_fg = fg_sel[idx] & ok

        out_rois = jnp.where(ok[:, None], cand[idx], 0.0)
        match = argmax[idx]
        # -1 marks padded slots so losses can mask them out
        labels = jnp.where(ok, jnp.where(is_fg, gtc_i[match], 0), -1)
        deltas = _box_to_delta(cand[idx], gt_scaled[match],
                               weights=reg_w, normalized=False)
        # per-class layout [S, 4*n_cls], only the matched class filled
        cls_onehot = jax.nn.one_hot(labels, n_cls,
                                    dtype=deltas.dtype)     # [S, C]
        tgt = cls_onehot[:, :, None] * deltas[:, None, :]   # [S, C, 4]
        w_in = cls_onehot[:, :, None] * \
            jnp.ones_like(deltas)[:, None, :] * is_fg[:, None, None]
        tgt = (tgt * is_fg[:, None, None]).reshape(-1, 4 * n_cls)
        w_in = w_in.reshape(-1, 4 * n_cls)
        return out_rois, labels, tgt, w_in, w_in

    keys = jax.random.split(key, rois.shape[0])
    r, l, t, wi, wo = jax.vmap(one)(rois, gtb, gtc, glens, scales, keys)
    return {"Rois": [r], "LabelsInt32": [l.astype(jnp.int32)],
            "BboxTargets": [t], "BboxInsideWeights": [wi],
            "BboxOutsideWeights": [wo]}
