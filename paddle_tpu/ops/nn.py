"""Neural-network op lowering rules: conv / pool / norm / embedding /
dropout / losses / metrics.

Capability parity with paddle/fluid/operators/{conv_op, pool_op,
batch_norm_op, layer_norm_op, lookup_table_op, dropout_op,
cross_entropy_op, softmax_with_cross_entropy_op, accuracy_op, auc_op,
...}.cc. Layout note: fluid kernels are NCHW; these rules accept NCHW at
the op boundary (for API parity) but run convolutions through
lax.conv_general_dilated with explicit dimension_numbers so XLA picks the
MXU-friendly internal layout.
"""
import os
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax import ad_checkpoint

from ..core.registry import canonical_int, register_op

# ---------------------------------------------------------------------------
# convolution
# ---------------------------------------------------------------------------


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


@register_op("conv2d")
def _conv2d(ctx, ins, attrs):
    """reference paddle/fluid/operators/conv_op.cc. Filter
    [cout, cin/groups, kh, kw] (fluid layout). Input NCHW by default;
    data_format="NHWC" runs channels-minor — the TPU-native layout
    (lane dim = features), which spares XLA the per-conv activation
    layout copies an NCHW graph needs (measured: the #1 kernel/bytes
    bucket of the NCHW ResNet-50 step)."""
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dil = _pair(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1) or 1
    fmt = attrs.get("data_format", attrs.get("data_layout", "NCHW"))
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    (fmt, "OIHW", fmt))
    out = lax.conv_general_dilated(
        x, w, window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dil, dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=jnp.float32 if x.dtype == jnp.float32 else None)
    out = out.astype(x.dtype)
    # remat hook ("save_conv_only" policy): conv outputs become the
    # ONLY saved residuals — the restrictive inverse of
    # recompute_norms' allow-most policy, whose pinned-everything
    # residual set OOM'd the XLA:TPU compiler at bench scale
    # (BASELINE lever_history_round4). Tagged only when active: the
    # name primitive changes the HLO and untouched programs must stay
    # byte-identical to the measured fast path.
    if getattr(ctx.program, "_remat_policy", None) == "save_conv_only":
        out = ad_checkpoint.checkpoint_name(out, "conv_out")
    return {"Output": [out]}


@register_op("depthwise_conv2d")
def _depthwise_conv2d(ctx, ins, attrs):
    return _conv2d(ctx, ins, attrs)


def _conv_transpose_nd(ins, attrs, nd, layouts, c_axis=1):
    """Shared N-D deconv lowering (reference conv_transpose_op.cc): the
    gradient of a forward conv whose [cin, cout/g, *k] fluid filter is
    the O-I-spatial kernel (cin is the forward conv's OUTPUT) —
    transpose_kernel=True. lax.conv_transpose's explicit padding counts
    from the FULL (zero-pad) deconv: out = (in-1)s + ke - 2(ke-1-p_jax)
    with effective kernel extent ke = d(k-1)+1, so the fluid padding p
    maps to p_jax = d(k-1) - p. (Passing p directly is only right at
    p == (ke-1)/2 — exactly the k=3,p=1 point the original 2D test sat
    on; the signature-parity sweep's conv3d_transpose exposed it.)
    ``c_axis`` is the activation channel axis (1 for NC*, last for
    N*C) — grouped deconvs split activations there."""
    x, w = ins["Input"][0], ins["Filter"][0]
    ones = [1] * nd
    strides = list(attrs.get("strides", ones))
    pads = list(attrs.get("paddings", [0] * nd))
    dil = list(attrs.get("dilations", ones))
    groups = attrs.get("groups", 1) or 1
    jpads = [dil[i] * (w.shape[2 + i] - 1) - pads[i] for i in range(nd)]

    def one_group(xg, wg):
        dn = lax.conv_dimension_numbers(xg.shape, wg.shape, layouts)
        return lax.conv_transpose(
            xg, wg, strides=strides,
            padding=[(p_, p_) for p_ in jpads],
            rhs_dilation=dil, dimension_numbers=dn,
            transpose_kernel=True)

    if groups == 1:
        out = one_group(x, w)
    else:
        xs = jnp.split(x, groups, axis=c_axis)
        ws = jnp.split(w, groups, axis=0)
        out = jnp.concatenate(
            [one_group(xg, wg) for xg, wg in zip(xs, ws)],
            axis=c_axis)
    return {"Output": [out]}


@register_op("conv2d_transpose")
def _conv2d_transpose(ctx, ins, attrs):
    fmt = attrs.get("data_format", attrs.get("data_layout", "NCHW"))
    if fmt == "NHWC":
        return _conv_transpose_nd(ins, attrs, 2,
                                  ("NHWC", "OIHW", "NHWC"), c_axis=3)
    return _conv_transpose_nd(ins, attrs, 2, ("NCHW", "OIHW", "NCHW"))


@register_op("conv3d_transpose")
def _conv3d_transpose(ctx, ins, attrs):
    return _conv_transpose_nd(ins, attrs, 3, ("NCDHW", "OIDHW", "NCDHW"))


@register_op("conv3d")
def _conv3d(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = _pair(attrs.get("strides", [1, 1, 1]), 3)
    pads = _pair(attrs.get("paddings", [0, 0, 0]), 3)
    dil = _pair(attrs.get("dilations", [1, 1, 1]), 3)
    dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                    ("NCDHW", "OIDHW", "NCDHW"))
    out = lax.conv_general_dilated(
        x, w, window_strides=strides,
        padding=[(p, p) for p in pads], rhs_dilation=dil,
        dimension_numbers=dn,
        feature_group_count=attrs.get("groups", 1) or 1)
    return {"Output": [out]}


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------


def _pool(x, ksize, strides, pads, ptype, ceil_mode, global_pool, nd=2,
          fmt="NCHW"):
    spatial = (range(2, 2 + nd) if fmt == "NCHW"
               else range(1, 1 + nd))
    if global_pool:
        ksize = tuple(x.shape[i] for i in spatial)
        pads = (0,) * nd
        strides = ksize
    if fmt == "NCHW":
        window = (1, 1) + tuple(ksize)
        stride = (1, 1) + tuple(strides)
        pad_sp = tuple((p, p) for p in pads)
        padding = ((0, 0), (0, 0)) + pad_sp
    else:                       # N <spatial> C
        window = (1,) + tuple(ksize) + (1,)
        stride = (1,) + tuple(strides) + (1,)
        pad_sp = tuple((p, p) for p in pads)
        padding = ((0, 0),) + pad_sp + ((0, 0),)
    if ceil_mode:
        # pad right edge so the last partial window is included
        extra = []
        for i, ax in enumerate(spatial):
            size = x.shape[ax] + 2 * pads[i]
            rem = (size - ksize[i]) % strides[i]
            extra.append((strides[i] - rem) % strides[i] if rem else 0)
        pad_sp = tuple((p, p + e) for p, e in zip(pads, extra))
        if fmt == "NCHW":
            padding = ((0, 0), (0, 0)) + pad_sp
        else:
            padding = ((0, 0),) + pad_sp + ((0, 0),)
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, stride, padding)
    # avg: fluid's default (exclusive=True) divides by actual window size.
    # bf16 input accumulates in f32 (the upcast fuses into the window
    # reduce; a 49-tap bf16 sum would cost ~1% relative error).
    acc_dtype = jnp.float32 if x.dtype == jnp.bfloat16 else x.dtype
    s = lax.reduce_window(x.astype(acc_dtype), 0.0, lax.add, window,
                          stride, padding)
    if fmt == "NCHW":
        ones_shape = x.shape[:1] + (1,) + x.shape[2:]
    else:
        ones_shape = x.shape[:-1] + (1,)
    ones = jnp.ones(ones_shape, acc_dtype)
    cnt = lax.reduce_window(ones, 0.0, lax.add, window, stride, padding)
    out = s / cnt
    # float inputs round-trip to their own dtype (bf16 stays bf16);
    # integer avg keeps the float quotient (parity with the pre-f32-
    # accumulation behavior)
    if jnp.issubdtype(x.dtype, jnp.floating):
        out = out.astype(x.dtype)
    return out


@register_op("pool2d")
def _pool2d(ctx, ins, attrs):
    x = ins["X"][0]
    out = _pool(x, _pair(attrs.get("ksize", [2, 2])),
                _pair(attrs.get("strides", [1, 1])),
                _pair(attrs.get("paddings", [0, 0])),
                attrs.get("pooling_type", "max"),
                attrs.get("ceil_mode", False),
                attrs.get("global_pooling", False), nd=2,
                fmt=attrs.get("data_format", "NCHW"))
    return {"Out": [out]}


@register_op("pool3d")
def _pool3d(ctx, ins, attrs):
    x = ins["X"][0]
    out = _pool(x, _pair(attrs.get("ksize", [2, 2, 2]), 3),
                _pair(attrs.get("strides", [1, 1, 1]), 3),
                _pair(attrs.get("paddings", [0, 0, 0]), 3),
                attrs.get("pooling_type", "max"),
                attrs.get("ceil_mode", False),
                attrs.get("global_pooling", False), nd=3)
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------


def _bn_autodiff():
    """A/B seam: PADDLE_TPU_BN_AUTODIFF=1 routes batch_norm training
    through plain autodiff of the forward instead of the hand-derived
    custom_vjp. Read at TRACE time (not import) so setting the env var
    after ``import paddle_tpu`` still takes effect."""
    return os.environ.get("PADDLE_TPU_BN_AUTODIFF", "0") == "1"


def _bn_core(x, scale, bias, axes, bshape, eps):
    """One-pass-stats batch norm in f32: returns (y, bm, bv, inv)."""
    bm = jnp.mean(x, axis=axes)
    bv = jnp.maximum(jnp.mean(x * x, axis=axes) - bm * bm, 0.0)
    inv = lax.rsqrt(bv.reshape(bshape) + eps)
    y = (x - bm.reshape(bshape)) * inv * scale.reshape(bshape) \
        + bias.reshape(bshape)
    return y, bm, bv, inv


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _bn_train(x, scale, bias, axes, bshape, eps):
    y, bm, bv, _ = _bn_core(x, scale, bias, axes, bshape, eps)
    return y, bm, bv


def _bn_train_fwd(x, scale, bias, axes, bshape, eps):
    y, bm, bv, inv = _bn_core(x, scale, bias, axes, bshape, eps)
    return (y, bm, bv), (x, scale, bm, inv)


def _bn_train_bwd(axes, bshape, eps, res, cts):
    """Hand-derived (textbook) BN backward — round-5 device-time
    profile evidence: autodiff of the one-pass-stats graph compiled to
    ~3 separate activation sweeps per BN (52.9% of the whole ResNet-50
    step's device time, BASELINE device_time_profile_round5); the
    canonical form needs one fused (dbias, dscale) reduce sweep over
    (x, dy) plus one elementwise dx pass:

      x̂ = (x - μ)·inv;  dβ = Σ dy;  dγ = Σ dy·x̂
      dx = γ·inv·(dy - dβ/n - x̂·dγ/n)

    The moving-stat outputs' cotangents are zero by construction (the
    op stop_gradients them), so they are ignored here."""
    x, scale, bm, inv = res
    dy = cts[0]
    n = x.size // scale.size            # reduced elements per channel
    xhat = (x - bm.reshape(bshape)) * inv
    dbias = jnp.sum(dy, axis=axes)
    dscale = jnp.sum(dy * xhat, axis=axes)
    dx = (inv * scale.reshape(bshape)) * (
        dy - (dbias / n).reshape(bshape)
        - xhat * (dscale / n).reshape(bshape))
    return dx, dscale, dbias


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


@register_op("batch_norm")
def _batch_norm(ctx, ins, attrs):
    """reference paddle/fluid/operators/batch_norm_op.cc. Data NCHW (or NC).
    Outputs updated moving stats functionally (MeanOut/VarianceOut alias the
    input stat vars; the executor writes them back to scope)."""
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean, var = ins["Mean"][0], ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False) or ctx.is_test
    layout = attrs.get("data_layout", "NCHW")
    c_axis = 1 if layout == "NCHW" else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = tuple(x.shape[c_axis] if i == c_axis else 1
                   for i in range(x.ndim))

    # bf16 activations (AMP O2): statistics and the normalize math run
    # in f32 internally — the upcast fuses into the reduce/elementwise
    # kernels so HBM traffic stays 2 bytes/element — and Y is cast back
    # to the input dtype. Scale/bias/moving stats are f32 either way.
    in_dtype = x.dtype
    xf = x.astype(jnp.float32) if in_dtype == jnp.bfloat16 else x

    if is_test or attrs.get("use_global_stats", False):
        inv = lax.rsqrt(var.reshape(bshape) + eps)
        y = (xf - mean.reshape(bshape)) * inv * scale.reshape(bshape) \
            + bias.reshape(bshape)
        mean_out, var_out = mean, var
        saved_mean, saved_var = mean, var
    else:
        # one-pass statistics (E[x^2] - E[x]^2, like the reference's
        # CUDA kernels): both reduces share the input and shape, so XLA
        # fuses them into ONE kernel reading x once — jnp.var's
        # two-pass form costs a second full activation sweep per BN.
        # The TRAIN path runs through _bn_train (hand-derived
        # custom_vjp backward — see _bn_train_bwd for the measured
        # rationale); PADDLE_TPU_BN_AUTODIFF=1 falls back to plain
        # autodiff of the same forward (the A/B seam the round-5
        # profile numbers were taken against).
        if _bn_autodiff():
            y, bm, bv, _ = _bn_core(xf, scale, bias, axes, bshape, eps)
        else:
            y, bm, bv = _bn_train(xf, scale, bias, axes, bshape, eps)
        mean_out = mean * momentum + bm * (1 - momentum)
        var_out = var * momentum + bv * (1 - momentum)
        saved_mean, saved_var = bm, bv
    y = y.astype(in_dtype)
    # remat hook (transpiler/memory_optimization.py "recompute_norms"):
    # the normalize is cheap elementwise math over x, which autodiff
    # must save for the BN backward anyway — naming y lets the policy
    # recompute it in the backward instead of saving BOTH x and y.
    # Tagged only when that policy is active: the name primitive
    # changes the emitted HLO, and untouched programs must stay
    # byte-identical to the measured fast path.
    if getattr(ctx.program, "_remat_policy", None) == "recompute_norms":
        y = ad_checkpoint.checkpoint_name(y, "batch_norm_out")
    return {"Y": [y],
            "MeanOut": [lax.stop_gradient(mean_out)],
            "VarianceOut": [lax.stop_gradient(var_out)],
            "SavedMean": [lax.stop_gradient(saved_mean)],
            "SavedVariance": [lax.stop_gradient(saved_var)]}


@register_op("layer_norm")
def _layer_norm(ctx, ins, attrs):
    x = ins["X"][0]
    begin = attrs.get("begin_norm_axis", 1)
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(begin, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    norm_shape = (1,) * begin + x.shape[begin:]
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(norm_shape)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(norm_shape)
    return {"Y": [y], "Mean": [mean.reshape(x.shape[:begin])],
            "Variance": [var.reshape(x.shape[:begin])]}


@register_op("lrn")
def _lrn(ctx, ins, attrs):
    """Local response norm across channels. NCHW by default;
    data_format="NHWC" windows the LAST axis instead (the layout
    conversion pass flips this attr like conv/pool/BN)."""
    x = ins["X"][0]
    n = attrs.get("n", 5)
    k, alpha, beta = attrs.get("k", 2.0), attrs.get("alpha", 1e-4), \
        attrs.get("beta", 0.75)
    c_axis = 1 if attrs.get("data_format", "NCHW") == "NCHW" \
        else x.ndim - 1
    sq = jnp.square(x)
    half = n // 2
    pads = [(half, half) if i == c_axis else (0, 0)
            for i in range(x.ndim)]
    pad = jnp.pad(sq, pads)
    c = x.shape[c_axis]
    acc = sum(lax.slice_in_dim(pad, i, i + c, axis=c_axis)
              for i in range(n))
    return {"Out": [x / jnp.power(k + alpha * acc, beta)],
            "MidOut": [acc]}


@register_op("group_norm")
def _group_norm(ctx, ins, attrs):
    x = ins["X"][0]  # NCHW
    g = attrs.get("groups", 32)
    eps = attrs.get("epsilon", 1e-5)
    n, c = x.shape[:2]
    xr = x.reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xr.ndim))
    mean = jnp.mean(xr, axis=axes, keepdims=True)
    var = jnp.var(xr, axis=axes, keepdims=True)
    y = ((xr - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(bshape)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(bshape)
    return {"Y": [y], "Mean": [mean.reshape(n, g)],
            "Variance": [var.reshape(n, g)]}


# ---------------------------------------------------------------------------
# embedding / dropout
# ---------------------------------------------------------------------------


@register_op("lookup_table", seq_aware=True)
def _lookup_table(ctx, ins, attrs):
    """reference paddle/fluid/operators/lookup_table_op.cc. Ids [..., 1]
    int64; padding_idx rows return zeros. SequenceBatch ids yield a
    SequenceBatch of embeddings."""
    from ..core.sequence import SequenceBatch
    w, ids = ins["W"][0], ins["Ids"][0]
    lengths = counts = None
    if isinstance(ids, SequenceBatch):
        lengths = ids.lengths
        counts = ids.outer_counts
        ids = ids.data
    if ids.shape and ids.shape[-1] == 1:
        ids = ids.reshape(ids.shape[:-1])
    if not jnp.issubdtype(ids.dtype, jnp.integer):
        ids = ids.astype(jnp.int32)
    pad = attrs.get("padding_idx", -1)
    out = jnp.take(w, ids, axis=0)
    if pad is not None and pad != -1:
        mask = (ids != pad)[..., None].astype(out.dtype)
        out = out * mask
    if lengths is not None:
        out = SequenceBatch(out, lengths, counts)
    return {"Out": [out]}


@register_op("dropout", stateful=True)
def _dropout(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs.get("dropout_prob", 0.5)
    is_test = attrs.get("is_test", False) or ctx.is_test
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if is_test:
        out = x * (1.0 - p) if impl == "downgrade_in_infer" else x
        return {"Out": [out], "Mask": [jnp.ones_like(x)]}
    keep = jax.random.bernoulli(ctx.next_key(), 1.0 - p, x.shape)
    mask = keep.astype(x.dtype)
    if impl == "upscale_in_train":
        out = jnp.where(keep, x / (1.0 - p), 0.0)
    else:
        out = x * mask
    return {"Out": [out], "Mask": [mask]}


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------


@register_op("cross_entropy")
def _cross_entropy(ctx, ins, attrs):
    """reference paddle/fluid/operators/cross_entropy_op.cc: X is a
    probability distribution [N, D]; Label is int64 [N, 1] (or soft [N, D])."""
    x, label = ins["X"][0], ins["Label"][0]
    eps = 1e-9
    if attrs.get("soft_label", False):
        out = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        lbl = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 else label
        ignore = attrs.get("ignore_index", -100)
        safe = jnp.where(lbl == ignore, 0, lbl)
        picked = jnp.take_along_axis(x, safe[..., None].astype(jnp.int32),
                                     axis=-1)
        out = jnp.where((lbl == ignore)[..., None], 0.0, -jnp.log(picked + eps))
    return {"Y": [out]}


@register_op("softmax_with_cross_entropy")
def _softmax_with_cross_entropy(ctx, ins, attrs):
    logits, label = ins["Logits"][0], ins["Label"][0]
    lsm = jax.nn.log_softmax(logits, axis=-1)
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * lsm, axis=-1, keepdims=True)
    else:
        lbl = label.reshape(label.shape[:-1]) if label.shape[-1] == 1 else label
        ignore = attrs.get("ignore_index", -100)
        safe = jnp.where(lbl == ignore, 0, lbl)
        picked = jnp.take_along_axis(lsm, safe[..., None].astype(jnp.int32),
                                     axis=-1)
        loss = jnp.where((lbl == ignore)[..., None], 0.0, -picked)
    return {"Loss": [loss], "Softmax": [jnp.exp(lsm)]}


@register_op("sigmoid_cross_entropy_with_logits")
def _sigmoid_ce(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    loss = jnp.maximum(x, 0) - x * label + jax.nn.softplus(-jnp.abs(x))
    ignore = attrs.get("ignore_index", -100)
    loss = jnp.where(label == ignore, 0.0, loss)
    return {"Out": [loss]}


@register_op("square_error_cost")
def _square_error_cost(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [jnp.square(x - y)]}


@register_op("smooth_l1_loss")
def _smooth_l1(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    sigma2 = attrs.get("sigma", 1.0) ** 2
    diff = x - y
    if ins.get("InsideWeight"):
        diff = diff * ins["InsideWeight"][0]
    ad = jnp.abs(diff)
    loss = jnp.where(ad < 1.0 / sigma2, 0.5 * sigma2 * diff * diff,
                     ad - 0.5 / sigma2)
    if ins.get("OutsideWeight"):
        loss = loss * ins["OutsideWeight"][0]
    out = jnp.sum(loss.reshape(loss.shape[0], -1), axis=1, keepdims=True)
    return {"Out": [out], "Diff": [diff]}


@register_op("huber_loss")
def _huber_loss(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    d = attrs.get("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    loss = jnp.where(ar <= d, 0.5 * r * r, d * (ar - 0.5 * d))
    return {"Out": [loss], "Residual": [r]}


@register_op("rank_loss")
def _rank_loss(ctx, ins, attrs):
    label, left, right = ins["Label"][0], ins["Left"][0], ins["Right"][0]
    d = left - right
    return {"Out": [jax.nn.softplus(d) - label * d]}


@register_op("margin_rank_loss")
def _margin_rank_loss(ctx, ins, attrs):
    label, x1, x2 = ins["Label"][0], ins["X1"][0], ins["X2"][0]
    margin = attrs.get("margin", 0.0)
    act = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": [act], "Activated": [(act > 0).astype(x1.dtype)]}


@register_op("hinge_loss")
def _hinge_loss(ctx, ins, attrs):
    logits, label = ins["Logits"][0], ins["Labels"][0]
    return {"Loss": [jnp.maximum(0.0, 1.0 - (2 * label - 1) * logits)]}


@register_op("log_loss")
def _log_loss(ctx, ins, attrs):
    pred, label = ins["Predicted"][0], ins["Labels"][0]
    eps = attrs.get("epsilon", 1e-4)
    out = -label * jnp.log(pred + eps) - (1 - label) * jnp.log(1 - pred + eps)
    return {"Loss": [out]}


@register_op("kldiv_loss")
def _kldiv_loss(ctx, ins, attrs):
    x, target = ins["X"][0], ins["Target"][0]
    loss = target * (jnp.log(jnp.maximum(target, 1e-10)) - x)
    red = attrs.get("reduction", "mean")
    if red == "mean":
        loss = jnp.mean(loss).reshape(())
    elif red == "sum":
        loss = jnp.sum(loss).reshape(())
    elif red == "batchmean":
        loss = (jnp.sum(loss) / x.shape[0]).reshape(())
    return {"Loss": [loss]}


@register_op("dice_loss")
def _dice_loss(ctx, ins, attrs):
    # composed in fluid python; kept as an op for convenience
    x, label = ins["X"][0], ins["Label"][0]
    eps = attrs.get("epsilon", 1e-5)
    lbl = jax.nn.one_hot(label.reshape(label.shape[:-1]), x.shape[-1],
                         dtype=x.dtype)
    reduce_dims = tuple(range(1, x.ndim))
    inter = jnp.sum(x * lbl, axis=reduce_dims)
    union = jnp.sum(x, axis=reduce_dims) + jnp.sum(lbl, axis=reduce_dims)
    return {"Out": [(1 - (2 * inter + eps) / (union + eps))]}


@register_op("label_smooth")
def _label_smooth(ctx, ins, attrs):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 0.1)
    if ins.get("PriorDist"):
        prior = ins["PriorDist"][0]
        return {"Out": [(1 - eps) * x + eps * prior]}
    return {"Out": [(1 - eps) * x + eps / x.shape[-1]]}


@register_op("l1_norm")
def _l1_norm(ctx, ins, attrs):
    return {"Out": [jnp.sum(jnp.abs(ins["X"][0])).reshape((1,))]}


@register_op("squared_l2_norm")
def _squared_l2_norm(ctx, ins, attrs):
    return {"Out": [jnp.sum(jnp.square(ins["X"][0])).reshape((1,))]}


@register_op("squared_l2_distance")
def _squared_l2_distance(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    d = x - y
    return {"Out": [jnp.sum(jnp.square(d), axis=-1, keepdims=True)],
            "sub_result": [d]}


@register_op("mean_iou")
def _mean_iou(ctx, ins, attrs):
    pred, label = ins["Predictions"][0], ins["Labels"][0]
    n = attrs["num_classes"]
    p = pred.reshape(-1).astype(jnp.int32)
    l = label.reshape(-1).astype(jnp.int32)
    cm = jnp.zeros((n, n), jnp.float32).at[l, p].add(1.0)
    inter = jnp.diag(cm)
    union = cm.sum(0) + cm.sum(1) - inter
    iou = jnp.where(union > 0, inter / jnp.maximum(union, 1), 0.0)
    valid = (union > 0).sum()
    return {"OutMeanIou": [iou.sum() / jnp.maximum(valid, 1)],
            "OutWrong": [(union - inter).astype(jnp.int32)],
            "OutCorrect": [inter.astype(jnp.int32)]}


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


@register_op("accuracy")
def _accuracy(ctx, ins, attrs):
    """reference paddle/fluid/operators/accuracy_op.cc: Out(top-k indices)
    vs Label [N, 1]."""
    idx, label = ins["Indices"][0], ins["Label"][0]
    lbl = label.reshape(-1)
    correct = jnp.any(idx == lbl[:, None], axis=1)
    total = jnp.asarray(lbl.shape[0], jnp.int32)
    c = jnp.sum(correct.astype(jnp.float32))
    return {"Accuracy": [(c / lbl.shape[0]).reshape((1,))],
            "Correct": [c.astype(jnp.int32).reshape((1,))],
            "Total": [total.reshape((1,))]}


@register_op("auc")
def _auc(ctx, ins, attrs):
    """Streaming AUC (reference paddle/fluid/operators/auc_op.cc): updates
    persistable TP/FP histogram state functionally."""
    preds, label = ins["Predict"][0], ins["Label"][0]
    stat_pos, stat_neg = ins["StatPos"][0], ins["StatNeg"][0]
    bins = stat_pos.shape[0]
    pos_score = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 \
        else preds.reshape(-1)
    idx = jnp.clip((pos_score * (bins - 1)).astype(jnp.int32), 0, bins - 1)
    lbl = label.reshape(-1).astype(jnp.float32)
    stat_pos = stat_pos.at[idx].add(lbl)
    stat_neg = stat_neg.at[idx].add(1.0 - lbl)
    # trapezoid over thresholds (descending)
    tp = jnp.cumsum(stat_pos[::-1])
    fp = jnp.cumsum(stat_neg[::-1])
    tot_pos, tot_neg = tp[-1], fp[-1]
    tpr = tp / jnp.maximum(tot_pos, 1.0)
    fpr = fp / jnp.maximum(tot_neg, 1.0)
    tpr0 = jnp.concatenate([jnp.zeros(1), tpr[:-1]])
    fpr0 = jnp.concatenate([jnp.zeros(1), fpr[:-1]])
    auc = jnp.sum((fpr - fpr0) * (tpr + tpr0) / 2.0)
    return {"AUC": [auc.reshape((1,))],
            "StatPosOut": [stat_pos], "StatNegOut": [stat_neg]}


# ---------------------------------------------------------------------------
# attention (composed scaled-dot-product; flash attention kernel lives in
# paddle_tpu/ops/pallas_attention.py and is used by the transformer models)
# ---------------------------------------------------------------------------


@register_op("scaled_dot_product_attention")
def _sdpa(ctx, ins, attrs):
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    scale = attrs.get("scale", None) or (1.0 / np.sqrt(q.shape[-1]))
    logits = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if ins.get("Mask"):
        logits = logits + ins["Mask"][0]
    w = jax.nn.softmax(logits, axis=-1)
    return {"Out": [jnp.einsum("...qk,...kd->...qd", w, v)]}


# ---------------------------------------------------------------------------
# image ops
# ---------------------------------------------------------------------------


@register_op("bilinear_interp")
def _bilinear_interp(ctx, ins, attrs):
    x = ins["X"][0]  # NCHW
    oh = attrs.get("out_h")
    ow = attrs.get("out_w")
    if ins.get("OutSize"):
        pass  # dynamic sizes unsupported under jit; attrs take precedence
    out = jax.image.resize(x, (x.shape[0], x.shape[1], oh, ow), "bilinear")
    return {"Out": [out]}


@register_op("nearest_interp")
def _nearest_interp(ctx, ins, attrs):
    x = ins["X"][0]
    oh, ow = attrs.get("out_h"), attrs.get("out_w")
    out = jax.image.resize(x, (x.shape[0], x.shape[1], oh, ow), "nearest")
    return {"Out": [out]}


@register_op("roi_pool")
def _roi_pool(ctx, ins, attrs):
    """reference paddle/fluid/operators/roi_pool_op.cc — static-shape
    version: rois [R, 4] (x1,y1,x2,y2) with batch ids."""
    x, rois = ins["X"][0], ins["ROIs"][0]
    if rois.ndim == 3:
        # batched [B, S, 4] rois (generate_proposal_labels output):
        # flatten and derive the batch ids
        b, s, _ = rois.shape
        batch_ids = jnp.repeat(jnp.arange(b, dtype=jnp.int32), s)
        rois = rois.reshape(b * s, 4)
    elif ins.get("RoisBatchId"):
        batch_ids = ins["RoisBatchId"][0].reshape(-1).astype(jnp.int32)
    else:
        batch_ids = jnp.zeros((rois.shape[0],), jnp.int32)
    ph, pw = attrs["pooled_height"], attrs["pooled_width"]
    scale = attrs.get("spatial_scale", 1.0)
    H, W = x.shape[2], x.shape[3]

    def pool_one(roi, bid):
        x1, y1, x2, y2 = jnp.round(roi * scale)
        h = jnp.maximum(y2 - y1 + 1, 1.0)
        w = jnp.maximum(x2 - x1 + 1, 1.0)
        ys = jnp.linspace(0, 1, ph + 1) * h + y1
        xs = jnp.linspace(0, 1, pw + 1) * w + x1
        img = x[bid]  # [C, H, W]
        rows = jnp.arange(H)[None, :]
        cols = jnp.arange(W)[None, :]
        rmask = (rows >= ys[:-1, None]) & (rows < jnp.maximum(ys[1:, None],
                                                              ys[:-1, None] + 1))
        cmask = (cols >= xs[:-1, None]) & (cols < jnp.maximum(xs[1:, None],
                                                              xs[:-1, None] + 1))
        m = rmask[:, None, :, None] & cmask[None, :, None, :]  # ph pw H W
        vals = jnp.where(m[None], img[:, None, None, :, :], -jnp.inf)
        maxed = vals.max(axis=(3, 4))  # [C, ph, pw]
        # empty bins (roi clipped past the feature map) pool to 0 like
        # the reference (is_empty path in roi_pool_op.h) — never -inf
        empty = ~jnp.any(m, axis=(2, 3))  # [ph, pw]
        return jnp.where(empty[None], 0.0, maxed)

    out = jax.vmap(pool_one)(rois.astype(jnp.float32), batch_ids)
    return {"Out": [out], "Argmax": [jnp.zeros_like(out, dtype=canonical_int())]}


@register_op("random_crop", stateful=True)
def _random_crop(ctx, ins, attrs):
    x = ins["X"][0]
    shape = attrs["shape"]  # crop shape for trailing dims
    lead = x.ndim - len(shape)
    key = ctx.next_key()
    starts = []
    for i, s in enumerate(shape):
        limit = x.shape[lead + i] - s
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, max(limit, 0) + 1))
    start_idx = [jnp.asarray(0)] * lead + starts
    out = lax.dynamic_slice(x, start_idx, list(x.shape[:lead]) + list(shape))
    return {"Out": [out]}


@register_op("im2sequence", seq_aware=True)
def _im2sequence(ctx, ins, attrs):
    """Each image becomes one sequence of its oh*ow patches (the
    reference emits LoD [0, oh*ow, 2*oh*ow, ...]; here that is a
    SequenceBatch of equal lengths), so the output feeds sequence ops
    like dynamic_gru directly — the CRNN/OCR pipeline."""
    from ..core.sequence import SequenceBatch
    x = ins["X"][0]  # NCHW
    kh, kw = _pair(attrs["kernels"])
    sh, sw = _pair(attrs.get("strides", [1, 1]))
    pt, pl, pb, pr = (attrs.get("paddings", [0, 0, 0, 0]) + [0] * 4)[:4]
    x = jnp.pad(x, [(0, 0), (0, 0), (pt, pb), (pl, pr)])
    n, c, h, w = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), "VALID",
        dimension_numbers=lax.conv_dimension_numbers(
            x.shape, (1, c, kh, kw), ("NCHW", "OIHW", "NCHW")))
    # patches: [N, C*kh*kw, oh, ow] -> [N, oh*ow, C*kh*kw]
    out = patches.transpose(0, 2, 3, 1).reshape(n, oh * ow, c * kh * kw)
    lengths = jnp.full((n,), oh * ow, jnp.int32)
    return {"Out": [SequenceBatch(out, lengths)]}


# ---------------------------------------------------------------------------
# hierarchical sigmoid / NCE / row_conv
# ---------------------------------------------------------------------------


@register_op("hierarchical_sigmoid")
def _hsigmoid(ctx, ins, attrs):
    """Complete-binary-tree hsigmoid: precompute static code/path tables
    (host-side numpy, embedded as constants) and contract densely."""
    x, label, w = ins["X"][0], ins["Label"][0], ins["W"][0]
    num_classes = attrs["num_classes"]
    depth = int(np.ceil(np.log2(num_classes)))
    # node ids along the path from root for each class (heap layout)
    codes = np.zeros((num_classes, depth), np.int32)   # inner-node index
    signs = np.zeros((num_classes, depth), np.float32)  # +1 left, 0 pad
    valid = np.zeros((num_classes, depth), np.float32)
    for c in range(num_classes):
        node = c + num_classes  # leaves start at num_classes in heap
        path = []
        while node > 1:
            parent = node // 2
            path.append((parent - 1, 1.0 if node % 2 == 0 else 0.0))
            node = parent
        for d, (nid, bit) in enumerate(reversed(path)):
            if nid < num_classes - 1:
                codes[c, d] = nid
                signs[c, d] = bit
                valid[c, d] = 1.0
    codes_t, signs_t, valid_t = map(jnp.asarray, (codes, signs, valid))
    lbl = label.reshape(-1).astype(jnp.int32)
    node_ids = codes_t[lbl]          # [B, depth]
    bit = signs_t[lbl]               # [B, depth]
    msk = valid_t[lbl]
    wsel = w[node_ids]               # [B, depth, dim]
    logits = jnp.einsum("bd,bkd->bk", x, wsel)
    if ins.get("Bias"):
        logits = logits + ins["Bias"][0][node_ids]
    # bit==1 -> sigmoid(logit), else sigmoid(-logit); NLL over path
    ll = bit * jax.nn.log_sigmoid(logits) + (1 - bit) * jax.nn.log_sigmoid(-logits)
    return {"Out": [(-jnp.sum(ll * msk, axis=1, keepdims=True))]}


@register_op("nce", stateful=True)
def _nce(ctx, ins, attrs):
    x, label, w = ins["Input"][0], ins["Label"][0], ins["Weight"][0]
    k = attrs.get("num_neg_samples", 10)
    n = attrs["num_total_classes"]
    lbl = label.reshape(-1).astype(jnp.int32)
    neg = jax.random.randint(ctx.next_key(), (x.shape[0], k), 0, n)
    ids = jnp.concatenate([lbl[:, None], neg], axis=1)  # [B, 1+k]
    wsel = w[ids]                                       # [B, 1+k, dim]
    logits = jnp.einsum("bd,bkd->bk", x, wsel)
    if ins.get("Bias"):
        logits = logits + ins["Bias"][0][ids]
    # NCE with uniform noise: P_n = 1/n
    log_noise = jnp.log(jnp.asarray(k / n, dtype=x.dtype))
    adjusted = logits - log_noise
    lbls = jnp.concatenate([jnp.ones((x.shape[0], 1)),
                            jnp.zeros((x.shape[0], k))], axis=1)
    loss = jnp.maximum(adjusted, 0) - adjusted * lbls + \
        jax.nn.softplus(-jnp.abs(adjusted))
    out = jnp.sum(loss, axis=1, keepdims=True)
    if ins.get("SampleWeight"):
        out = out * ins["SampleWeight"][0].reshape(-1, 1)
    return {"Cost": [out]}


@register_op("row_conv")
def _row_conv(ctx, ins, attrs):
    x, f = ins["X"][0], ins["Filter"][0]  # x [B,T,D], f [ctx+1, D]
    k = f.shape[0]
    padded = jnp.pad(x, [(0, 0), (0, k - 1), (0, 0)])
    out = sum(padded[:, i:i + x.shape[1], :] * f[i] for i in range(k))
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# Static shape/dtype inference rules (analysis/infer.py engine) — pure
# shape arithmetic colocated with the lowerings above, the reference's
# InferShape-on-the-op pairing.
# ---------------------------------------------------------------------------
from ..analysis.infer import (InferError, VarInfo, first_in,  # noqa: E402
                              same_as)
from ..core.registry import register_infer  # noqa: E402


def _conv_dim(i, k, p, s, d=1):
    if i < 0:
        return -1
    eff = (k - 1) * d + 1
    return (i + 2 * p - eff) // s + 1


def _infer_conv2d(op, ins, attrs):
    x, w = first_in(ins, "Input"), first_in(ins, "Filter")
    if x.shape is None or w.shape is None or len(x.shape) != 4 \
            or len(w.shape) != 4:
        return {"Output": [VarInfo(None, x.dtype)]}
    strides = attrs.get("strides", [1, 1])
    pads = attrs.get("paddings", [0, 0])
    dil = attrs.get("dilations", [1, 1])
    groups = attrs.get("groups", 1) or 1
    fmt = attrs.get("data_format", attrs.get("data_layout", "NCHW"))
    n, c, h, wd = (x.shape if fmt == "NCHW"
                   else (x.shape[0], x.shape[3], x.shape[1], x.shape[2]))
    cout, cin_g, kh, kw = w.shape
    if x.confident and w.confident and c >= 0 \
            and c != cin_g * groups:
        raise InferError(
            f"conv2d channel mismatch: input has {c} channels "
            f"({fmt}) but filter {w.shape} expects "
            f"{cin_g * groups} (groups={groups})")
    oh = _conv_dim(h, kh, pads[0], strides[0], dil[0])
    ow = _conv_dim(wd, kw, pads[1], strides[1], dil[1])
    shape = (n, cout, oh, ow) if fmt == "NCHW" else (n, oh, ow, cout)
    return {"Output": [VarInfo(shape, x.dtype,
                               confident=x.confident and w.confident)]}


register_infer("conv2d")(_infer_conv2d)
register_infer("depthwise_conv2d")(_infer_conv2d)


def _deconv_dim(i, k, p, s, d=1):
    if i < 0:
        return -1
    eff = (k - 1) * d + 1
    return (i - 1) * s + eff - 2 * p


@register_infer("conv2d_transpose")
def _infer_conv2d_transpose(op, ins, attrs):
    x, w = first_in(ins, "Input"), first_in(ins, "Filter")
    if x.shape is None or w.shape is None or len(x.shape) != 4 \
            or len(w.shape) != 4:
        return {"Output": [VarInfo(None, x.dtype)]}
    strides = attrs.get("strides", [1, 1])
    pads = attrs.get("paddings", [0, 0])
    dil = attrs.get("dilations", [1, 1])
    groups = attrs.get("groups", 1) or 1
    fmt = attrs.get("data_format", attrs.get("data_layout", "NCHW"))
    n, c, h, wd = (x.shape if fmt == "NCHW"
                   else (x.shape[0], x.shape[3], x.shape[1], x.shape[2]))
    cin, cout_g, kh, kw = w.shape   # fluid deconv filter [cin, cout/g,*]
    cout = cout_g * groups
    oh = _deconv_dim(h, kh, pads[0], strides[0], dil[0])
    ow = _deconv_dim(wd, kw, pads[1], strides[1], dil[1])
    shape = (n, cout, oh, ow) if fmt == "NCHW" else (n, oh, ow, cout)
    return {"Output": [VarInfo(shape, x.dtype,
                               confident=x.confident and w.confident)]}


def _pool_dim(i, k, p, s, ceil_mode):
    if i < 0:
        return -1
    num = i + 2 * p - k
    return (num + s - 1) // s + 1 if ceil_mode else num // s + 1


@register_infer("pool2d")
def _infer_pool2d(op, ins, attrs):
    x = first_in(ins, "X")
    if x.shape is None or len(x.shape) != 4:
        return {"Out": [VarInfo(None, x.dtype)]}
    fmt = attrs.get("data_format", "NCHW")
    n, c, h, w = (x.shape if fmt == "NCHW"
                  else (x.shape[0], x.shape[3], x.shape[1], x.shape[2]))
    if attrs.get("global_pooling", False):
        oh = ow = 1
    else:
        ksize = attrs.get("ksize", [2, 2])
        strides = attrs.get("strides", [1, 1])
        pads = attrs.get("paddings", [0, 0])
        ksize = ksize if isinstance(ksize, (list, tuple)) else [ksize] * 2
        strides = strides if isinstance(strides, (list, tuple)) \
            else [strides] * 2
        pads = pads if isinstance(pads, (list, tuple)) else [pads] * 2
        cm = attrs.get("ceil_mode", False)
        oh = _pool_dim(h, ksize[0], pads[0], strides[0], cm)
        ow = _pool_dim(w, ksize[1], pads[1], strides[1], cm)
    shape = (n, c, oh, ow) if fmt == "NCHW" else (n, oh, ow, c)
    return {"Out": [VarInfo(shape, x.dtype, confident=x.confident)]}


@register_infer("batch_norm")
def _infer_batch_norm(op, ins, attrs):
    x, mean = first_in(ins, "X"), first_in(ins, "Mean")
    stat = VarInfo(mean.shape, "float32", confident=mean.confident)
    return {"Y": [same_as(x)], "MeanOut": [stat], "VarianceOut": [stat],
            "SavedMean": [stat], "SavedVariance": [stat]}


@register_infer("layer_norm")
def _infer_layer_norm(op, ins, attrs):
    return {"Y": [same_as(first_in(ins, "X"))]}


@register_infer("group_norm")
def _infer_group_norm(op, ins, attrs):
    return {"Y": [same_as(first_in(ins, "X"))]}


@register_infer("lrn")
def _infer_lrn(op, ins, attrs):
    return {"Out": [same_as(first_in(ins, "X"))]}


@register_infer("lookup_table")
def _infer_lookup_table(op, ins, attrs):
    w, ids = first_in(ins, "W"), first_in(ins, "Ids")
    emb = w.shape[-1] if w.shape is not None and len(w.shape) else -1
    if ids.shape is None:
        return {"Out": [VarInfo(None, w.dtype, ids.lod_level)]}
    base = ids.shape[:-1] if ids.shape and ids.shape[-1] == 1 \
        else ids.shape
    return {"Out": [VarInfo(base + (emb,), w.dtype, ids.lod_level,
                            confident=w.confident and ids.confident)]}


@register_infer("dropout")
def _infer_dropout(op, ins, attrs):
    x = first_in(ins, "X")
    return {"Out": [same_as(x)], "Mask": [same_as(x)]}


def _loss_shape(x):
    """[N, ..., D] → [N, ..., 1] per-row loss."""
    if x.shape is None:
        return None
    return x.shape[:-1] + (1,)


@register_infer("cross_entropy")
def _infer_cross_entropy(op, ins, attrs):
    x = first_in(ins, "X")
    return {"Y": [VarInfo(_loss_shape(x), x.dtype,
                          confident=x.confident)]}


@register_infer("softmax_with_cross_entropy")
def _infer_softmax_ce(op, ins, attrs):
    logits = first_in(ins, "Logits")
    return {"Loss": [VarInfo(_loss_shape(logits), logits.dtype,
                             confident=logits.confident)],
            "Softmax": [same_as(logits)]}


@register_infer("sigmoid_cross_entropy_with_logits")
def _infer_sigmoid_ce(op, ins, attrs):
    return {"Out": [same_as(first_in(ins, "X"))]}


@register_infer("square_error_cost")
def _infer_square_error(op, ins, attrs):
    return {"Out": [same_as(first_in(ins, "X"))]}


@register_infer("accuracy")
def _infer_accuracy(op, ins, attrs):
    conf = first_in(ins, "Indices").confident
    return {"Accuracy": [VarInfo((1,), "float32", confident=conf)],
            "Correct": [VarInfo((1,), "int32", confident=conf)],
            "Total": [VarInfo((1,), "int32", confident=conf)]}


# ---------------------------------------------------------------------------
# Numerics transfer functions (analysis/numcheck.py) — value-range and
# finiteness behavior, colocated like the infer rules above. Pure
# interval arithmetic, no jax.
# ---------------------------------------------------------------------------
import math  # noqa: E402

from ..analysis.infer import dim_prod as _nc_dim_prod  # noqa: E402
from ..analysis.numcheck import (interval, num_first)  # noqa: E402
from ..core.registry import register_numerics  # noqa: E402


def _num_conv(op, ins, attrs):
    """Accumulate-width aware: |out| ≤ k·max|x|·max|w| with
    k = (C_in/groups)·kh·kw contraction taps (+ bias join)."""
    x, w = num_first(ins, "Input"), num_first(ins, "Filter")
    if w.shape is None or len(w.shape) != 4 or x.mag == math.inf \
            or w.mag == math.inf:
        out = interval(-math.inf, math.inf)
    else:
        k = _nc_dim_prod(w.shape[1:])
        if k < 0:
            out = interval(-math.inf, math.inf)
        else:
            m = k * x.mag * w.mag
            b = num_first(ins, "Bias")
            if ins.get("Bias"):
                m += b.mag
                if b.mag == math.inf:
                    m = math.inf
            out = interval(-m, m)
    return {"Output": [out]}


register_numerics("conv2d")(_num_conv)
register_numerics("depthwise_conv2d")(_num_conv)
register_numerics("conv2d_transpose")(_num_conv)


@register_numerics("pool2d")
def _num_pool2d(op, ins, attrs):
    # max pool selects, avg pool averages: both stay inside X's range
    x = num_first(ins, "X")
    return {"Out": [interval(x.lo, x.hi)]}


register_numerics("pool3d")(_num_pool2d)


@register_numerics("batch_norm")
def _num_batch_norm(op, ins, attrs):
    """(x-μ)/√(σ²+ε)·γ+β: ε>0 keeps the denominator away from 0, so Y
    is finite whenever the inputs are; the magnitude depends on the
    learned γ/β, which the seeds leave unbounded."""
    y = interval(-math.inf, math.inf)
    stat = interval(-math.inf, math.inf)
    var = interval(0.0, math.inf)
    return {"Y": [y], "MeanOut": [stat], "VarianceOut": [var],
            "SavedMean": [stat], "SavedVariance": [var]}


@register_numerics("layer_norm")
def _num_layer_norm(op, ins, attrs):
    return {"Y": [interval(-math.inf, math.inf)]}


@register_numerics("group_norm")
def _num_group_norm(op, ins, attrs):
    return {"Y": [interval(-math.inf, math.inf)]}


@register_numerics("lrn")
def _num_lrn(op, ins, attrs):
    # out = x / (k + α·Σx²)^β with k ≥ 1 by default: |out| ≤ |x|/k^β
    x = num_first(ins, "X")
    k = float(attrs.get("k", 1.0))
    if k <= 0:
        return None
    return {"Out": [interval(min(x.lo, 0.0), max(x.hi, 0.0))]}


@register_numerics("lookup_table")
def _num_lookup_table(op, ins, attrs):
    w = num_first(ins, "W")
    return {"Out": [interval(w.lo, w.hi)]}


@register_numerics("dropout")
def _num_dropout(op, ins, attrs):
    """Train: mask then 1/(1-p) upscale; eval: identity or (1-p)
    downscale. Either way the range is the (0-joined) input range
    scaled by at most 1/(1-p)."""
    x = num_first(ins, "X")
    p = float(attrs.get("dropout_prob", 0.5))
    s = 1.0 / max(1.0 - p, 1e-6)
    return {"Out": [interval(min(x.lo * s, 0.0), max(x.hi * s, 0.0))],
            "Mask": [interval(0.0, s)]}


@register_numerics("cross_entropy")
def _num_cross_entropy(op, ins, attrs):
    """-log(p + 1e-9) (the lowering's epsilon): bounded and finite for
    probability inputs p ∈ [0, 1]; unproven otherwise (a negative p
    would put the log over a non-positive argument)."""
    x = num_first(ins, "X")
    if x.lo >= 0.0:
        hi = -math.log(max(x.lo, 0.0) + 1e-9)
        lo = 0.0 if x.hi == math.inf else min(-math.log(x.hi + 1e-9),
                                              0.0)
        return {"Y": [interval(lo, hi)]}
    return {"Y": [interval(-math.inf, math.inf, finite=False)]}


@register_numerics("softmax_with_cross_entropy")
def _num_softmax_ce(op, ins, attrs):
    # stable log-softmax formulation: finite for finite logits; loss
    # magnitude bounded by the logit spread, which seeds leave open
    return {"Loss": [interval(0.0, math.inf)],
            "Softmax": [interval(0.0, 1.0)]}


@register_numerics("sigmoid_cross_entropy_with_logits")
def _num_sigmoid_ce(op, ins, attrs):
    return {"Out": [interval(0.0, math.inf)]}


@register_numerics("square_error_cost")
def _num_square_error(op, ins, attrs):
    x, y = num_first(ins, "X"), num_first(ins, "Label")
    d = max(abs(x.hi - y.lo), abs(y.hi - x.lo))
    return {"Out": [interval(0.0, d * d if d < math.inf else math.inf)]}


@register_numerics("accuracy")
def _num_accuracy(op, ins, attrs):
    return {"Accuracy": [interval(0.0, 1.0)],
            "Correct": [interval(0.0, math.inf)],
            "Total": [interval(0.0, math.inf)]}
