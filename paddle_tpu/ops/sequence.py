"""Sequence op lowering rules over SequenceBatch (padded + lengths).

Capability parity with paddle/fluid/operators/sequence_*.cc
(sequence_pool, sequence_softmax, sequence_expand, sequence_conv,
sequence_reshape, sequence_pad, sequence_mask, ...). The reference
iterates LoD offset tables on the host; here every op is a masked dense
computation over [batch, max_len, ...] that XLA vectorizes — the
TPU-native representation of variable-length data.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..core.registry import canonical_int, register_op
from ..core.sequence import SequenceBatch, sequence_mask_from_lengths


def _as_seq(v):
    if isinstance(v, SequenceBatch):
        return v
    raise TypeError(
        f"op expected a SequenceBatch (lod_level>0 input), got {type(v)}; "
        "feed variable-length data via DataFeeder / to_sequence_batch")


@register_op("sequence_pool", seq_aware=True)
def _sequence_pool(ctx, ins, attrs):
    seq = _as_seq(ins["X"][0])
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    if getattr(seq, "lod_level", 1) == 2:
        # multi-level LoD: pooling consumes the INNERMOST level
        # (reference sequence_pool_op semantics — the result keeps the
        # remaining levels): [B, S, T, ...] + lengths [B, S] pools over
        # T into a level-1 SequenceBatch [B, S, ...] whose lengths are
        # the outer level's subsequence counts
        b, s = seq.data.shape[:2]
        inner = SequenceBatch(
            seq.data.reshape((b * s,) + seq.data.shape[2:]),
            seq.lengths.reshape(b * s))
        pooled = _pool_level1(inner, ptype)
        out = SequenceBatch(pooled.reshape((b, s) + pooled.shape[1:]),
                            seq.sub_counts())
        if ptype == "MAX":
            im = inner.mask(inner.dtype).reshape(
                inner.data.shape[:2] + (1,) * (inner.data.ndim - 2))
            mi = jnp.argmax(jnp.where(im > 0, inner.data, -jnp.inf),
                            axis=1).astype(jnp.int32)
            max_index = mi.reshape((b, s) + mi.shape[1:])
        else:
            max_index = jnp.zeros(out.data.shape, jnp.int32)
        return {"Out": [out], "MaxIndex": [max_index]}
    x, lengths = seq.data, seq.lengths
    out = _pool_level1(seq, ptype)
    mask = sequence_mask_from_lengths(lengths, x.shape[1], x.dtype)
    m = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    max_index = jnp.argmax(jnp.where(m > 0, x, -jnp.inf), axis=1) \
        if ptype == "MAX" else jnp.zeros(out.shape, jnp.int32)
    return {"Out": [out], "MaxIndex": [max_index]}


def _pool_level1(seq, ptype):
    """Masked pooling over the time axis of a level-1 SequenceBatch."""
    x, lengths = seq.data, seq.lengths
    mask = sequence_mask_from_lengths(lengths, x.shape[1], x.dtype)
    mshape = mask.shape + (1,) * (x.ndim - 2)
    m = mask.reshape(mshape)
    denom = jnp.maximum(lengths.astype(x.dtype), 1).reshape(
        (-1,) + (1,) * (x.ndim - 2))
    if ptype == "AVERAGE":
        out = jnp.sum(x * m, axis=1) / denom
    elif ptype == "SUM":
        out = jnp.sum(x * m, axis=1)
    elif ptype == "SQRT":
        out = jnp.sum(x * m, axis=1) / jnp.sqrt(denom)
    elif ptype == "MAX":
        out = jnp.max(jnp.where(m > 0, x, -jnp.inf), axis=1)
        out = jnp.where(lengths.reshape(denom.shape) > 0, out, 0.0)
    elif ptype == "LAST":
        idx = jnp.maximum(lengths - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1)[:, 0]
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError(f"unknown pooltype {ptype}")
    return out


@register_op("sequence_first_step", seq_aware=True)
def _sequence_first_step(ctx, ins, attrs):
    seq = _as_seq(ins["X"][0])
    if getattr(seq, "lod_level", 1) == 2:
        # innermost level: first timestep of each subsequence → level-1
        return {"Out": [SequenceBatch(seq.data[:, :, 0],
                                      seq.sub_counts())]}
    return {"Out": [seq.data[:, 0]]}


@register_op("sequence_last_step", seq_aware=True)
def _sequence_last_step(ctx, ins, attrs):
    seq = _as_seq(ins["X"][0])
    if getattr(seq, "lod_level", 1) == 2:
        idx = jnp.maximum(seq.lengths - 1, 0)
        out = jnp.take_along_axis(
            seq.data,
            idx.reshape(idx.shape + (1,) * (seq.data.ndim - 2)),
            axis=2)[:, :, 0]
        return {"Out": [SequenceBatch(out, seq.sub_counts())]}
    idx = jnp.maximum(seq.lengths - 1, 0)
    out = jnp.take_along_axis(
        seq.data, idx.reshape((-1, 1) + (1,) * (seq.data.ndim - 2)),
        axis=1)[:, 0]
    return {"Out": [out]}


@register_op("sequence_softmax", seq_aware=True)
def _sequence_softmax(ctx, ins, attrs):
    seq = _as_seq(ins["X"][0])
    x, lengths = seq.data, seq.lengths
    mask = sequence_mask_from_lengths(lengths, x.shape[1], jnp.bool_)
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    z = jnp.where(mask, x, -jnp.inf)
    out = jax.nn.softmax(z, axis=1)
    out = jnp.where(mask, out, 0.0)
    return {"Out": [SequenceBatch(out, lengths)]}


@register_op("sequence_expand", seq_aware=True)
def _sequence_expand(ctx, ins, attrs):
    """x broadcast along y's reference LoD level (padded analogue of
    LoD-expand, reference sequence_expand_op.cc; multi-level ref_level
    semantics per reference layers/nn.py:2595).

    Level-1 y: x [B, D] → [B, T, D] with y's lengths. Level-2 y:
    ``ref_level=0`` expands one x row per OUTER sequence across its
    subsequences ([B, D] → level-1 [B, S, D] with subseq counts as
    lengths); ``ref_level=1``/``-1`` expands one x row per SUBSEQUENCE
    across its timesteps (x level-1 [B, S, D] → level-2 [B, S, T, D]
    with y's inner lengths)."""
    x = ins["X"][0]
    y = _as_seq(ins["Y"][0])
    xd = x.data if isinstance(x, SequenceBatch) else x
    ref_level = int(attrs.get("ref_level", -1))
    if getattr(y, "lod_level", 1) == 2:
        if ref_level == 0:
            out = jnp.broadcast_to(
                xd[:, None, :],
                (xd.shape[0], y.data.shape[1], xd.shape[-1]))
            return {"Out": [SequenceBatch(out, y.sub_counts())]}
        # ref_level 1 (or -1, the innermost): per-subsequence rows
        out = jnp.broadcast_to(
            xd[:, :, None, :],
            xd.shape[:2] + (y.data.shape[2], xd.shape[-1]))
        return {"Out": [SequenceBatch(out, y.lengths,
                                      y.outer_counts)]}
    if xd.ndim == 2:
        out = jnp.broadcast_to(xd[:, None, :],
                               (xd.shape[0], y.data.shape[1], xd.shape[1]))
    else:
        out = xd
    return {"Out": [SequenceBatch(out, y.lengths)]}


@register_op("sequence_conv", seq_aware=True)
def _sequence_conv(ctx, ins, attrs):
    """Context-window conv over time (reference sequence_conv_op.cc):
    filter [ctx_len * D, num_filters], zero-padded outside the sequence."""
    seq = _as_seq(ins["X"][0])
    w = ins["Filter"][0]
    ctx_len = attrs.get("contextLength", 3)
    ctx_start = attrs.get("contextStart", -(ctx_len // 2))
    x, lengths = seq.data, seq.lengths
    b, t, d = x.shape
    mask = sequence_mask_from_lengths(lengths, t, x.dtype)[..., None]
    xm = x * mask
    cols = []
    for i in range(ctx_len):
        off = ctx_start + i
        if off < 0:
            shifted = jnp.pad(xm, ((0, 0), (-off, 0), (0, 0)))[:, :t]
        elif off > 0:
            shifted = jnp.pad(xm, ((0, 0), (0, off), (0, 0)))[:, off:]
        else:
            shifted = xm
        cols.append(shifted)
    stacked = jnp.concatenate(cols, axis=-1)          # [B, T, ctx*D]
    out = jnp.einsum("btc,cf->btf", stacked, w)
    out = out * mask
    return {"Out": [SequenceBatch(out, lengths)]}


@register_op("sequence_reshape", seq_aware=True)
def _sequence_reshape(ctx, ins, attrs):
    seq = _as_seq(ins["X"][0])
    new_dim = attrs["new_dim"]
    b, t, d = seq.data.shape
    if d % new_dim == 0:
        k = d // new_dim
        out = seq.data.reshape(b, t * k, new_dim)
        lengths = seq.lengths * k
    elif new_dim % d == 0:
        ratio = new_dim // d
        if t % ratio:
            pad = ratio - t % ratio
            data = jnp.pad(seq.data, ((0, 0), (0, pad), (0, 0)))
            t += pad
        else:
            data = seq.data
        out = data.reshape(b, t // ratio, new_dim)
        # reference requires each row's len*d divisible by new_dim; ceil
        # keeps partially-filled tail rows addressable either way
        lengths = (seq.lengths + ratio - 1) // ratio
    else:
        raise ValueError(
            f"sequence_reshape: dim {d} and new_dim {new_dim} must divide "
            "one another")
    return {"Out": [SequenceBatch(out, lengths)]}


@register_op("sequence_concat", seq_aware=True)
def _sequence_concat(ctx, ins, attrs):
    """Time-axis concatenation per row (reference sequence_concat_op.h
    default level): row i becomes x1[i,:l1], x2[i,:l2], ..., padding."""
    seqs = [_as_seq(v) for v in ins["X"]]
    total_t = sum(s.data.shape[1] for s in seqs)
    b = seqs[0].data.shape[0]
    tail = seqs[0].data.shape[2:]
    out = jnp.zeros((b, total_t) + tail, seqs[0].data.dtype)
    lengths = jnp.zeros((b,), jnp.int32)

    def place(out_row, offset, row):
        idx = (offset,) + (0,) * (row.ndim - 1)
        return jax.lax.dynamic_update_slice(out_row, row, idx)

    for s in seqs:
        mask = sequence_mask_from_lengths(s.lengths, s.data.shape[1],
                                          s.data.dtype)
        clean = s.data * mask.reshape(mask.shape + (1,) *
                                      (s.data.ndim - 2))
        out = jax.vmap(place)(out, lengths, clean)
        lengths = lengths + s.lengths
    # zero anything beyond the summed lengths (pad rows of later inputs
    # may have overwritten zeros with zeros already, but be exact)
    final_mask = sequence_mask_from_lengths(lengths, total_t, out.dtype)
    out = out * final_mask.reshape(final_mask.shape + (1,) *
                                   (out.ndim - 2))
    return {"Out": [SequenceBatch(out, lengths)]}


@register_op("sequence_slice", seq_aware=True)
def _sequence_slice(ctx, ins, attrs):
    seq = _as_seq(ins["X"][0])
    offset = ins["Offset"][0].reshape(-1)
    length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    t = seq.data.shape[1]
    # roll each row so its slice starts at 0, then zero the stale tail
    rolled = jax.vmap(lambda row, off: jnp.roll(row, -off, axis=0))(
        seq.data, offset)
    mask = sequence_mask_from_lengths(length, t, rolled.dtype)
    rolled = rolled * mask.reshape(mask.shape + (1,) * (rolled.ndim - 2))
    return {"Out": [SequenceBatch(rolled, length)]}


@register_op("sequence_enumerate", seq_aware=True)
def _sequence_enumerate(ctx, ins, attrs):
    seq = _as_seq(ins["X"][0])
    win = attrs.get("win_size", 2)
    pad = attrs.get("pad_value", 0)
    x = seq.data  # [B, T] ids
    if x.ndim == 3 and x.shape[-1] == 1:
        x = x[..., 0]
    t = x.shape[1]
    cols = []
    for i in range(win):
        shifted = jnp.pad(x, ((0, 0), (0, i)),
                          constant_values=pad)[:, i:i + t]
        valid = (jnp.arange(t)[None, :] + i) < seq.lengths[:, None]
        cols.append(jnp.where(valid, shifted, pad))
    out = jnp.stack(cols, axis=-1)  # [B, T, win]
    return {"Out": [SequenceBatch(out, seq.lengths)]}


@register_op("sequence_erase", seq_aware=True)
def _sequence_erase(ctx, ins, attrs):
    """Marks erased tokens by compacting valid ones to the front
    (padded analogue of sequence_erase_op.cc)."""
    seq = _as_seq(ins["X"][0])
    tokens = attrs.get("tokens", [])
    x = seq.data
    keep = jnp.ones(x.shape[:2], bool)
    for tok in tokens:
        keep &= (x != tok) if x.ndim == 2 else (x[..., 0] != tok)
    keep &= sequence_mask_from_lengths(seq.lengths, x.shape[1], jnp.bool_)
    # stable compaction via argsort on (not keep)
    order = jnp.argsort(~keep, axis=1, stable=True)
    data = jnp.take_along_axis(
        x, order.reshape(order.shape + (1,) * (x.ndim - 2)), axis=1)
    lengths = keep.sum(axis=1).astype(jnp.int32)
    mask = sequence_mask_from_lengths(lengths, x.shape[1], x.dtype)
    data = data * mask.reshape(mask.shape + (1,) * (x.ndim - 2)).astype(
        data.dtype)
    return {"Out": [SequenceBatch(data, lengths)]}


@register_op("sequence_mask", seq_aware=True)
def _sequence_mask(ctx, ins, attrs):
    x = ins["X"][0]
    lengths = x.lengths if isinstance(x, SequenceBatch) else x.reshape(-1)
    maxlen = attrs.get("maxlen", -1)
    if maxlen is None or maxlen < 0:
        raise ValueError(
            "sequence_mask needs a static maxlen under XLA; pass maxlen=")
    dt = jnp.dtype(attrs.get("out_dtype", "int64"))
    return {"Y": [sequence_mask_from_lengths(lengths.astype(jnp.int32),
                                             maxlen, dt)]}


@register_op("sequence_pad", seq_aware=True)
def _sequence_pad(ctx, ins, attrs):
    seq = _as_seq(ins["X"][0])
    return {"Out": [seq.data],
            "Length": [seq.lengths.astype(canonical_int())]}


@register_op("sequence_unpad", seq_aware=True)
def _sequence_unpad(ctx, ins, attrs):
    x = ins["X"][0]
    lengths = ins["Length"][0].reshape(-1).astype(jnp.int32)
    return {"Out": [SequenceBatch(x, lengths)]}


@register_op("lod_reset", seq_aware=True)
def _lod_reset(ctx, ins, attrs):
    x = ins["X"][0]
    data = x.data if isinstance(x, SequenceBatch) else x
    if ins.get("Y"):
        y = ins["Y"][0]
        lengths = y.lengths if isinstance(y, SequenceBatch) \
            else y.reshape(-1).astype(jnp.int32)
        return {"Out": [SequenceBatch(data, lengths)]}
    return {"Out": [data]}


@register_op("lod_array_length", seq_aware=True)
def _lod_array_length(ctx, ins, attrs):
    arr = ins["X"][0]
    return {"Out": [jnp.asarray([len(arr)], canonical_int())]}


# ---------------------------------------------------------------------------
# CTC / edit distance (reference warpctc_op.cc, edit_distance_op.cc)
# ---------------------------------------------------------------------------


@register_op("edit_distance", seq_aware=True)
def _edit_distance(ctx, ins, attrs):
    hyp = _as_seq(ins["Hyps"][0])
    ref = _as_seq(ins["Refs"][0])
    normalized = attrs.get("normalized", True)

    h = hyp.data if hyp.data.ndim == 2 else hyp.data[..., 0]
    r = ref.data if ref.data.ndim == 2 else ref.data[..., 0]

    def one(hrow, hlen, rrow, rlen):
        tm, tn = h.shape[1], r.shape[1]

        def row_step(prev_row, i):
            def col_step(left, j):
                cost = jnp.where(hrow[i] == rrow[j], 0, 1)
                val = jnp.minimum(jnp.minimum(left + 1, prev_row[j + 1] + 1),
                                  prev_row[j] + cost)
                return val, val

            _, vals = jax.lax.scan(col_step, jnp.asarray(i + 1, jnp.int32),
                                   jnp.arange(tn))
            new_row = jnp.concatenate(
                [jnp.asarray(i + 1, jnp.int32).reshape(1), vals])
            new_row = jnp.where(i < hlen, new_row, prev_row)
            return new_row, None

        row0 = jnp.arange(tn + 1, dtype=jnp.int32)
        final, _ = jax.lax.scan(row_step, row0, jnp.arange(tm))
        return final[rlen]

    d = jax.vmap(one)(h.astype(jnp.int32), hyp.lengths,
                      r.astype(jnp.int32), ref.lengths)
    d = d.astype(jnp.float32)
    if normalized:
        d = d / jnp.maximum(ref.lengths.astype(jnp.float32), 1.0)
    return {"Out": [d.reshape(-1, 1)],
            "SequenceNum": [jnp.asarray([h.shape[0]], canonical_int())]}
