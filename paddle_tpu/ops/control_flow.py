"""Control-flow op lowering rules: while, if_else, conditional_block.

Capability parity with paddle/fluid/operators/{while_op, conditional_
block_op}.cc. The reference interprets sub-blocks with a scoped
executor; here sub-blocks lower into lax.while_loop / lax.cond so the
whole loop compiles into the XLA program — the only legal form of
data-dependent control flow on TPU.
"""
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


@register_op("while")
def _while(ctx, ins, attrs):
    """attrs: sub_block, condition (var name), carry_names (vars the body
    updates that live on after the loop). The body must recompute the
    condition variable each iteration."""
    from ..core.lowering import Env

    sub_block = attrs["sub_block"]
    cond_name = attrs["condition"]
    carry_names = list(attrs["carry_names"])
    outer_env = ctx.env

    init = tuple(outer_env[n] for n in carry_names)
    cond0 = outer_env[cond_name]

    def cond_fn(state):
        cond_val, _ = state
        return jnp.reshape(cond_val, ()).astype(bool)

    def body_fn(state):
        cond_val, carries = state
        env = Env(parent=outer_env)
        for n, v in zip(carry_names, carries):
            env[n] = v
        env[cond_name] = cond_val
        ctx.eval_block(sub_block, env)
        new_carries = tuple(env[n] for n in carry_names)
        return env[cond_name], new_carries

    max_iters = int(attrs.get("max_iters", 0) or 0)
    if max_iters > 0:
        # bounded, DIFFERENTIABLE form (the WhileGradOp equivalent,
        # reference while_op.cc:101): a lax.scan of exactly max_iters
        # steps; once the condition goes false every later step keeps
        # the carry unchanged (masked select), so values match the
        # unbounded loop whenever it finishes within the bound — and
        # reverse-mode AD flows through scan's fixed-length tape.
        # CONSTRAINT (the classic where-grad pitfall): the body still
        # EXECUTES on the frozen carry after the condition goes false;
        # only its result is discarded. A body op that is numerically
        # undefined past the natural exit (1/(n-i), log of a shrinking
        # value) yields NaN in the dead branch, and d/dx jnp.where
        # propagates NaN gradients even though the forward value is
        # right. Bodies must stay finite on a frozen carry — see
        # layers.While docs; guard hazardous denominators in the body
        # (e.g. add a where/maximum there) if needed.
        def scan_body(state, _):
            cond_val, carries = state
            live = jnp.reshape(cond_val, ()).astype(bool)
            new_cond, new_carries = body_fn((cond_val, carries))
            sel = tuple(jnp.where(live, nv, ov)
                        for nv, ov in zip(new_carries, carries))
            kept_cond = jnp.where(live, jnp.reshape(new_cond, ()),
                                  False).reshape(cond_val.shape
                                                 ).astype(cond_val.dtype)
            return (kept_cond, sel), None

        (final_cond, final), _ = lax.scan(scan_body, (cond0, init),
                                          None, length=max_iters)
    else:
        final_cond, final = lax.while_loop(cond_fn, body_fn,
                                           (cond0, init))
    out = {"Out": [final[i] for i in range(len(carry_names))]}
    out["Condition"] = [final_cond]
    return out


@register_op("if_else")
def _if_else(ctx, ins, attrs):
    """attrs: true_block, false_block, out_names (vars both branches
    write). Scalar condition → lax.cond."""
    from ..core.lowering import Env

    cond = jnp.reshape(ins["Cond"][0], ()).astype(bool)
    out_names = list(attrs["out_names"])
    outer_env = ctx.env

    def run(block):
        def fn(_):
            env = Env(parent=outer_env)
            ctx.eval_block(block, env)
            return tuple(env[n] for n in out_names)
        return fn

    outs = lax.cond(cond, run(attrs["true_block"]),
                    run(attrs["false_block"]), operand=None)
    return {"Out": list(outs)}


@register_op("select_input")
def _select_input(ctx, ins, attrs):
    mask = jnp.reshape(ins["Mask"][0], ()).astype(jnp.int32)
    stacked = jnp.stack(ins["X"], axis=0)
    return {"Out": [stacked[mask]]}


@register_op("print")
def _print(ctx, ins, attrs):
    import jax
    x = ins["X"][0]
    msg = attrs.get("message", "")
    jax.debug.print(msg + " {}", x)
    return {"Out": [x]}


@register_op("is_empty")
def _is_empty(ctx, ins, attrs):
    x = ins["X"][0]
    size = x.data.size if hasattr(x, "data") else x.size
    return {"Out": [jnp.asarray([size == 0])]}


@register_op("write_to_array")
def _write_to_array(ctx, ins, attrs):
    x = ins["X"][0]
    arr_name = ctx.op.output("Out")[0]
    arr = ctx.env.get(arr_name)
    arr = list(arr) if arr is not None else []
    arr.append(x)
    return {"Out": [arr]}


@register_op("read_from_array")
def _read_from_array(ctx, ins, attrs):
    arr = ins["X"][0]
    idx = ins["I"][0]
    stacked = jnp.stack(arr, axis=0)
    i = jnp.reshape(idx, ()).astype(jnp.int32)
    return {"Out": [lax.dynamic_index_in_dim(stacked, i, axis=0,
                                             keepdims=False)]}
