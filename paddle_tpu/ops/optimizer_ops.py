"""Optimizer update op lowering rules.

Capability parity with paddle/fluid/operators/{sgd,momentum,adam,adagrad,
adamax,adadelta,decayed_adagrad,rmsprop,ftrl}_op.cc. Each op consumes
Param/Grad/accumulator state and emits the functionally-updated tensors;
because they lower inside the same jitted program as forward+backward,
XLA fuses the whole optimizer sweep into the train step (no per-op
kernel launches, donated buffers update in place in HBM).
"""
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


def _lr(ins):
    return ins["LearningRate"][0].reshape(())


def _f32(*vals):
    """Upcast update ARITHMETIC to f32 — pair with :func:`_like` on
    every output so the STORED dtype never changes. Without the
    cast-back, the f32 learning-rate scalar silently promotes a bf16
    parameter update to f32: the executable materializes f32 copies of
    every weight (measured +21 GB of HBM traffic and a retrace-per-step
    on the dim-4096 bench) and the scope dtype flips.

    Note the limit of per-step f32 math: storing params/moments in bf16
    still ROUNDS each update to bf16 on write-back, so updates smaller
    than half a bf16 ulp of the value vanish. That is the inherent
    pure-bf16-training tradeoff; for full update fidelity keep f32
    params with bf16 COMPUTE (the amp transpiler — f32 master weights),
    or pass ``moment_dtype="float32"`` to AdamOptimizer for f32
    moments over bf16 params."""
    return tuple(None if v is None else v.astype(jnp.float32)
                 for v in vals)


def _like(val, ref):
    return val.astype(ref.dtype)


@register_op("sgd")
def _sgd(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    pf, gf = _f32(p, g)
    return {"ParamOut": [_like(pf - _lr(ins) * gf, p)]}


@register_op("momentum")
def _momentum(ctx, ins, attrs):
    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    mu = attrs.get("mu", 0.9)
    lr = _lr(ins)
    pf, gf, vf = _f32(p, g, v)
    v_out = mu * vf + gf
    if attrs.get("use_nesterov", False):
        p_out = pf - (gf + mu * v_out) * lr
    else:
        p_out = pf - lr * v_out
    return {"ParamOut": [_like(p_out, p)],
            "VelocityOut": [_like(v_out, v)]}


@register_op("adam")
def _adam(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1p = ins["Beta1Pow"][0].reshape(())
    b2p = ins["Beta2Pow"][0].reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(ins) * jnp.sqrt(1 - b2p) / (1 - b1p)
    pf, gf, m1f, m2f = _f32(p, g, m1, m2)
    m1o = b1 * m1f + (1 - b1) * gf
    m2o = b2 * m2f + (1 - b2) * jnp.square(gf)
    po = pf - lr * m1o / (jnp.sqrt(m2o) + eps)
    return {"ParamOut": [_like(po, p)], "Moment1Out": [_like(m1o, m1)],
            "Moment2Out": [_like(m2o, m2)]}


@register_op("adamax")
def _adamax(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m, inf = ins["Moment"][0], ins["InfNorm"][0]
    b1p = ins["Beta1Pow"][0].reshape(())
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    pf, gf, mf, inff = _f32(p, g, m, inf)
    mo = b1 * mf + (1 - b1) * gf
    info = jnp.maximum(b2 * inff, jnp.abs(gf))
    po = pf - (_lr(ins) / (1 - b1p)) * (mo / (info + eps))
    return {"ParamOut": [_like(po, p)], "MomentOut": [_like(mo, m)],
            "InfNormOut": [_like(info, inf)]}


@register_op("adagrad")
def _adagrad(ctx, ins, attrs):
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    eps = attrs.get("epsilon", 1e-6)
    pf, gf, mf = _f32(p, g, m)
    mo = mf + jnp.square(gf)
    po = pf - _lr(ins) * gf / (jnp.sqrt(mo) + eps)
    return {"ParamOut": [_like(po, p)], "MomentOut": [_like(mo, m)]}


@register_op("decayed_adagrad")
def _decayed_adagrad(ctx, ins, attrs):
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    pf, gf, mf = _f32(p, g, m)
    mo = decay * mf + (1 - decay) * jnp.square(gf)
    po = pf - _lr(ins) * gf / (jnp.sqrt(mo) + eps)
    return {"ParamOut": [_like(po, p)], "MomentOut": [_like(mo, m)]}


@register_op("adadelta")
def _adadelta(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    avg_sq_g, avg_sq_u = ins["AvgSquaredGrad"][0], ins["AvgSquaredUpdate"][0]
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    pf, gf, asgf, asuf = _f32(p, g, avg_sq_g, avg_sq_u)
    asg = rho * asgf + (1 - rho) * jnp.square(gf)
    update = -jnp.sqrt((asuf + eps) / (asg + eps)) * gf
    asu = rho * asuf + (1 - rho) * jnp.square(update)
    return {"ParamOut": [_like(pf + update, p)],
            "AvgSquaredGradOut": [_like(asg, avg_sq_g)],
            "AvgSquaredUpdateOut": [_like(asu, avg_sq_u)]}


@register_op("rmsprop")
def _rmsprop(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    ms, mom = ins["MeanSquare"][0], ins["Moment"][0]
    rho = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    mu = attrs.get("momentum", 0.0)
    lr = _lr(ins)
    pf, gf, msf, momf = _f32(p, g, ms, mom)
    if attrs.get("centered", False):
        mg = ins["MeanGrad"][0]
        mgf, = _f32(mg)
        mgo = rho * mgf + (1 - rho) * gf
        mso = rho * msf + (1 - rho) * jnp.square(gf)
        momo = mu * momf + lr * gf / jnp.sqrt(mso - jnp.square(mgo) + eps)
        return {"ParamOut": [_like(pf - momo, p)],
                "MeanSquareOut": [_like(mso, ms)],
                "MomentOut": [_like(momo, mom)],
                "MeanGradOut": [_like(mgo, mg)]}
    mso = rho * msf + (1 - rho) * jnp.square(gf)
    momo = mu * momf + lr * gf / jnp.sqrt(mso + eps)
    return {"ParamOut": [_like(pf - momo, p)],
            "MeanSquareOut": [_like(mso, ms)],
            "MomentOut": [_like(momo, mom)]}


@register_op("ftrl")
def _ftrl(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    sq, lin = ins["SquaredAccumulator"][0], ins["LinearAccumulator"][0]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    power = attrs.get("lr_power", -0.5)
    lr = _lr(ins)
    p, g, sq, lin = _f32(p, g, sq, lin)
    new_sq = sq + jnp.square(g)
    if power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -power) - jnp.power(sq, -power)) / lr
    new_lin = lin + g - sigma * p
    x = l1 * jnp.sign(new_lin) - new_lin
    if power == -0.5:
        y = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        y = jnp.power(new_sq, -power) / lr + 2 * l2
    po = jnp.where(jnp.abs(new_lin) > l1, x / y, 0.0)
    return {"ParamOut": [_like(po, ins["Param"][0])],
            "SquaredAccumOut": [_like(new_sq, ins["SquaredAccumulator"][0])],
            "LinearAccumOut": [_like(new_lin, ins["LinearAccumulator"][0])]}


@register_op("lamb")
def _lamb(ctx, ins, attrs):
    """LAMB (layer-adaptive Adam) — needed for large-batch TPU training;
    not in the reference op set but part of its capability envelope via
    contrib optimizers."""
    p, g = ins["Param"][0], ins["Grad"][0]
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    pf, gf, m1f, m2f = _f32(p, g, m1, m2)
    m1o = b1 * m1f + (1 - b1) * gf
    m2o = b2 * m2f + (1 - b2) * jnp.square(gf)
    update = m1o / (jnp.sqrt(m2o) + eps) + wd * pf
    w_norm = jnp.sqrt(jnp.sum(jnp.square(pf)))
    u_norm = jnp.sqrt(jnp.sum(jnp.square(update)))
    ratio = jnp.where(w_norm > 0, jnp.where(u_norm > 0, w_norm / u_norm, 1.0),
                      1.0)
    po = pf - _lr(ins) * ratio * update
    return {"ParamOut": [_like(po, p)], "Moment1Out": [_like(m1o, m1)],
            "Moment2Out": [_like(m2o, m2)]}


# ---- proximal optimizers (reference proximal_gd_op.h,
# proximal_adagrad_op.h): l1/l2-regularized proximal steps ------------

def _prox(prox_param, lr, l1, l2):
    return (jnp.sign(prox_param) *
            jnp.maximum(jnp.abs(prox_param) - lr * l1, 0.0) /
            (1.0 + lr * l2))


@register_op("proximal_gd")
def _proximal_gd(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    lr = _lr(ins)
    l1, l2 = attrs.get("l1", 0.0), attrs.get("l2", 0.0)
    pf, gf = _f32(p, g)
    return {"ParamOut": [_like(_prox(pf - lr * gf, lr, l1, l2), p)]}


@register_op("proximal_adagrad")
def _proximal_adagrad(ctx, ins, attrs):
    """Per-element adagrad step inside the prox, but the l1/l2
    shrinkage uses the SCALAR learning rate like the reference."""
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    lr = _lr(ins)
    l1, l2 = attrs.get("l1", 0.0), attrs.get("l2", 0.0)
    pf, gf, mf = _f32(p, g, m)
    mo = mf + jnp.square(gf)
    return {"ParamOut": [_like(_prox(pf - lr * gf / jnp.sqrt(mo + 1e-12),
                                     lr, l1, l2), p)],
            "MomentOut": [_like(mo, m)]}


# ---------------------------------------------------------------------------
# Static inference rules: every optimizer update op's outputs mirror
# the state inputs they update (ParamOut ≡ Param, MomentOut ≡ Moment,
# ...), which is exactly what the verifier needs to prove parameter
# shapes survive the update sweep.
# ---------------------------------------------------------------------------
from ..analysis.infer import passthrough  # noqa: E402
from ..core.registry import register_infer  # noqa: E402

_OPT_SLOT_MAPS = {
    "sgd": {"ParamOut": "Param"},
    "momentum": {"ParamOut": "Param", "VelocityOut": "Velocity"},
    "adam": {"ParamOut": "Param", "Moment1Out": "Moment1",
             "Moment2Out": "Moment2"},
    "adamax": {"ParamOut": "Param", "MomentOut": "Moment",
               "InfNormOut": "InfNorm"},
    "adagrad": {"ParamOut": "Param", "MomentOut": "Moment"},
    "decayed_adagrad": {"ParamOut": "Param", "MomentOut": "Moment"},
    "adadelta": {"ParamOut": "Param",
                 "AvgSquaredGradOut": "AvgSquaredGrad",
                 "AvgSquaredUpdateOut": "AvgSquaredUpdate"},
    "rmsprop": {"ParamOut": "Param", "MeanSquareOut": "MeanSquare",
                "MomentOut": "Moment", "MeanGradOut": "MeanGrad"},
    "ftrl": {"ParamOut": "Param",
             "SquaredAccumOut": "SquaredAccumulator",
             "LinearAccumOut": "LinearAccumulator"},
    "lamb": {"ParamOut": "Param", "Moment1Out": "Moment1",
             "Moment2Out": "Moment2"},
    "proximal_gd": {"ParamOut": "Param"},
    "proximal_adagrad": {"ParamOut": "Param", "MomentOut": "Moment"},
}

for _t, _m in _OPT_SLOT_MAPS.items():
    register_infer(_t)(passthrough(_m))
