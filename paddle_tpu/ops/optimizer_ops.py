"""Optimizer update op lowering rules.

Capability parity with paddle/fluid/operators/{sgd,momentum,adam,adagrad,
adamax,adadelta,decayed_adagrad,rmsprop,ftrl}_op.cc. Each op consumes
Param/Grad/accumulator state and emits the functionally-updated tensors;
because they lower inside the same jitted program as forward+backward,
XLA fuses the whole optimizer sweep into the train step (no per-op
kernel launches, donated buffers update in place in HBM).
"""
import jax.numpy as jnp
from jax import lax

from ..core.registry import register_op


def _lr(ins):
    return ins["LearningRate"][0].reshape(())


@register_op("sgd")
def _sgd(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    return {"ParamOut": [p - _lr(ins) * g.astype(p.dtype)]}


@register_op("momentum")
def _momentum(ctx, ins, attrs):
    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    mu = attrs.get("mu", 0.9)
    lr = _lr(ins)
    v_out = mu * v + g
    if attrs.get("use_nesterov", False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": [p_out], "VelocityOut": [v_out]}


@register_op("adam")
def _adam(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1p = ins["Beta1Pow"][0].reshape(())
    b2p = ins["Beta2Pow"][0].reshape(())
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr = _lr(ins) * jnp.sqrt(1 - b2p) / (1 - b1p)
    m1o = b1 * m1 + (1 - b1) * g
    m2o = b2 * m2 + (1 - b2) * jnp.square(g)
    po = p - lr * m1o / (jnp.sqrt(m2o) + eps)
    return {"ParamOut": [po], "Moment1Out": [m1o], "Moment2Out": [m2o]}


@register_op("adamax")
def _adamax(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    m, inf = ins["Moment"][0], ins["InfNorm"][0]
    b1p = ins["Beta1Pow"][0].reshape(())
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    mo = b1 * m + (1 - b1) * g
    info = jnp.maximum(b2 * inf, jnp.abs(g))
    po = p - (_lr(ins) / (1 - b1p)) * (mo / (info + eps))
    return {"ParamOut": [po], "MomentOut": [mo], "InfNormOut": [info]}


@register_op("adagrad")
def _adagrad(ctx, ins, attrs):
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    eps = attrs.get("epsilon", 1e-6)
    mo = m + jnp.square(g)
    po = p - _lr(ins) * g / (jnp.sqrt(mo) + eps)
    return {"ParamOut": [po], "MomentOut": [mo]}


@register_op("decayed_adagrad")
def _decayed_adagrad(ctx, ins, attrs):
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    mo = decay * m + (1 - decay) * jnp.square(g)
    po = p - _lr(ins) * g / (jnp.sqrt(mo) + eps)
    return {"ParamOut": [po], "MomentOut": [mo]}


@register_op("adadelta")
def _adadelta(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    avg_sq_g, avg_sq_u = ins["AvgSquaredGrad"][0], ins["AvgSquaredUpdate"][0]
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    asg = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((avg_sq_u + eps) / (asg + eps)) * g
    asu = rho * avg_sq_u + (1 - rho) * jnp.square(update)
    return {"ParamOut": [p + update], "AvgSquaredGradOut": [asg],
            "AvgSquaredUpdateOut": [asu]}


@register_op("rmsprop")
def _rmsprop(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    ms, mom = ins["MeanSquare"][0], ins["Moment"][0]
    rho = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    mu = attrs.get("momentum", 0.0)
    lr = _lr(ins)
    if attrs.get("centered", False):
        mg = ins["MeanGrad"][0]
        mgo = rho * mg + (1 - rho) * g
        mso = rho * ms + (1 - rho) * jnp.square(g)
        momo = mu * mom + lr * g / jnp.sqrt(mso - jnp.square(mgo) + eps)
        return {"ParamOut": [p - momo], "MeanSquareOut": [mso],
                "MomentOut": [momo], "MeanGradOut": [mgo]}
    mso = rho * ms + (1 - rho) * jnp.square(g)
    momo = mu * mom + lr * g / jnp.sqrt(mso + eps)
    return {"ParamOut": [p - momo], "MeanSquareOut": [mso],
            "MomentOut": [momo]}


@register_op("ftrl")
def _ftrl(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    sq, lin = ins["SquaredAccumulator"][0], ins["LinearAccumulator"][0]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    power = attrs.get("lr_power", -0.5)
    lr = _lr(ins)
    new_sq = sq + jnp.square(g)
    if power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -power) - jnp.power(sq, -power)) / lr
    new_lin = lin + g - sigma * p
    x = l1 * jnp.sign(new_lin) - new_lin
    if power == -0.5:
        y = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        y = jnp.power(new_sq, -power) / lr + 2 * l2
    po = jnp.where(jnp.abs(new_lin) > l1, x / y, 0.0)
    return {"ParamOut": [po], "SquaredAccumOut": [new_sq],
            "LinearAccumOut": [new_lin]}


@register_op("lamb")
def _lamb(ctx, ins, attrs):
    """LAMB (layer-adaptive Adam) — needed for large-batch TPU training;
    not in the reference op set but part of its capability envelope via
    contrib optimizers."""
    p, g = ins["Param"][0], ins["Grad"][0]
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    m1o = b1 * m1 + (1 - b1) * g
    m2o = b2 * m2 + (1 - b2) * jnp.square(g)
    update = m1o / (jnp.sqrt(m2o) + eps) + wd * p
    w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    u_norm = jnp.sqrt(jnp.sum(jnp.square(update)))
    ratio = jnp.where(w_norm > 0, jnp.where(u_norm > 0, w_norm / u_norm, 1.0),
                      1.0)
    po = p - _lr(ins) * ratio * update
    return {"ParamOut": [po], "Moment1Out": [m1o], "Moment2Out": [m2o]}


# ---- proximal optimizers (reference proximal_gd_op.h,
# proximal_adagrad_op.h): l1/l2-regularized proximal steps ------------

def _prox(prox_param, lr, l1, l2):
    return (jnp.sign(prox_param) *
            jnp.maximum(jnp.abs(prox_param) - lr * l1, 0.0) /
            (1.0 + lr * l2))


@register_op("proximal_gd")
def _proximal_gd(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    lr = _lr(ins)
    l1, l2 = attrs.get("l1", 0.0), attrs.get("l2", 0.0)
    return {"ParamOut": [_prox(p - lr * g, lr, l1, l2)]}


@register_op("proximal_adagrad")
def _proximal_adagrad(ctx, ins, attrs):
    """Per-element adagrad step inside the prox, but the l1/l2
    shrinkage uses the SCALAR learning rate like the reference."""
    p, g, m = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    lr = _lr(ins)
    l1, l2 = attrs.get("l1", 0.0), attrs.get("l2", 0.0)
    mo = m + jnp.square(g)
    return {"ParamOut": [_prox(p - lr * g / jnp.sqrt(mo + 1e-12),
                               lr, l1, l2)],
            "MomentOut": [mo]}
