"""In-graph evaluation ops: chunk_eval (sequence labeling P/R/F1) and
detection_map (VOC mAP).

Capability parity with reference paddle/fluid/operators/chunk_eval_op.h
and detection_map_op.h. The reference walks LoD sequences on the host;
here both are fixed-shape XLA computations — chunk segmentation is a
masked scan over padded tags, mAP is a per-class sort + matching scan —
so evaluation can run fused with the forward pass.
"""
import jax
import jax.numpy as jnp

from ..core.registry import canonical_int, register_op

NEG_INF = -1e30

_SCHEMES = {
    # num_tag_types, tag_begin, tag_inside, tag_end, tag_single
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _chunk_flags(labels, num_chunk_types, scheme):
    """Begin/end flags per position (reference chunk_eval_op.h
    ChunkBegin/ChunkEnd). labels [T] with out-of-sequence positions
    already set to the 'other' type. Returns (begin [T], end [T],
    type [T])."""
    ntag, t_begin, t_inside, t_end, t_single = _SCHEMES[scheme]
    other = num_chunk_types
    tag = labels % ntag
    typ = labels // ntag
    prev_tag = jnp.concatenate([jnp.array([-1], tag.dtype), tag[:-1]])
    prev_typ = jnp.concatenate([jnp.array([other], typ.dtype), typ[:-1]])
    next_tag = jnp.concatenate([tag[1:], jnp.array([-1], tag.dtype)])
    next_typ = jnp.concatenate([typ[1:], jnp.array([other], typ.dtype)])

    def begin(ptag, ptyp, ctag, ctyp):
        out = jnp.where(ptyp == other, ctyp != other,
                jnp.where(ctyp == other, False,
                jnp.where(ctyp != ptyp, True,
                jnp.where(ctag == t_begin, True,
                jnp.where(ctag == t_inside,
                          (ptag == t_end) | (ptag == t_single),
                jnp.where(ctag == t_end,
                          (ptag == t_end) | (ptag == t_single),
                jnp.where(ctag == t_single, True, False)))))))
        return out

    def end(ctag, ctyp, ntag_, ntyp):
        out = jnp.where(ctyp == other, False,
                jnp.where(ntyp == other, True,
                jnp.where(ntyp != ctyp, True,
                jnp.where(ctag == t_begin,
                          (ntag_ == t_begin) | (ntag_ == t_single),
                jnp.where(ctag == t_inside,
                          (ntag_ == t_begin) | (ntag_ == t_single),
                jnp.where((ctag == t_end) | (ctag == t_single),
                          True, False))))))
        return out

    return (begin(prev_tag, prev_typ, tag, typ),
            end(tag, typ, next_tag, next_typ), typ)


@register_op("chunk_eval", seq_aware=True)
def _chunk_eval(ctx, ins, attrs):
    """Inference/Label: lod_level-1 int sequences of chunk tags.
    Outputs the reference's six: Precision, Recall, F1-Score,
    NumInferChunks, NumLabelChunks, NumCorrectChunks."""
    inf = ins["Inference"][0]
    lab = ins["Label"][0]
    scheme = attrs.get("chunk_scheme", "IOB")
    nct = int(attrs["num_chunk_types"])
    excluded = [int(e) for e in attrs.get("excluded_chunk_types") or []]
    ntag = _SCHEMES[scheme][0]
    other_tag = nct * ntag   # maps to type == other

    inf_data, lengths = inf.data, inf.lengths
    lab_data = lab.data
    if inf_data.ndim == 3:
        inf_data = inf_data[..., 0]
    if lab_data.ndim == 3:
        lab_data = lab_data[..., 0]
    t = inf_data.shape[1]

    def one(iseq, lseq, n):
        mask = jnp.arange(t) < n
        iseq = jnp.where(mask, iseq, other_tag).astype(jnp.int32)
        lseq = jnp.where(mask, lseq, other_tag).astype(jnp.int32)
        ib, ie, ityp = _chunk_flags(iseq, nct, scheme)
        lb, le, ltyp = _chunk_flags(lseq, nct, scheme)
        inc_i = ib
        inc_l = lb
        for e in excluded:
            inc_i = inc_i & (ityp != e)
            inc_l = inc_l & (ltyp != e)

        def step(carry, x):
            in_match, correct = carry
            ib_, ie_, it_, lb_, le_, lt_, ok = x
            starts = ib_ & lb_ & (it_ == lt_) & ok
            # a mismatched boundary or type kills any active match
            in_match = jnp.where(ib_ != lb_, False, in_match)
            in_match = jnp.where(starts, True, in_match)
            both_end = ie_ & le_
            correct = correct + (in_match & both_end)
            in_match = jnp.where(ie_ | le_, False, in_match)
            return (in_match, correct), None

        ok_i = inc_i  # exclusion applies to match starts too
        (_, correct), _ = jax.lax.scan(
            step, (False, jnp.asarray(0, jnp.int32)),
            (ib, ie, ityp, lb, le, ltyp, ok_i))
        return inc_i.sum(), inc_l.sum(), correct

    ni, nl, nc = jax.vmap(one)(inf_data, lab_data, lengths)
    num_i = ni.sum().astype(canonical_int())
    num_l = nl.sum().astype(canonical_int())
    num_c = nc.sum().astype(canonical_int())
    p = jnp.where(num_i > 0, num_c / jnp.maximum(num_i, 1), 0.0)
    r = jnp.where(num_l > 0, num_c / jnp.maximum(num_l, 1), 0.0)
    f1 = jnp.where(num_c > 0, 2 * p * r / jnp.maximum(p + r, 1e-12), 0.0)
    return {"Precision": [p.astype(jnp.float32)],
            "Recall": [r.astype(jnp.float32)],
            "F1-Score": [f1.astype(jnp.float32)],
            "NumInferChunks": [num_i],
            "NumLabelChunks": [num_l],
            "NumCorrectChunks": [num_c]}


@register_op("detection_map", seq_aware=True)
def _detection_map(ctx, ins, attrs):
    """VOC mAP over the minibatch (reference detection_map_op.h).
    DetectRes: dense [B, K, 6] rows [label, score, x1, y1, x2, y2]
    (label -1 pads — the multiclass_nms output). Label: lod_level-1 gt
    per image, rows [label, x1, y1, x2, y2] or — matching the reference
    detection_map_op.h GetBoxes 6-wide layout — [label, is_difficult,
    x1, y1, x2, y2]. Greedy per-(image, class) matching in score order,
    then per-class AP (integral or 11point) averaged over classes with
    gt.
    """
    from .detection import _iou_matrix
    det = ins["DetectRes"][0]
    gt = ins["Label"][0]
    class_num = int(attrs["class_num"])
    overlap = float(attrs.get("overlap_threshold", 0.3))
    ap_version = attrs.get("ap_version", "integral")
    evaluate_difficult = bool(attrs.get("evaluate_difficult", True))
    background = int(attrs.get("background_label", 0))

    if hasattr(det, "data"):
        det = det.data
    gt_data, gt_lens = gt.data, gt.lengths
    b, k, _ = det.shape
    g = gt_data.shape[1]
    has_diff = gt_data.shape[-1] >= 6
    gt_label = gt_data[..., 0].astype(jnp.int32)
    if has_diff:
        difficult = gt_data[..., 1] > 0
        gt_boxes = gt_data[..., 2:6]
    else:
        difficult = jnp.zeros(gt_data.shape[:2], bool)
        gt_boxes = gt_data[..., 1:5]
    gt_valid = jnp.arange(g)[None, :] < gt_lens[:, None]
    # difficult gts stay matchable but are IGNORED (neither TP nor FP,
    # and excluded from the gt count) when evaluate_difficult is off —
    # the reference/VOC protocol
    gt_counted = gt_valid & (difficult == False) if not evaluate_difficult \
        else gt_valid  # noqa: E712

    det_label = det[..., 0].astype(jnp.int32)
    det_score = det[..., 1]
    det_boxes = det[..., 2:6]
    det_valid = det_label >= 0

    def match_image(dl, ds, db, gl, gb, gv, gdiff):
        """VOC matching in score order: each detection pairs with its
        single max-IoU same-class gt; TP if above threshold and
        unclaimed, FP if claimed or below threshold, ignored if the gt
        is difficult and difficult evaluation is off."""
        order = jnp.argsort(-ds)

        def step(used, i):
            di = order[i]
            iou = _iou_matrix(db[di][None], gb)[0]          # [G]
            same = gv & (gl == dl[di])
            best = jnp.argmax(jnp.where(same, iou, -1.0))
            best_iou = jnp.where(same[best], iou[best], -1.0)
            over = (best_iou >= overlap) & det_valid_row[di]
            hit = over & ~used[best]
            ign = over & (gdiff[best] if not evaluate_difficult
                          else False)
            used = used.at[best].set(used[best] | over)
            return used, (di, hit & ~ign, ign)

        det_valid_row = dl >= 0
        used, (dis, hits, igns) = jax.lax.scan(
            step, jnp.zeros((g,), bool), jnp.arange(k))
        tp = jnp.zeros((k,), bool).at[dis].set(hits)
        ignored = jnp.zeros((k,), bool).at[dis].set(igns)
        return tp, ignored

    tps, ignored = jax.vmap(match_image)(
        det_label, det_score, det_boxes, gt_label, gt_boxes, gt_valid,
        difficult)                                           # [B, K]

    flat_label = det_label.reshape(-1)
    flat_score = det_score.reshape(-1)
    flat_tp = tps.reshape(-1)
    flat_valid = det_valid.reshape(-1) & ~ignored.reshape(-1)

    def class_ap(c):
        mask = flat_valid & (flat_label == c)
        n_gt = (gt_counted & (gt_label == c)).sum()
        s = jnp.where(mask, flat_score, NEG_INF)
        order = jnp.argsort(-s)
        tp = (flat_tp & mask)[order].astype(jnp.float32)
        valid = mask[order].astype(jnp.float32)
        fp = valid - tp
        tp_cum = jnp.cumsum(tp)
        fp_cum = jnp.cumsum(fp)
        recall = tp_cum / jnp.maximum(n_gt, 1)
        precision = tp_cum / jnp.maximum(tp_cum + fp_cum, 1e-12)
        if ap_version == "11point":
            pts = jnp.linspace(0.0, 1.0, 11)
            pmax = jax.vmap(
                lambda t: jnp.max(jnp.where(recall >= t, precision, 0.0))
            )(pts)
            ap = pmax.mean()
        else:
            prev_recall = jnp.concatenate(
                [jnp.zeros((1,)), recall[:-1]])
            ap = jnp.sum((recall - prev_recall) * precision * valid)
        return jnp.where(n_gt > 0, ap, 0.0), (n_gt > 0)

    classes = jnp.arange(class_num)
    aps, present = jax.vmap(class_ap)(classes)
    if background >= 0:
        bg = jnp.arange(class_num) == background
        present = present & ~bg
        aps = jnp.where(bg, 0.0, aps)
    n_present = jnp.maximum(present.sum(), 1)
    m_ap = (aps.sum() / n_present).astype(jnp.float32)
    # per-detection match rows + per-class gt counts let the evaluator
    # accumulate TP/FP across batches and compute the DATASET mAP like
    # the reference's AccumTruePos/AccumFalsePos state path
    match_info = jnp.stack(
        [flat_label.astype(jnp.float32), flat_score,
         flat_tp.astype(jnp.float32), flat_valid.astype(jnp.float32)],
        axis=-1)
    gt_count = jax.vmap(
        lambda c: (gt_counted & (gt_label == c)).sum())(classes)
    return {"MAP": [m_ap], "MatchInfo": [match_info],
            "GTCount": [gt_count.astype(jnp.int32)]}
